"""Threaded vs process lanes on the same RAW stream (ISSUE 15).

LANES_r07 measured the threaded multi-lane ingest win at ~2.2x and
called it the floor: lanes overlap only where stages release the GIL.
Process lanes put each lane's drain+apply on a true core. This bench
measures exactly that delta, route_micro-style — the SAME raw watch
lines pushed into both engines' ingest queues, interleaved best-of
windows (single windows on shared hosts swing far more than the delta
under test), with per-window distinct keys so every event is a fresh
row:

- threaded arm: a ``drain_shards=L`` engine (in-process FakeKube; the
  ingest path never touches the wire — pods land on an unmanaged node,
  so no transitions fire and the measurement is the drain tier alone);
- process arm: a ``--lane-procs`` engine against an HTTP mock master
  (the children need real clients); same lines through the parent
  router -> shm ring -> child parse+apply; completion read from the
  shared StatusBank (refreshed every 50ms — up to one refresh of
  measurement noise per window, disclosed).

Both engines stay alive across windows (spawn cost excluded — it is
startup, not throughput). Prints ONE JSON line with the measured
events/s per arm and the ratio; ``--check`` exits nonzero if the
process arm does not reach PROC_OVER_THREADED_MIN x the threaded arm on
hosts with >= 2 effective cores, and emits an honest skip verdict (the
TPU-leg pattern) on starved hosts where the ratio measures the
scheduler instead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: the acceptance ratio at >= 2 effective cores (ISSUE 15); override per
#: deployment with KWOK_PROC_MICRO_MIN_RATIO
PROC_OVER_THREADED_MIN = float(
    os.environ.get("KWOK_PROC_MICRO_MIN_RATIO", "2.0")
)


def effective_cores() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def _pod_line(window: int, i: int) -> bytes:
    return json.dumps({
        "type": "ADDED",
        "object": {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pm{window}-{i}", "namespace": "default",
                         "resourceVersion": str(1000 + window * 1000000 + i)},
            "spec": {"nodeName": "pm-node-absent",
                     "containers": [{"name": "c", "image": "x"}]},
            "status": {"phase": "Pending"},
        },
    }, separators=(",", ":")).encode()


def run(events: int, lanes: int, windows: int, timeout: float) -> dict:
    from kwok_tpu.edge.httpclient import HttpKubeClient
    from kwok_tpu.edge.mockserver import FakeKube, HttpFakeApiserver
    from kwok_tpu.engine import ClusterEngine, EngineConfig
    from kwok_tpu.engine import shm as shm_mod

    cores = effective_cores()
    thr = ClusterEngine(FakeKube(), EngineConfig(
        manage_all_nodes=True, tick_interval=0.05, drain_shards=lanes,
        initial_capacity=max(4096, events * (windows + 1)),
    ))
    thr.start()
    srv = HttpFakeApiserver(store=FakeKube()).start()
    proc = ClusterEngine(
        HttpKubeClient(f"http://127.0.0.1:{srv.port}"),
        EngineConfig(
            manage_all_nodes=True, tick_interval=0.05, drain_shards=lanes,
            lane_procs=True,
            initial_capacity=max(4096, events * (windows + 1)),
        ),
    )
    proc.start()
    out: dict = {
        "metric": (
            f"multi-lane RAW ingest wall at {events} events x {lanes} "
            f"lanes (best of {windows} interleaved windows; threaded = "
            "shared-GIL ShardLanes, process = spawned lane workers over "
            "the shm ring)"
        ),
        "events": events, "lanes": lanes, "windows": windows,
        "effective_cores": cores,
    }
    try:
        deadline = time.time() + 120
        while time.time() < deadline and not proc.ready:
            time.sleep(0.2)
        if not proc.ready:
            raise RuntimeError("process-lane engine never became ready")

        def thr_count() -> int:
            return sum(
                len(lane.engine.pods.pool) for lane in thr._lanes.lanes
            )

        def proc_count() -> int:
            return int(
                proc._proc.bank.rows[:, shm_mod.BANK_PODS].sum()
            )

        def window(eng, count_fn, base: int, w: int) -> float:
            lines = [_pod_line(w, i) for i in range(events)]
            target = base + events
            t0 = time.perf_counter()
            put = eng._q.put
            t = time.monotonic()
            for ln in lines:
                put(("pods", "RAW", ln, t))
            end = time.time() + timeout
            while count_fn() < target:
                if time.time() > end:
                    raise RuntimeError(
                        f"window {w}: {count_fn()}/{target} applied"
                    )
                time.sleep(0.002)
            return time.perf_counter() - t0

        thr_best = proc_best = float("inf")
        for w in range(windows):
            thr_best = min(
                thr_best, window(thr, thr_count, thr_count(), 2 * w)
            )
            proc_best = min(
                proc_best, window(proc, proc_count, proc_count(), 2 * w + 1)
            )
        thr_eps = events / thr_best
        proc_eps = events / proc_best
        out.update({
            "threaded_events_per_s": round(thr_eps, 1),
            "proc_events_per_s": round(proc_eps, 1),
            "threaded_us_per_event": round(1e6 * thr_best / events, 3),
            "proc_us_per_event": round(1e6 * proc_best / events, 3),
            "proc_over_threaded": round(proc_eps / max(thr_eps, 1e-9), 3),
            "status_refresh_noise_s": 0.05,
        })
    finally:
        try:
            thr.stop()
        finally:
            try:
                proc.stop()
            finally:
                srv.stop()
    return out


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--events", type=int, default=20000)
    p.add_argument("--lanes", type=int, default=0,
                   help="lane count (0 = effective cores, capped at 8)")
    p.add_argument("--windows", type=int, default=3)
    p.add_argument("--timeout", type=float, default=120.0,
                   help="per-window apply deadline (s)")
    p.add_argument("--check", action="store_true",
                   help="regression gate: small workload; on >= 2 "
                   "effective cores exit 1 unless process lanes reach "
                   f"{PROC_OVER_THREADED_MIN}x threaded; on starved "
                   "hosts record an honest skip verdict instead")
    args = p.parse_args()
    cores = effective_cores()
    if args.lanes <= 0:
        args.lanes = max(2, min(8, cores))
    if args.check:
        args.events = min(args.events, 8000)
        args.windows = min(args.windows, 2)
    out = run(args.events, args.lanes, args.windows, args.timeout)
    gate = None
    if cores < 2:
        # a 1-core host cannot overlap lanes at all: the ratio measures
        # the scheduler, not the architecture — record the measurement
        # with an explicit skip verdict (the BENCH_TPU skip-rider
        # pattern) instead of gating on it
        gate = {
            "skipped": (
                f"host exposes {cores} effective core(s); the "
                f">= {PROC_OVER_THREADED_MIN}x process-vs-threaded gate "
                "needs >= 2 — re-run on a multi-core host"
            )
        }
    else:
        gate = {
            "required_ratio": PROC_OVER_THREADED_MIN,
            "ok": out.get("proc_over_threaded", 0.0)
            >= PROC_OVER_THREADED_MIN,
        }
    out["gate"] = gate
    print(json.dumps(out))
    if args.check and gate.get("ok") is False:
        print(
            "proc_micro: process lanes "
            f"{out.get('proc_over_threaded')}x threaded < required "
            f"{PROC_OVER_THREADED_MIN}x on {cores} cores",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
