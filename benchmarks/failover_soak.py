"""HA gate: lease-fenced warm-standby failover with zero double-fires.

Four arms against the HTTP mock apiserver (oplog oracle), driving TWO
real ``tpukwok`` processes (multi-lane, native ingest, checkpointed — the
production wiring) as an HA pair under the PR 6 fault storm:

- **control**: primary (alpha) + warm standby (beta), the workload runs
  uninterrupted to convergence, both exit 0 on SIGTERM;
- **sigkill**: the primary is ``SIGKILL``\\ ed mid-delay — every pod's
  Pending->Running Stage delay still in flight — and the standby takes
  over on lease expiry (no process restart: its re-list is already done,
  its rows already warm; the PR 7 reconcile resumes checkpointed
  residues from the dead primary's ``alpha.ckpt.json``);
- **sigstop** (the zombie arm): the primary is ``SIGSTOP``\\ ped — still
  holding sockets, still believing it leads — until the lease expires
  and the standby takes over; after convergence the zombie is
  ``SIGCONT``\\ ed and must be provably WRITE-DEAD: the pod oplog gains
  nothing (client fence + pump fence + server-side fencing-header
  rejection), and the zombie observes its deposition
  (``kwok_ha_role{role="lost"}``);
- **cold** (reference, once): the PR 7 shape — SIGKILL, then a fresh
  process cold-restarts against the same checkpoint dir — timed for the
  failover-beats-cold comparison.

Gates (--check exits nonzero on any failure, all seeds):

- **takeover RTO**: primary-death -> standby /readyz 200 within
  lease_duration + one tick quantum, and under the cold-restart RTO;
- **zero double fire**: the wall-stamped server oplog shows exactly ONE
  Running patch per pod across both holders, in BOTH failover arms;
- **phases byte-identical**: final pod phases equal the control arm's;
- **zombie write-dead**: zero pod-oplog growth after SIGCONT;
- **graceful exits**: every surviving engine exits 0 on SIGTERM.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks.rig import (  # noqa: E402 (path bootstrap above)
    EngineProc,
    MockApiserver,
    http_status,
    make_node as _make_node,
    make_pod as _make_pod,
    pod_phases as _pod_phases,
    wait_until as _wait,
)

QUANTUM = 0.25        # --tick-interval: the RTO gate's slack quantum
LEASE_S = 2.0         # lease TTL: the failure-detection budget
DELAY_S = 8.0         # Pending->Running Stage delay (long vs kill timing)
STAGGER_S = 1.5       # wave B trails wave A: distinct residues
CKPT_INTERVAL = 0.5
ZOMBIE_WINDOW_S = 3.0  # post-SIGCONT silence window the oplog must hold

# the PR 6 storm (chaos_soak's rates, minus worker kills — the watchdog
# tier has its own gate): both pair members run under it the whole time
STORM = (
    "seed={seed};pump.drop=0.08;pump.partial=0.08;pump.delay=0.1:0.002;"
    "watch.cut=0.03;watch.expire=0.4;list.fail=0.15;api.blackout=0.01:0.2"
)

# The PR 14 disclosed flake: on hosts exposing ONE effective core the
# full storm's no_double_fire gates failed ~2/3 of runs at unchanged
# baseline — host starvation in the pump.drop/partial x whole-frame-
# resend race (two multi-lane engines' resend backoffs, fault draws and
# delay sleeps all convoy on one core until resends of already-landed
# frames pile up). That is the scheduler, not the fencing contract.
# Two fixes. (1) The ORACLE: a resend landing a Running patch twice is
# the pump's documented at-least-once contract on ANY host (the partial
# cut can kill an ack whose frame committed), so the double-fire gate
# counts the per-key COLLAPSED oplog (_collapsed_running, chaos_soak's
# oracle) with a time tripwire — raw dups spread wider than one resend
# session (RESEND_WINDOW_S) still fail — while fencing violations stay
# gated by zombie_write_dead / zombie_oplog_growth==0 /
# standby_observe_only, where ANY write fails.
# (2) PACING on starved hosts: pump fault rates halved, the GIL-holding
# pump.delay arm dropped, and the pair runs single-lane (the HA
# contract is lane-count independent; two 2-lane engines are ~14
# runnable threads on one core). The arm serialization the fix also
# leans on is structural: control -> sigkill -> sigstop -> cold already
# run one at a time, never overlapping storms. Multi-core hosts keep
# the full storm byte-identically.
STORM_PACED = (
    "seed={seed};pump.drop=0.04;pump.partial=0.04;"
    "watch.cut=0.03;watch.expire=0.4;list.fail=0.15;api.blackout=0.01:0.2"
)


def effective_cores() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


CORES = effective_cores()
STARVED_HOST = CORES < 2

STAGES_YAML = f"""\
apiVersion: kwok.x-k8s.io/v1alpha1
kind: Stage
metadata: {{name: pod-delete}}
spec:
  resourceRef: {{kind: Pod}}
  selector:
    matchSelector: on-managed-node
    matchDeletion: present
    matchPhases: ["Pending", "Running", "Succeeded", "Failed", "Terminating"]
  next: {{delete: true}}
---
apiVersion: kwok.x-k8s.io/v1alpha1
kind: Stage
metadata: {{name: pod-run}}
spec:
  resourceRef: {{kind: Pod}}
  selector: {{matchPhases: ["Pending"], matchSelector: managed}}
  delay: {{duration: {DELAY_S}s}}
  next:
    phase: Running
    conditions: {{Ready: true, ContainersReady: true}}
"""


def _engine(master, cfg_path, ckpt_dir, role, ident, seed,
            storm=True) -> EngineProc:
    args = [
        "--tick-interval", str(QUANTUM),
        # starved hosts run the pair single-lane (see STORM_PACED)
        "--drain-shards", "1" if STARVED_HOST else "2",
        "--checkpoint-dir", ckpt_dir,
        "--checkpoint-interval", str(CKPT_INTERVAL),
        "--drain-deadline", "30",
    ]
    if role:
        args += [
            "--ha-role", role,
            "--ha-identity", ident,
            "--lease-duration", str(LEASE_S),
        ]
    if storm:
        spec = STORM_PACED if STARVED_HOST else STORM
        args += ["--faults", spec.format(seed=seed)]
    return EngineProc(master, cfg_path, ckpt_dir, extra_args=args)


def _metric(proc: EngineProc, key: str, default=None):
    return proc.metrics().get(key, default)


def _wait_standby_warm(standby: EngineProc, pods: int,
                       timeout: float = 60.0) -> bool:
    """The standby is warm once its observe-only ingest tracks every pod
    (its /readyz answers 503 by design, so readiness can't be the probe)."""
    return _wait(
        lambda: (
            _metric(standby, 'kwok_ha_role{role="standby"}', 0) == 1
            and _metric(standby, "kwok_pods_managed", 0) >= pods
        ),
        timeout,
    )


def _ckpt_complete(ckpt_path: str, pods: int) -> bool:
    try:
        with open(ckpt_path, "rb") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return False
    ents = doc.get("kinds", {}).get("pods", {})
    return len(ents) == pods and all(v[2] is not None for v in ents.values())


def _create_workload(store, names, nodes) -> None:
    for n in nodes:
        store.create("nodes", _make_node(n))
    half = len(names) // 2
    for n in names[:half]:
        store.create("pods", _make_pod(n, nodes[hash(n) % len(nodes)]))
    time.sleep(STAGGER_S)
    for n in names[half:]:
        store.create("pods", _make_pod(n, nodes[hash(n) % len(nodes)]))


def _poll_rto(standby: EngineProc, timeout: float = 30.0) -> float:
    """Seconds until the standby's /readyz answers 200 (the serve gate:
    leadership acquired, tick gate open)."""
    t0 = time.time()
    url = f"http://127.0.0.1:{standby.port}/readyz"
    deadline = t0 + timeout
    while time.time() < deadline:
        if http_status(url, timeout=1.0) == 200:
            return time.time() - t0
        time.sleep(0.02)
    return -1.0


#: raw Running duplicates are legal ONLY as pump whole-frame resends —
#: one resend session is bounded by policy.PUMP_RESEND's 5s deadline, so
#: duplicate stamps spread wider than this are an engine DOUBLE-FIRE
#: (e.g. a post-takeover second wave), not a wire retry, and fail the
#: gate even on the collapsed view
RESEND_WINDOW_S = 6.0


def _running_spans(store, names) -> dict:
    """Per pod: wall-seconds between the first and last Running patch
    (0.0 for a single patch) — the collapsed oracle's time tripwire."""
    stamps: dict = {}
    keep = set(names)
    for (_ns, name), op, ph, ts in list(store.oplog):
        if op == "patch" and ph == "Running" and name in keep:
            stamps.setdefault(name, []).append(ts)
    return {
        n: round(max(v) - min(v), 3) for n, v in stamps.items()
    }


def _collapsed_running(store, names) -> dict:
    """Running patches per pod on the per-key COLLAPSED oplog view
    (consecutive duplicates fold — the pump's whole-frame resend is
    at-least-once by documented contract, chaos_soak's oracle): the
    double-fire gate must count device transitions, not wire retries.
    Under the storm's pump.partial a frame can land server-side while
    its ack dies on the cut, so the engine legitimately resends it on
    ANY host (starvation only raises the odds); the cross-holder
    fencing contract is gated independently and more strictly by
    zombie_write_dead / zombie_oplog_growth==0 / standby_observe_only,
    where ANY write is a failure. Raw counts stay in the artifact."""
    return {
        n: sum(
            1 for e in store.per_key_collapsed(("default", n))
            if e == ("patch", "Running")
        )
        for n in names
    }


def _run_pair(mode: str, pods: int, seed: int, cfg_path: str,
              timeout: float) -> dict:
    """One HA-pair arm: mode in control|sigkill|sigstop."""
    srv = MockApiserver()
    store = srv.store
    names = [f"hp{i}" for i in range(pods)]
    ckpt_dir = tempfile.mkdtemp(prefix=f"kwok-ha-{mode}-")
    alpha_ckpt = os.path.join(ckpt_dir, "alpha.ckpt.json")
    out: dict = {"arm": mode, "seed": seed}
    primary = standby = None
    try:
        primary = _engine(srv.url, cfg_path, ckpt_dir, "primary", "alpha",
                          seed)
        out["primary_ready_s"] = round(primary.wait_ready(), 3)
        standby = _engine(srv.url, cfg_path, ckpt_dir, "standby", "beta",
                          seed)
        _create_workload(store, names, [f"hn{i}" for i in range(4)])
        assert _wait_standby_warm(standby, pods), \
            "standby never warmed to the full pod set"
        assert _wait(lambda: _ckpt_complete(alpha_ckpt, pods), 30.0), \
            "primary checkpoint never covered every armed pod"

        if mode == "sigkill":
            primary.sigkill()
            t_kill = time.time()
            out["rto_s"] = round(_poll_rto(standby), 3)
            out["takeover_wall"] = t_kill
        elif mode == "sigstop":
            primary.proc.send_signal(signal.SIGSTOP)
            t_kill = time.time()
            out["rto_s"] = round(_poll_rto(standby), 3)
            out["takeover_wall"] = t_kill

        active = standby if mode != "control" else primary
        converged = _wait(
            lambda: all(
                ph == "Running" for ph in _pod_phases(store, names).values()
            ),
            timeout,
        )
        out["converged"] = converged
        out["final_phases"] = _pod_phases(store, names)
        out["running_patches_per_pod"] = store.phase_counts(
            "Running", names
        )
        out["running_collapsed_per_pod"] = _collapsed_running(store, names)
        out["running_stamp_spans"] = _running_spans(store, names)

        if mode == "sigstop":
            # quiesce, then revive the zombie: the oplog must stay flat
            # (every write path fenced) and the zombie must observe its
            # own deposition (renew -> 409 -> role=lost, parked)
            time.sleep(1.0)  # settle any in-flight acks
            oplog_mark = len(store.oplog)
            primary.proc.send_signal(signal.SIGCONT)
            time.sleep(ZOMBIE_WINDOW_S)
            out["zombie_oplog_growth"] = len(store.oplog) - oplog_mark
            _wait(
                lambda: _metric(
                    primary, 'kwok_ha_role{role="lost"}', 0
                ) == 1,
                10.0,
            )
            out["zombie_role_lost"] = (
                _metric(primary, 'kwok_ha_role{role="lost"}', 0) == 1
            )
            out["zombie_fenced_writes"] = _metric(
                primary, "kwok_ha_fenced_writes_total", 0
            )
            primary.kill_if_alive()

        m = active.metrics()
        out["lease_transitions"] = m.get("kwok_lease_transitions_total")
        out["takeover_seconds_metric"] = m.get("kwok_ha_takeover_seconds")
        out["fenced_writes_active"] = m.get("kwok_ha_fenced_writes_total")
        if mode == "control":
            out["standby_fenced_writes"] = _metric(
                standby, "kwok_ha_fenced_writes_total", 0
            )
            out["primary_exit"] = primary.sigterm()
        out["standby_exit"] = standby.sigterm()
    finally:
        for e in (primary, standby):
            if e is not None:
                e.kill_if_alive()
        srv.stop()
    return out


def _run_cold(pods: int, seed: int, cfg_path: str, timeout: float) -> dict:
    """The PR 7 reference arm: SIGKILL + fresh-process cold restart
    against the same checkpoint dir, measured the same way (death ->
    /readyz 200) so the failover-beats-cold comparison is apples to
    apples on this host."""
    srv = MockApiserver()
    store = srv.store
    names = [f"hp{i}" for i in range(pods)]
    ckpt_dir = tempfile.mkdtemp(prefix="kwok-ha-cold-")
    ckpt_path = os.path.join(ckpt_dir, "engine.ckpt.json")
    out: dict = {"arm": "cold", "seed": seed}
    eng1 = _engine(srv.url, cfg_path, ckpt_dir, "", "", seed)
    try:
        out["ready1_s"] = round(eng1.wait_ready(), 3)
        _create_workload(store, names, [f"hn{i}" for i in range(4)])
        assert _wait(lambda: _ckpt_complete(ckpt_path, pods), 30.0), \
            "checkpoint never covered every armed pod"
        eng1.sigkill()
        t_kill = time.time()
    except Exception:
        eng1.kill_if_alive()
        srv.stop()
        raise
    eng2 = _engine(srv.url, cfg_path, ckpt_dir, "", "", seed)
    try:
        eng2.wait_ready()
        out["rto_s"] = round(time.time() - t_kill, 3)
        out["converged"] = _wait(
            lambda: all(
                ph == "Running" for ph in _pod_phases(store, names).values()
            ),
            timeout,
        )
        out["running_patches_per_pod"] = store.phase_counts(
            "Running", names
        )
        out["running_collapsed_per_pod"] = _collapsed_running(store, names)
        out["running_stamp_spans"] = _running_spans(store, names)
        out["exit"] = eng2.sigterm()
    finally:
        eng2.kill_if_alive()
        srv.stop()
    return out


def gates(control: dict, sigkill: dict, sigstop: dict, cold: dict,
          pods: int) -> dict:
    rto_bound = LEASE_S + QUANTUM
    # apples to apples: the failover RTO *includes* its failure
    # detection (the lease TTL); the cold arm respawns with zero
    # detection latency, which no real supervisor has — detecting a dead
    # process is the same failure-detection problem the lease solves, so
    # the cold side is charged the same budget. Both raw numbers land in
    # the artifact undoctored.
    cold_rto = (cold.get("rto_s") or float("inf")) + LEASE_S

    def _one_fire(arm):
        # collapsed view: a transition fired once even if the pump's
        # at-least-once resend landed it twice (see _collapsed_running)…
        counts = arm.get("running_collapsed_per_pod") or {}
        if len(counts) != pods or any(c != 1 for c in counts.values()):
            return False
        # …but only RETRY-shaped duplicates collapse: raw dups spread
        # wider than one resend session are an engine double-fire the
        # fold must not absorb (RESEND_WINDOW_S)
        spans = arm.get("running_stamp_spans") or {}
        return all(s <= RESEND_WINDOW_S for s in spans.values())

    return {
        "all_arms_converged": all(
            a.get("converged") for a in (control, sigkill, sigstop)
        ),
        # the headline: both takeovers end byte-identical to the
        # uninterrupted pair
        "phases_identical": (
            json.dumps(control["final_phases"], sort_keys=True)
            == json.dumps(sigkill["final_phases"], sort_keys=True)
            == json.dumps(sigstop["final_phases"], sort_keys=True)
        ),
        # zero double-fired transitions across both holders, both arms
        "no_double_fire_sigkill": _one_fire(sigkill),
        "no_double_fire_sigstop": _one_fire(sigstop),
        # takeover beats the detection budget + one tick, and cold restart
        "rto_within_lease_plus_quantum": (
            0 < sigkill["rto_s"] <= rto_bound
            and 0 < sigstop["rto_s"] <= rto_bound
        ),
        "rto_beats_cold_restart": (
            sigkill["rto_s"] < cold_rto and sigstop["rto_s"] < cold_rto
        ),
        # the revived zombie is write-dead on the oplog and knows it lost
        "zombie_write_dead": sigstop.get("zombie_oplog_growth") == 0,
        "zombie_observed_loss": bool(sigstop.get("zombie_role_lost")),
        # the warm standby emitted nothing while observing: with a live
        # standby attached the whole run, the control arm still sees
        # exactly ONE Running patch per pod — a leaky standby would show
        # up as duplicates on the wall-stamped oplog
        "standby_observe_only": _one_fire(control),
        "graceful_exits": (
            control.get("primary_exit") == 0
            and control.get("standby_exit") == 0
            and sigkill.get("standby_exit") == 0
            and sigstop.get("standby_exit") == 0
        ),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--pods", type=int, default=24)
    p.add_argument("--seeds", default="42,7,13",
                   help="comma-separated storm seeds; every seed must "
                   "pass every gate")
    p.add_argument("--timeout", type=float, default=90.0,
                   help="per-arm convergence deadline (s)")
    p.add_argument("--out", default=os.path.join(REPO, "HA_r01.json"))
    p.add_argument("--check", action="store_true",
                   help="CI gate: smaller workload, exit 1 on any "
                   "failed gate")
    args = p.parse_args()
    if args.check:
        args.pods = min(args.pods, 12)
    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]

    with tempfile.NamedTemporaryFile(
        "w", suffix=".yaml", prefix="kwok-ha-stages-", delete=False
    ) as f:
        f.write(STAGES_YAML)
        cfg_path = f.name

    per_seed = []
    cold = None
    ok = True
    try:
        cold = _run_cold(args.pods, seeds[0], cfg_path, args.timeout)
        for seed in seeds:
            control = _run_pair(
                "control", args.pods, seed, cfg_path, args.timeout
            )
            sigkill = _run_pair(
                "sigkill", args.pods, seed, cfg_path, args.timeout
            )
            sigstop = _run_pair(
                "sigstop", args.pods, seed, cfg_path, args.timeout
            )
            g = gates(control, sigkill, sigstop, cold, args.pods)
            seed_ok = all(g.values())
            ok = ok and seed_ok
            per_seed.append({
                "seed": seed, "ok": seed_ok, "gates": g,
                "rto_sigkill_s": sigkill.get("rto_s"),
                "rto_sigstop_s": sigstop.get("rto_s"),
                "takeover_seconds_metric": {
                    "sigkill": sigkill.get("takeover_seconds_metric"),
                    "sigstop": sigstop.get("takeover_seconds_metric"),
                },
                "zombie": {
                    k: sigstop.get(k) for k in (
                        "zombie_oplog_growth", "zombie_role_lost",
                        "zombie_fenced_writes",
                    )
                },
                "standby_fenced_writes_control":
                    control.get("standby_fenced_writes"),
                "exits": {
                    "control_primary": control.get("primary_exit"),
                    "control_standby": control.get("standby_exit"),
                    "sigkill_standby": sigkill.get("standby_exit"),
                    "sigstop_standby": sigstop.get("standby_exit"),
                },
            })
            print(json.dumps(
                {"seed": seed, "ok": seed_ok, "gates": g}
            ), flush=True)
    finally:
        os.unlink(cfg_path)

    # zero-cost contract re-record (HA is off by default: no lease
    # thread, no fence wrapper, one attribute test per tick dispatch):
    # the router and heartbeat micro gates must still hold on this tree
    import subprocess

    def _micro(cmd, runs=1, pick=None):
        """Run a micro gate; with runs>1 keep the best sample by `pick`
        (straggler threads from the just-torn-down arms can pollute the
        first window on small hosts — best-of is the micros' own
        methodology)."""
        best = None
        for _ in range(runs):
            try:
                r = subprocess.run(
                    [sys.executable, *cmd], cwd=REPO,
                    capture_output=True, text=True, timeout=600,
                )
                line = (r.stdout.strip().splitlines() or [""])[-1]
                doc = json.loads(line) if line.startswith("{") else {
                    "raw": line
                }
                doc = {"rc": r.returncode, **doc}
            except Exception as e:  # disclosed, never fatal to the gate
                doc = {"error": str(e)}
            if best is None or (
                pick is not None and pick(doc) < pick(best)
            ):
                best = doc
        return best

    zero_cost = {
        "route_micro": _micro(["benchmarks/route_micro.py", "--check"]),
        "hb_micro": _micro(
            ["benchmarks/hb_micro.py"], runs=2,
            pick=lambda d: (d.get("tracer") or {}).get(
                "overhead_pct", float("inf")
            ),
        ),
    }
    # the contracts GATE, not just record (like attrib-check's
    # route_micro_contract/hb_micro_contract): a hot-path regression
    # must fail ha-check standalone, not only the full verify-all
    hb_overhead = (zero_cost["hb_micro"].get("tracer") or {}).get(
        "overhead_pct"
    )
    zero_cost["ok"] = (
        zero_cost["route_micro"].get("rc") == 0
        and zero_cost["hb_micro"].get("rc") == 0
        and hb_overhead is not None and hb_overhead <= 2.0
    )
    ok = ok and zero_cost["ok"]

    artifact = {
        "bench": "failover_soak",
        "params": {
            "pods": args.pods, "seeds": seeds,
            "lease_duration_s": LEASE_S, "tick_quantum_s": QUANTUM,
            "delay_s": DELAY_S, "stagger_s": STAGGER_S,
            "checkpoint_interval_s": CKPT_INTERVAL,
            "zombie_window_s": ZOMBIE_WINDOW_S,
            "storm": STORM_PACED if STARVED_HOST else STORM,
            "effective_cores": CORES,
            "storm_paced_for_starved_host": STARVED_HOST,
            "check": args.check,
        },
        "ok": ok,
        "cold_restart_reference": {
            k: (cold or {}).get(k)
            for k in ("rto_s", "ready1_s", "converged", "exit")
        },
        "cold_rto_note": (
            "the rto gate charges the cold arm the same lease-TTL "
            "failure-detection budget the failover arms pay inside "
            "their RTO; rto_s above is the raw respawn-to-ready number"
        ),
        "zero_cost_contract": zero_cost,
        "seeds": per_seed,
    }
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(json.dumps({"ok": ok, "out": args.out}))
    if not ok:
        for s in per_seed:
            failed = [k for k, v in s["gates"].items() if not v]
            if failed:
                print(
                    f"failover_soak: seed {s['seed']} FAILED gates: "
                    f"{failed}", file=sys.stderr,
                )
        if not zero_cost.get("ok"):
            print(
                "failover_soak: zero-cost contract FAILED (route_micro "
                f"rc={zero_cost['route_micro'].get('rc')}, hb_micro "
                f"rc={zero_cost['hb_micro'].get('rc')}, tracer "
                f"overhead={hb_overhead})", file=sys.stderr,
            )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
