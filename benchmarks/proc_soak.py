"""proc-check: the process-lane correctness gate (ISSUE 15).

Three arms against the HTTP mock apiserver with the server-side oplog
oracle, all driving the REAL ``tpukwok`` process (the production wiring
— parent router + spawned lane worker processes over shared memory):

- **ordering**: the same create -> converge -> delete workload through
  the single-lane engine (the reference arm) and the 2-lane process
  engine. Gates: final phases byte-identical, per-key collapsed patch
  order identical for EVERY key, exactly one Running patch per pod in
  both arms (process fan-out introduces no duplicates).
- **chaos**: the process engine converges the creates workload while
  the fault plane's ``worker.kill=kwok-lane*`` delivers rotating REAL
  SIGKILLs to the lane processes. Gates: converged, one Running patch
  per pod, respawns recorded (``kwok_lane_proc_restarts_total`` > 0),
  /readyz not degraded at the end, graceful exit 0.
- **restart**: pods armed with an 8s Pending->Running Stage delay and
  per-lane checkpoints on a short cadence; ONE lane process is
  SIGKILLed mid-delay (the process-lane twin of restart_soak's
  whole-engine kill). Gates: zero double-fires on the wall-stamped
  oplog, every pod converges, the killed lane's delays resume within
  one tick quantum of their checkpointed residues (common respawn
  anchor factored out with the median, surviving-lane pods excluded —
  they never stopped), respawn accounted.

Every arm ends with the shm-hygiene gate: no ``kwoktpu-*`` segment left
in /dev/shm after engine exit — the zero-leak half of the zero-cost
contract (the threaded-path half rides lane-check's route_micro gate).

ISSUE 17 adds the **chaos+drift storm** (artifact ``PROC_r02.json``):
an in-process 2-lane engine (in-process so the rig can quiesce both
sides of the fault boundary mid-run) runs the creates workload under
the FULL combined storm — hostile wire + clock.jump + pump.* + the
whole shm/IPC tier (shm.torn, shm.stall, shm.desc_drop,
shm.desc_garble) + rotating worker.kill SIGKILLs + lane.sigstop — with
the shard-scoped anti-entropy auditor on. After every spec'd kind has
provably fired (union of the parent plane's tally and the merged child
exposition), the rig quiesces all planes (FAULTSOFF broadcast), waits
for convergence, then mutates the apiserver SILENTLY (a status rewind
on a lane-0-owned pod, a delete on a lane-1-owned pod — no events, no
rv bumps) and gates: final phases byte-identical to an unfaulted
control arm, per-key collapsed patch order preserved, both mutations
detected (merged ``kwok_drift_detected_total{reason=stale-row|
ghost-row}``) and repaired, engine not degraded at exit, /dev/shm
clean.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import statistics
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks.rig import (  # noqa: E402 (path bootstrap above)
    EngineProc,
    MockApiserver,
    make_node as _make_node,
    make_pod as _make_pod,
    pod_phases as _pod_phases,
    wait_until as _wait,
)

QUANTUM = 0.25
DELAY_S = 8.0
CKPT_INTERVAL = 0.5
LANES = 2

STAGES_FAST = """\
apiVersion: kwok.x-k8s.io/v1alpha1
kind: Stage
metadata: {name: pod-delete}
spec:
  resourceRef: {kind: Pod}
  selector:
    matchSelector: on-managed-node
    matchDeletion: present
    matchPhases: ["Pending", "Running", "Succeeded", "Failed", "Terminating"]
  next: {delete: true}
---
apiVersion: kwok.x-k8s.io/v1alpha1
kind: Stage
metadata: {name: pod-run}
spec:
  resourceRef: {kind: Pod}
  selector: {matchPhases: ["Pending"], matchSelector: managed}
  next:
    phase: Running
    conditions: {Ready: true, ContainersReady: true}
"""

STAGES_DELAY = STAGES_FAST.replace(
    "  next:\n    phase: Running",
    f"  delay: {{duration: {DELAY_S}s}}\n  next:\n    phase: Running",
)


def _shm_leftovers() -> list:
    try:
        return [f for f in os.listdir("/dev/shm") if f.startswith("kwoktpu")]
    except OSError:
        return []


def _engine(master: str, cfg_path: str, workdir: str, *, procs: bool,
            extra=()) -> EngineProc:
    args = ["--tick-interval", str(QUANTUM), "--drain-deadline", "30"]
    if procs:
        args += ["--drain-shards", str(LANES), "--lane-procs", "true"]
    else:
        args += ["--drain-shards", "1"]
    return EngineProc(master, cfg_path, workdir, extra_args=args + list(extra))


def _lane_pids(engine_pid: int) -> list[int]:
    """The engine's spawned lane processes (cmdline carries
    multiprocessing's spawn bootstrap; the resource tracker does not)."""
    out = []
    try:
        kids = os.popen(f"ps -o pid= --ppid {engine_pid}").read().split()
    except OSError:
        return out
    for pid in kids:
        try:
            with open(f"/proc/{int(pid)}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ")
        except (OSError, ValueError):
            continue
        if b"spawn_main" in cmd and b"resource_tracker" not in cmd:
            out.append(int(pid))
    return sorted(out)


def _converge_and_delete(store, names, timeout: float) -> dict:
    out = {}
    out["converged"] = _wait(
        lambda: all(
            ph == "Running" for ph in _pod_phases(store, names).values()
        ),
        timeout,
    )
    out["final_phases"] = _pod_phases(store, names)
    # delete wave: half the keys get a deletionTimestamp -> the engine
    # must emit its DELETE after that key's Running patch (per-key order)
    doomed = names[::2]
    for n in doomed:
        store.patch_meta(
            "pods", "default", n,
            {"metadata": {"deletionTimestamp": "2026-01-01T00:00:00Z"}},
        )
    out["deleted_ok"] = _wait(
        lambda: all(
            store.get("pods", "default", n) is None for n in doomed
        ),
        timeout,
    )
    out["doomed"] = doomed
    out["per_key"] = {
        n: store.per_key_collapsed(("default", n)) for n in names
    }
    out["running_patches_per_pod"] = store.phase_counts("Running", names)
    return out


def _run_ordering_arm(pods, cfg_path, timeout, *, procs: bool) -> dict:
    srv = MockApiserver()
    store = srv.store
    names = [f"pp{i}" for i in range(pods)]
    workdir = tempfile.mkdtemp(prefix="kwok-proc-ord-")
    eng = _engine(srv.url, cfg_path, workdir, procs=procs)
    out = {"arm": f"ordering-{'proc' if procs else 'single'}"}
    try:
        out["ready_s"] = round(eng.wait_ready(), 3)
        for i in range(4):
            store.create("nodes", _make_node(f"pn{i}"))
        for n in names:
            store.create("pods", _make_pod(n, f"pn{hash(n) % 4}"))
        out.update(_converge_and_delete(store, names, timeout))
        out["sigterm_exit"] = eng.sigterm()
    finally:
        eng.kill_if_alive()
        srv.stop()
    out["shm_leftover"] = _shm_leftovers()
    return out


def _run_chaos_arm(pods, cfg_path, timeout) -> dict:
    """Rotating lane-process SIGKILLs, bench-driven so the rotation is
    paced by OBSERVED respawns (a period-driven storm on a starved host
    would out-kill the respawn latency and measure the scheduler, not
    the contract — the ha-check lesson). A parent-side wire storm
    (watch.cut) runs concurrently: the one fault plane composes with
    process lanes. The plane's own worker.kill -> SIGKILL delivery is
    pinned by tests/test_proclanes.py."""
    srv = MockApiserver()
    store = srv.store
    names = [f"cp{i}" for i in range(pods)]
    workdir = tempfile.mkdtemp(prefix="kwok-proc-chaos-")
    ckpt = tempfile.mkdtemp(prefix="kwok-proc-chaos-ckpt-")
    eng = _engine(
        srv.url, cfg_path, workdir, procs=True,
        extra=[
            "--faults", "seed=42;watch.cut=0.02",
            "--checkpoint-dir", ckpt,
            "--checkpoint-interval", str(CKPT_INTERVAL),
        ],
    )
    out = {"arm": "chaos"}
    try:
        out["ready_s"] = round(eng.wait_ready(), 3)
        for i in range(4):
            store.create("nodes", _make_node(f"cn{i}"))
        for n in names:
            store.create("pods", _make_pod(n, f"cn{hash(n) % 4}"))

        def restarts(shard: int) -> float:
            return eng.metrics().get(
                f'kwok_lane_proc_restarts_total{{shard="{shard}"}}', 0
            )

        # rotate: SIGKILL each lane in turn, mid-ingest, waiting for the
        # supervisor's respawn before the next round
        kills = 0
        for shard in range(LANES):
            lanes = _lane_pids(eng.proc.pid)
            if len(lanes) <= shard:
                break
            before = restarts(shard)
            os.kill(lanes[shard], signal.SIGKILL)
            kills += 1
            if not _wait(lambda: restarts(shard) > before, 120):
                break
        out["kills_delivered"] = kills
        out["converged"] = _wait(
            lambda: all(
                ph == "Running"
                for ph in _pod_phases(store, names).values()
            ),
            timeout * 2,
        )
        out["final_phases"] = _pod_phases(store, names)
        out["running_patches_per_pod"] = store.phase_counts("Running", names)
        m = eng.metrics()
        out["lane_restarts"] = {
            s: m.get(f'kwok_lane_proc_restarts_total{{shard="{s}"}}', 0)
            for s in range(LANES)
        }
        out["wire_faults_injected"] = m.get(
            'kwok_faults_injected_total{kind="watch.cut"}', 0
        )
        out["readyz_degraded"] = any(
            v for k, v in m.items() if k.startswith("kwok_degraded{")
        )
        out["sigterm_exit"] = eng.sigterm(timeout=60)
    finally:
        eng.kill_if_alive()
        srv.stop()
    out["shm_leftover"] = _shm_leftovers()
    return out


def _run_restart_arm(pods, cfg_path, timeout) -> dict:
    from kwok_tpu.engine.rowpool import shard_of

    srv = MockApiserver()
    store = srv.store
    names = [f"dp{i}" for i in range(pods)]
    workdir = tempfile.mkdtemp(prefix="kwok-proc-restart-")
    ckpt_dir = tempfile.mkdtemp(prefix="kwok-proc-restart-ckpt-")
    eng = _engine(
        srv.url, cfg_path, workdir, procs=True,
        extra=["--checkpoint-dir", ckpt_dir,
               "--checkpoint-interval", str(CKPT_INTERVAL)],
    )
    out = {"arm": "restart"}
    try:
        out["ready_s"] = round(eng.wait_ready(), 3)
        store.create("nodes", _make_node("dn0"))
        for n in names[: pods // 2]:
            store.create("pods", _make_pod(n, "dn0"))
        time.sleep(1.5)  # second wave: distinct checkpoint residues
        for n in names[pods // 2:]:
            store.create("pods", _make_pod(n, "dn0"))

        victim_lane = 0
        victim_pods = [
            n for n in names if shard_of(("default", n), LANES) == victim_lane
        ]
        ckpt_path = os.path.join(ckpt_dir, f"lane{victim_lane}.ckpt.json")

        def ckpt_armed():
            try:
                with open(ckpt_path, "rb") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                return False
            ents = doc.get("kinds", {}).get("pods", {})
            return len(ents) == len(victim_pods) and all(
                v[2] is not None for v in ents.values()
            )

        if not _wait(ckpt_armed, 30.0):
            raise RuntimeError(
                "lane checkpoint never covered every armed pod"
            )
        time.sleep(CKPT_INTERVAL + 0.2)  # gate against FRESH residues
        with open(ckpt_path, "rb") as f:
            doc = json.load(f)
        residues = {
            ks.split("/", 1)[1]: v[2]
            for ks, v in doc["kinds"]["pods"].items()
        }
        lanes = _lane_pids(eng.proc.pid)
        out["lane_pids"] = lanes
        if len(lanes) < LANES:
            raise RuntimeError(f"expected {LANES} lane processes: {lanes}")
        # mid-delay, no warning: the process-lane twin of restart_soak.
        # _lane_pids sorts by pid = spawn order, so lanes[0] is lane 0.
        os.kill(lanes[victim_lane], signal.SIGKILL)
        out["killed_at_wall"] = time.time()
        out["converged"] = _wait(
            lambda: all(
                ph == "Running"
                for ph in _pod_phases(store, names).values()
            ),
            timeout + DELAY_S + 60,
        )
        out["final_phases"] = _pod_phases(store, names)
        out["running_patches_per_pod"] = store.phase_counts("Running", names)
        m = eng.metrics()
        out["lane_restarts"] = m.get(
            f'kwok_lane_proc_restarts_total{{shard="{victim_lane}"}}', 0
        )
        # residue-resume oracle over the KILLED lane's pods only (the
        # surviving lane never stopped — its fires carry no respawn
        # anchor and would poison the median)
        fires = store.phase_stamps("Running")
        devs = {
            n: fires[n] - residues[n]
            for n in victim_pods
            if n in fires and residues.get(n) is not None
        }
        anchor = statistics.median(devs.values()) if devs else 0.0
        out["resume_pods_measured"] = len(devs)
        out["resume_deviation_s"] = {
            n: round(d - anchor, 4) for n, d in devs.items()
        }
        out["resume_max_abs_dev_s"] = round(
            max((abs(d - anchor) for d in devs.values()), default=999.0), 4
        )
        out["victim_pods"] = len(victim_pods)
        out["sigterm_exit"] = eng.sigterm(timeout=60)
    finally:
        eng.kill_if_alive()
        srv.stop()
    out["shm_leftover"] = _shm_leftovers()
    return out


# --------------------------------------------- chaos+drift storm (ISSUE 17)

AUDIT_S = 0.5
#: parent-side kinds the storm must prove fired (the plane's own tally)
STORM_PARENT_KINDS = (
    "wire.garble", "wire.truncate", "wire.dup", "wire.stale",
    "watch.cut", "clock.jump",
    "shm.desc_drop", "shm.desc_garble",
    "worker.kill", "lane.sigstop",
)
#: child-side kinds, visible only through the merged exposition
STORM_CHILD_KINDS = (
    "pump.drop", "pump.partial", "pump.delay",
    "clock.jump", "shm.torn", "shm.stall",
)
#: Rates are sized to the arm's traffic volume so every kind provably
#: fires inside the hold window (the workload drip-feeds creates to keep
#: the wire/ring/pump sites drawing); kill/sigstop periods are sized so
#: each lane's respawn charges stay WELL under the watchdog's restart
#: budget (5/30s per lane name) — rotation spreads one event per period
#: across the lanes, so per-lane charge rate is (kills + stall-kills)/2:
#: ~2 per 30s here. Overrunning the budget marks the lane permanently
#: dead (its shard goes dark and /readyz stays degraded), which is the
#: product contract under a genuine crash-loop but a bench bug here.
STORM_SPEC = (
    "seed=1337;"
    "wire.garble=0.08;wire.truncate=0.04;wire.dup=0.12;wire.stale=0.08;"
    "watch.cut=0.05;clock.jump=0.1:0.05;"
    "pump.drop=0.1;pump.partial=0.2;pump.delay=0.15:0.02;"
    "shm.torn=0.3;shm.stall=0.03:2.0;"
    "shm.desc_drop=0.08;shm.desc_garble=0.12;"
    "worker.kill=kwok-lane*:12.0;lane.sigstop=kwok-lane*:18.0"
)


def _fault_counts(text: str) -> dict:
    """kind -> count from a merged process exposition."""
    import re

    out = {}
    for kind, v in re.findall(
        r'kwok_faults_injected_total\{kind="([^"]+)"\} (\d+(?:\.\d+)?)',
        text,
    ):
        out[kind] = out.get(kind, 0) + float(v)
    return out


def _drift_counts(text: str) -> dict:
    """reason -> detected count from a merged engine exposition."""
    import re

    out = {}
    for labels, v in re.findall(
        r'kwok_drift_detected_total\{([^}]*)\} (\d+(?:\.\d+)?)', text
    ):
        m = re.search(r'reason="([^"]+)"', labels)
        if m:
            out[m.group(1)] = out.get(m.group(1), 0) + float(v)
    return out


def _metric_total(text: str, family: str) -> float:
    import re

    return sum(
        float(v) for v in re.findall(
            rf'^{family}(?:\{{[^}}]*\}})? (\d+(?:\.\d+)?)$', text,
            re.MULTILINE,
        )
    )


def _inproc_engine(url: str, *, faults: str = "", audit: float = 0.0,
                   ckpt: "str | None" = None):
    from kwok_tpu.edge.httpclient import HttpKubeClient
    from kwok_tpu.engine import ClusterEngine, EngineConfig

    kw = {}
    if faults:
        kw["faults"] = faults
    if audit:
        kw["audit_interval"] = audit
    if ckpt:
        kw["checkpoint_dir"] = ckpt
        kw["checkpoint_interval"] = CKPT_INTERVAL
    eng = ClusterEngine(HttpKubeClient(url), EngineConfig(
        manage_all_nodes=True, tick_interval=0.05, drain_shards=LANES,
        lane_procs=True, initial_capacity=4096, **kw,
    ))
    eng.start()
    return eng


def _storm_workload(store, pods: int):
    """Creates in two waves (the second lands mid-storm, so ingest keeps
    feeding the wire/shm fault sites after the first wave converges)."""
    names = [f"st{i}" for i in range(pods)]
    for i in range(4):
        store.create("nodes", _make_node(f"stn{i}"))
    for n in names[: pods // 2]:
        store.create("pods", _make_pod(n, f"stn{hash(n) % 4}"))
    return names


def _run_storm_control_arm(pods, timeout) -> dict:
    """The unfaulted reference: same in-process 2-lane engine, same
    workload, auditor on, no faults — the byte-identity baseline."""
    srv = MockApiserver()
    store = srv.store
    out = {"arm": "storm-control"}
    eng = None
    try:
        eng = _inproc_engine(srv.url, audit=AUDIT_S)
        if not _wait(lambda: eng.ready, 120):
            raise RuntimeError("control engine never became ready")
        names = _storm_workload(store, pods)
        for n in names[pods // 2:]:
            store.create("pods", _make_pod(n, f"stn{hash(n) % 4}"))
        out["converged"] = _wait(
            lambda: all(
                ph == "Running" for ph in _pod_phases(store, names).values()
            ),
            timeout,
        )
        out["final_phases"] = _pod_phases(store, names)
        out["per_key"] = {
            n: store.per_key_collapsed(("default", n)) for n in names
        }
    finally:
        if eng is not None:
            eng.stop()
        srv.stop()
    out["shm_leftover"] = _shm_leftovers()
    return out


def _run_storm_arm(pods, timeout) -> dict:
    import kwok_tpu.engine.proclanes as proclanes_mod
    from kwok_tpu.engine.rowpool import shard_of

    from benchmarks.rig import silent_delete, silent_patch

    # shrink the stall clocks so lane.sigstop -> stall-kill and
    # shm.stall -> ring-stall-drop resolve in bench time, not minutes.
    # The module constants are patched for the parent (already
    # imported); the env vars cover the spawned children, which import
    # proclanes fresh. The stall clock must still clear the worst-case
    # HEALTHY beat gap: a respawned child stamps its beat once on
    # attach, then builds its engine before the status thread starts
    # beating — several seconds under storm load. A clock inside that
    # gap stall-kills healthy children in a loop and burns the lane's
    # restart budget on bench-inflicted kills (observed with 3s: both
    # lanes marked permanently dead mid-storm).
    saved_env = {
        k: os.environ.get(k)
        for k in ("KWOK_TPU_LANE_STALL_S", "KWOK_TPU_RING_STALL_S")
    }
    os.environ["KWOK_TPU_LANE_STALL_S"] = "10"
    os.environ["KWOK_TPU_RING_STALL_S"] = "1.5"
    saved_const = (proclanes_mod._STALL_NS, proclanes_mod._RING_STALL_S)
    proclanes_mod._STALL_NS = int(10e9)
    proclanes_mod._RING_STALL_S = 1.5

    srv = MockApiserver()
    store = srv.store
    ckpt = tempfile.mkdtemp(prefix="kwok-proc-storm-ckpt-")
    out = {"arm": "storm"}
    eng = None
    try:
        eng = _inproc_engine(
            srv.url, faults=STORM_SPEC, audit=AUDIT_S, ckpt=ckpt,
        )
        if not _wait(lambda: eng.ready, 180):
            raise RuntimeError("storm engine never became ready")
        names = _storm_workload(store, pods)
        plane = eng._faults

        def kinds_covered() -> "tuple[dict, list]":
            seen = dict(plane.counts())
            for k, v in _fault_counts(eng.process_metrics_text()).items():
                seen[k] = max(seen.get(k, 0), v)
            missing = [
                k for k in set(STORM_PARENT_KINDS + STORM_CHILD_KINDS)
                if not seen.get(k)
            ]
            return seen, missing

        # the second wave DRIP-FEEDS through the hold window: the fault
        # sites only draw while traffic moves (watch events for the wire
        # tier, ring descriptors for the shm tier, lifecycle ticks for
        # clock.jump, patch sends for the pump tier), so a one-shot wave
        # that converges in seconds leaves the low-rate kinds with no
        # draws for the rest of the hold. The storm stays open until
        # every spec'd kind has provably fired (or the bound expires and
        # the gate reports exactly which kinds never did).
        time.sleep(3.0)
        second_wave = list(names[pods // 2:])
        # churn pods live OUTSIDE the oracle's name set: recycled
        # create/delete keeps every fault site drawing for as long as
        # the coverage poll needs, without perturbing the final-phase /
        # per-key byte-identity comparison (which only reads ``names``)
        churn = [f"stchurn{i}" for i in range(2)]
        churn_up: set = set()
        deadline = time.monotonic() + 75.0
        next_create = next_churn = 0.0
        while time.monotonic() < deadline:
            now = time.monotonic()
            if second_wave and now >= next_create:
                n = second_wave.pop(0)
                store.create("pods", _make_pod(n, f"stn{hash(n) % 4}"))
                next_create = now + 0.7
            if now >= next_churn:
                for c in churn:
                    if c in churn_up:
                        store.delete("pods", "default", c)
                        churn_up.discard(c)
                    else:
                        store.create(
                            "pods", _make_pod(c, f"stn{hash(c) % 4}")
                        )
                        churn_up.add(c)
                next_churn = now + 1.5
            _seen, missing = kinds_covered()
            if not missing and not second_wave:
                break
            time.sleep(0.25)
        for n in second_wave:  # bound expired mid-drip: finish the wave
            store.create("pods", _make_pod(n, f"stn{hash(n) % 4}"))
        for c in churn_up:     # retire the churn before the oracle phases
            store.delete("pods", "default", c)
        out["fault_counts"], out["kinds_never_fired"] = kinds_covered()
        out["fault_counts"] = {
            k: int(v) for k, v in sorted(out["fault_counts"].items())
        }

        # ---- quiesce BOTH sides of the process boundary
        plane.spec.rates.clear()
        plane.spec.kill_glob = ""
        plane.spec.sigstop_glob = ""
        eng._proc.quiesce_child_faults()

        out["converged"] = _wait(
            lambda: all(
                ph == "Running" for ph in _pod_phases(store, names).values()
            ),
            timeout * 2,
        )
        # let in-flight audit repairs settle: drift counters stable for
        # ~4 audit intervals before the silent-mutation baseline
        stable_since = time.monotonic()
        last = _drift_counts(eng.metrics_text())
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            cur = _drift_counts(eng.metrics_text())
            if cur != last:
                last, stable_since = cur, time.monotonic()
            elif time.monotonic() - stable_since > 4 * AUDIT_S:
                break
            time.sleep(0.25)
        out["storm_drift_repairs"] = last
        out["final_phases"] = _pod_phases(store, names)
        out["per_key"] = {
            n: store.per_key_collapsed(("default", n)) for n in names
        }
        # respawn quiet period: a respawn (the last sigstop's stall-kill
        # can land AFTER quiesce) triggers a full list+RESYNC, and the
        # wire-doubt timer defers integrity re-lists up to 5s — either
        # landing after the silent mutations would re-ingest the mutated
        # server state as row truth and blind the drift oracle. Wait for
        # the respawn counter to hold still past both windows.
        restarts = lambda: sum(l.restarts for l in eng._proc.lanes)  # noqa: E731
        quiet_since, seen_restarts = time.monotonic(), restarts()
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            cur = restarts()
            if cur != seen_restarts:
                seen_restarts, quiet_since = cur, time.monotonic()
            elif time.monotonic() - quiet_since > 6.0 and all(
                l.alive() for l in eng._proc.lanes
            ):
                break
            time.sleep(0.25)
        out["degraded_after_storm"] = eng.degraded
        out["degraded_reasons_after_storm"] = sorted(
            eng._degradation.reasons
        )
        out["lane_restarts"] = [l.restarts for l in eng._proc.lanes]
        out["lane_dead"] = [l.dead for l in eng._proc.lanes]

        # ---- post-convergence silent mutations, one per lane's shard
        lane0 = [n for n in names if shard_of(("default", n), LANES) == 0]
        lane1 = [n for n in names if shard_of(("default", n), LANES) == 1]
        rewind_victim, ghost_victim = lane0[0], lane1[0]
        base_drift = _drift_counts(eng.metrics_text())
        base_repaired = _metric_total(
            eng.metrics_text(), "kwok_drift_repaired_total"
        )

        def rewind(obj):
            obj.setdefault("status", {})["phase"] = "Pending"

        assert silent_patch(store, "pods", "default", rewind_victim, rewind)
        assert silent_delete(store, "pods", "default", ghost_victim)
        t_mut = time.monotonic()

        def mutations_detected() -> bool:
            d = _drift_counts(eng.metrics_text())
            return (
                d.get("stale-row", 0) > base_drift.get("stale-row", 0)
                and d.get("ghost-row", 0) > base_drift.get("ghost-row", 0)
            )

        out["drift_detected"] = _wait(mutations_detected, 30.0, every=0.1)
        out["detect_s"] = round(time.monotonic() - t_mut, 3)
        out["drift_counts_after_detect"] = _drift_counts(eng.metrics_text())

        def mutations_repaired() -> bool:
            phase = (
                (store.get("pods", "default", rewind_victim) or {})
                .get("status", {}).get("phase")
            )
            return phase == "Running" and _metric_total(
                eng.metrics_text(), "kwok_drift_repaired_total"
            ) >= base_repaired + 2
        out["drift_repaired"] = _wait(mutations_repaired, 30.0, every=0.1)
        out["repair_s"] = round(time.monotonic() - t_mut, 3)
        out["rewind_victim"], out["ghost_victim"] = rewind_victim, ghost_victim

        # observability riders: the new families moved under the storm
        m_text = eng.metrics_text()
        out["stall_kills"] = _metric_total(
            m_text, "kwok_lane_stall_kills_total"
        )
        out["desc_rejects"] = _metric_total(
            m_text, "kwok_shm_desc_rejects_total"
        )
        out["degraded_at_end"] = eng.degraded
        out["degraded_reasons_end"] = sorted(eng._degradation.reasons)
    finally:
        if eng is not None:
            eng.stop()
        srv.stop()
        proclanes_mod._STALL_NS, proclanes_mod._RING_STALL_S = saved_const
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    out["shm_leftover"] = _shm_leftovers()
    return out


def storm_gates(control, storm) -> dict:
    same_keys = set(control["per_key"]) == set(storm["per_key"])
    return {
        "storm_converged": bool(
            control["converged"] and storm["converged"]
        ),
        "storm_phases_match_control": (
            json.dumps(control["final_phases"], sort_keys=True)
            == json.dumps(storm["final_phases"], sort_keys=True)
        ),
        "storm_per_key_order_preserved": same_keys and all(
            control["per_key"][k] == storm["per_key"][k]
            for k in control["per_key"]
        ),
        "storm_every_kind_fired": not storm["kinds_never_fired"],
        "storm_sigstop_recovered_by_stall_kill": storm["stall_kills"] >= 1,
        "storm_garbled_descs_bounds_rejected": storm["desc_rejects"] >= 1,
        "storm_silent_mutations_detected": bool(storm["drift_detected"]),
        "storm_silent_mutations_repaired": bool(storm["drift_repaired"]),
        "storm_not_degraded_at_end": not storm["degraded_at_end"],
        "storm_no_leaked_shm": not (
            control["shm_leftover"] or storm["shm_leftover"]
        ),
    }


def gates(single, proc, chaos, restart, pods) -> dict:
    same_keys = set(single["per_key"]) == set(proc["per_key"])
    return {
        # ordering oracle: the process fan-out is invisible on the wire
        "ordering_converged": bool(
            single["converged"] and proc["converged"]
            and single["deleted_ok"] and proc["deleted_ok"]
        ),
        "phases_identical": (
            json.dumps(single["final_phases"], sort_keys=True)
            == json.dumps(proc["final_phases"], sort_keys=True)
        ),
        "per_key_order_identical": same_keys and all(
            single["per_key"][k] == proc["per_key"][k]
            for k in single["per_key"]
        ),
        "ordering_no_double_fire": all(
            c == 1 for c in proc["running_patches_per_pod"].values()
        ),
        # chaos: rotating REAL SIGKILLs, same convergence contract
        "chaos_converged": bool(chaos["converged"]),
        "chaos_no_double_fire": all(
            c == 1 for c in chaos["running_patches_per_pod"].values()
        ) and len(chaos["running_patches_per_pod"]) == pods,
        "chaos_respawns_recorded": (
            chaos["kills_delivered"] >= 2
            and sum(chaos["lane_restarts"].values()) >= 2
        ),
        "chaos_not_degraded": not chaos["readyz_degraded"],
        # restart: mid-delay SIGKILL of one lane PROCESS
        "restart_converged": bool(restart["converged"]),
        "restart_no_double_fire": all(
            c == 1 for c in restart["running_patches_per_pod"].values()
        ) and len(restart["running_patches_per_pod"]) == pods,
        "restart_delays_resumed_within_quantum": (
            restart["resume_pods_measured"] == restart["victim_pods"]
            and restart["resume_max_abs_dev_s"] <= QUANTUM
        ),
        "restart_respawned": restart["lane_restarts"] >= 1,
        "graceful_exit_zero": all(
            a["sigterm_exit"] == 0 for a in (single, proc, chaos, restart)
        ),
        # shm hygiene: nothing left mapped after ANY arm (incl. the
        # SIGKILL-respawn cycles)
        "no_leaked_shm": not any(
            a["shm_leftover"] for a in (single, proc, chaos, restart)
        ),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--pods", type=int, default=24)
    p.add_argument("--timeout", type=float, default=90.0)
    p.add_argument("--out", default=os.path.join(REPO, "PROC_r01.json"))
    p.add_argument("--out2", default=os.path.join(REPO, "PROC_r02.json"),
                   help="chaos+drift storm artifact (ISSUE 17)")
    p.add_argument("--check", action="store_true",
                   help="CI gate: smaller workload, exit 1 on any "
                   "failed gate")
    args = p.parse_args()
    if args.check:
        args.pods = min(args.pods, 16)

    def stages_file(content, tag):
        f = tempfile.NamedTemporaryFile(
            "w", suffix=".yaml", prefix=f"kwok-proc-{tag}-", delete=False
        )
        f.write(content)
        f.close()
        return f.name

    fast = stages_file(STAGES_FAST, "fast")
    delay = stages_file(STAGES_DELAY, "delay")
    try:
        single = _run_ordering_arm(
            args.pods, fast, args.timeout, procs=False
        )
        proc = _run_ordering_arm(args.pods, fast, args.timeout, procs=True)
        chaos = _run_chaos_arm(args.pods, fast, args.timeout)
        restart = _run_restart_arm(args.pods, delay, args.timeout)
        control = _run_storm_control_arm(args.pods, args.timeout)
        storm = _run_storm_arm(args.pods, args.timeout)
    finally:
        os.unlink(fast)
        os.unlink(delay)
    g = gates(single, proc, chaos, restart, args.pods)
    sg = storm_gates(control, storm)
    storm_ok = all(sg.values())
    storm_artifact = {
        "bench": "proc_soak.storm",
        "params": {"pods": args.pods, "lanes": LANES,
                   "audit_interval_s": AUDIT_S, "spec": STORM_SPEC,
                   "check": args.check},
        "gates": sg,
        "ok": storm_ok,
        "storm": {k: storm.get(k) for k in (
            "fault_counts", "kinds_never_fired", "storm_drift_repairs",
            "detect_s", "repair_s", "stall_kills", "desc_rejects",
            "rewind_victim", "ghost_victim", "degraded_after_storm",
            "degraded_at_end")},
    }
    with open(args.out2, "w", encoding="utf-8") as fh:
        json.dump(storm_artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")
    ok = all(g.values()) and storm_ok
    artifact = {
        "bench": "proc_soak",
        "params": {"pods": args.pods, "lanes": LANES,
                   "tick_quantum_s": QUANTUM, "delay_s": DELAY_S,
                   "checkpoint_interval_s": CKPT_INTERVAL,
                   "check": args.check},
        "gates": g,
        "ok": ok,
        "arms": {
            "ordering_single": {k: single.get(k) for k in
                                ("ready_s", "converged", "sigterm_exit")},
            "ordering_proc": {k: proc.get(k) for k in
                              ("ready_s", "converged", "sigterm_exit")},
            "chaos": {k: chaos.get(k) for k in (
                "ready_s", "converged", "kills_delivered", "lane_restarts",
                "wire_faults_injected", "readyz_degraded",
                "sigterm_exit")},
            "restart": {k: restart.get(k) for k in (
                "ready_s", "converged", "lane_restarts",
                "resume_max_abs_dev_s", "resume_pods_measured",
                "victim_pods", "sigterm_exit")},
        },
    }
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(json.dumps({
        "ok": ok, "gates": g, "storm_gates": sg,
        "out": args.out, "out2": args.out2,
    }))
    if not ok:
        failed = [k for k, v in g.items() if not v]
        failed += [k for k, v in sg.items() if not v]
        print(f"proc_soak: FAILED gates: {failed}", file=sys.stderr)
        if storm.get("kinds_never_fired"):
            print(
                "proc_soak: kinds never fired: "
                f"{storm['kinds_never_fired']}", file=sys.stderr,
            )
        if not g["per_key_order_identical"]:
            diffs = {
                k: (single["per_key"].get(k), proc["per_key"].get(k))
                for k in single["per_key"]
                if single["per_key"].get(k) != proc["per_key"].get(k)
            }
            print(f"proc_soak: per-key diffs: {diffs}", file=sys.stderr)
        if not g["restart_delays_resumed_within_quantum"]:
            print(
                "proc_soak: resume deviations: "
                f"{restart.get('resume_deviation_s')}", file=sys.stderr,
            )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
