"""proc-check: the process-lane correctness gate (ISSUE 15).

Three arms against the HTTP mock apiserver with the server-side oplog
oracle, all driving the REAL ``tpukwok`` process (the production wiring
— parent router + spawned lane worker processes over shared memory):

- **ordering**: the same create -> converge -> delete workload through
  the single-lane engine (the reference arm) and the 2-lane process
  engine. Gates: final phases byte-identical, per-key collapsed patch
  order identical for EVERY key, exactly one Running patch per pod in
  both arms (process fan-out introduces no duplicates).
- **chaos**: the process engine converges the creates workload while
  the fault plane's ``worker.kill=kwok-lane*`` delivers rotating REAL
  SIGKILLs to the lane processes. Gates: converged, one Running patch
  per pod, respawns recorded (``kwok_lane_proc_restarts_total`` > 0),
  /readyz not degraded at the end, graceful exit 0.
- **restart**: pods armed with an 8s Pending->Running Stage delay and
  per-lane checkpoints on a short cadence; ONE lane process is
  SIGKILLed mid-delay (the process-lane twin of restart_soak's
  whole-engine kill). Gates: zero double-fires on the wall-stamped
  oplog, every pod converges, the killed lane's delays resume within
  one tick quantum of their checkpointed residues (common respawn
  anchor factored out with the median, surviving-lane pods excluded —
  they never stopped), respawn accounted.

Every arm ends with the shm-hygiene gate: no ``kwoktpu-*`` segment left
in /dev/shm after engine exit — the zero-leak half of the zero-cost
contract (the threaded-path half rides lane-check's route_micro gate).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import statistics
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks.rig import (  # noqa: E402 (path bootstrap above)
    EngineProc,
    MockApiserver,
    make_node as _make_node,
    make_pod as _make_pod,
    pod_phases as _pod_phases,
    wait_until as _wait,
)

QUANTUM = 0.25
DELAY_S = 8.0
CKPT_INTERVAL = 0.5
LANES = 2

STAGES_FAST = """\
apiVersion: kwok.x-k8s.io/v1alpha1
kind: Stage
metadata: {name: pod-delete}
spec:
  resourceRef: {kind: Pod}
  selector:
    matchSelector: on-managed-node
    matchDeletion: present
    matchPhases: ["Pending", "Running", "Succeeded", "Failed", "Terminating"]
  next: {delete: true}
---
apiVersion: kwok.x-k8s.io/v1alpha1
kind: Stage
metadata: {name: pod-run}
spec:
  resourceRef: {kind: Pod}
  selector: {matchPhases: ["Pending"], matchSelector: managed}
  next:
    phase: Running
    conditions: {Ready: true, ContainersReady: true}
"""

STAGES_DELAY = STAGES_FAST.replace(
    "  next:\n    phase: Running",
    f"  delay: {{duration: {DELAY_S}s}}\n  next:\n    phase: Running",
)


def _shm_leftovers() -> list:
    try:
        return [f for f in os.listdir("/dev/shm") if f.startswith("kwoktpu")]
    except OSError:
        return []


def _engine(master: str, cfg_path: str, workdir: str, *, procs: bool,
            extra=()) -> EngineProc:
    args = ["--tick-interval", str(QUANTUM), "--drain-deadline", "30"]
    if procs:
        args += ["--drain-shards", str(LANES), "--lane-procs", "true"]
    else:
        args += ["--drain-shards", "1"]
    return EngineProc(master, cfg_path, workdir, extra_args=args + list(extra))


def _lane_pids(engine_pid: int) -> list[int]:
    """The engine's spawned lane processes (cmdline carries
    multiprocessing's spawn bootstrap; the resource tracker does not)."""
    out = []
    try:
        kids = os.popen(f"ps -o pid= --ppid {engine_pid}").read().split()
    except OSError:
        return out
    for pid in kids:
        try:
            with open(f"/proc/{int(pid)}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ")
        except (OSError, ValueError):
            continue
        if b"spawn_main" in cmd and b"resource_tracker" not in cmd:
            out.append(int(pid))
    return sorted(out)


def _converge_and_delete(store, names, timeout: float) -> dict:
    out = {}
    out["converged"] = _wait(
        lambda: all(
            ph == "Running" for ph in _pod_phases(store, names).values()
        ),
        timeout,
    )
    out["final_phases"] = _pod_phases(store, names)
    # delete wave: half the keys get a deletionTimestamp -> the engine
    # must emit its DELETE after that key's Running patch (per-key order)
    doomed = names[::2]
    for n in doomed:
        store.patch_meta(
            "pods", "default", n,
            {"metadata": {"deletionTimestamp": "2026-01-01T00:00:00Z"}},
        )
    out["deleted_ok"] = _wait(
        lambda: all(
            store.get("pods", "default", n) is None for n in doomed
        ),
        timeout,
    )
    out["doomed"] = doomed
    out["per_key"] = {
        n: store.per_key_collapsed(("default", n)) for n in names
    }
    out["running_patches_per_pod"] = store.phase_counts("Running", names)
    return out


def _run_ordering_arm(pods, cfg_path, timeout, *, procs: bool) -> dict:
    srv = MockApiserver()
    store = srv.store
    names = [f"pp{i}" for i in range(pods)]
    workdir = tempfile.mkdtemp(prefix="kwok-proc-ord-")
    eng = _engine(srv.url, cfg_path, workdir, procs=procs)
    out = {"arm": f"ordering-{'proc' if procs else 'single'}"}
    try:
        out["ready_s"] = round(eng.wait_ready(), 3)
        for i in range(4):
            store.create("nodes", _make_node(f"pn{i}"))
        for n in names:
            store.create("pods", _make_pod(n, f"pn{hash(n) % 4}"))
        out.update(_converge_and_delete(store, names, timeout))
        out["sigterm_exit"] = eng.sigterm()
    finally:
        eng.kill_if_alive()
        srv.stop()
    out["shm_leftover"] = _shm_leftovers()
    return out


def _run_chaos_arm(pods, cfg_path, timeout) -> dict:
    """Rotating lane-process SIGKILLs, bench-driven so the rotation is
    paced by OBSERVED respawns (a period-driven storm on a starved host
    would out-kill the respawn latency and measure the scheduler, not
    the contract — the ha-check lesson). A parent-side wire storm
    (watch.cut) runs concurrently: the one fault plane composes with
    process lanes. The plane's own worker.kill -> SIGKILL delivery is
    pinned by tests/test_proclanes.py."""
    srv = MockApiserver()
    store = srv.store
    names = [f"cp{i}" for i in range(pods)]
    workdir = tempfile.mkdtemp(prefix="kwok-proc-chaos-")
    ckpt = tempfile.mkdtemp(prefix="kwok-proc-chaos-ckpt-")
    eng = _engine(
        srv.url, cfg_path, workdir, procs=True,
        extra=[
            "--faults", "seed=42;watch.cut=0.02",
            "--checkpoint-dir", ckpt,
            "--checkpoint-interval", str(CKPT_INTERVAL),
        ],
    )
    out = {"arm": "chaos"}
    try:
        out["ready_s"] = round(eng.wait_ready(), 3)
        for i in range(4):
            store.create("nodes", _make_node(f"cn{i}"))
        for n in names:
            store.create("pods", _make_pod(n, f"cn{hash(n) % 4}"))

        def restarts(shard: int) -> float:
            return eng.metrics().get(
                f'kwok_lane_proc_restarts_total{{shard="{shard}"}}', 0
            )

        # rotate: SIGKILL each lane in turn, mid-ingest, waiting for the
        # supervisor's respawn before the next round
        kills = 0
        for shard in range(LANES):
            lanes = _lane_pids(eng.proc.pid)
            if len(lanes) <= shard:
                break
            before = restarts(shard)
            os.kill(lanes[shard], signal.SIGKILL)
            kills += 1
            if not _wait(lambda: restarts(shard) > before, 120):
                break
        out["kills_delivered"] = kills
        out["converged"] = _wait(
            lambda: all(
                ph == "Running"
                for ph in _pod_phases(store, names).values()
            ),
            timeout * 2,
        )
        out["final_phases"] = _pod_phases(store, names)
        out["running_patches_per_pod"] = store.phase_counts("Running", names)
        m = eng.metrics()
        out["lane_restarts"] = {
            s: m.get(f'kwok_lane_proc_restarts_total{{shard="{s}"}}', 0)
            for s in range(LANES)
        }
        out["wire_faults_injected"] = m.get(
            'kwok_faults_injected_total{kind="watch.cut"}', 0
        )
        out["readyz_degraded"] = any(
            v for k, v in m.items() if k.startswith("kwok_degraded{")
        )
        out["sigterm_exit"] = eng.sigterm(timeout=60)
    finally:
        eng.kill_if_alive()
        srv.stop()
    out["shm_leftover"] = _shm_leftovers()
    return out


def _run_restart_arm(pods, cfg_path, timeout) -> dict:
    from kwok_tpu.engine.rowpool import shard_of

    srv = MockApiserver()
    store = srv.store
    names = [f"dp{i}" for i in range(pods)]
    workdir = tempfile.mkdtemp(prefix="kwok-proc-restart-")
    ckpt_dir = tempfile.mkdtemp(prefix="kwok-proc-restart-ckpt-")
    eng = _engine(
        srv.url, cfg_path, workdir, procs=True,
        extra=["--checkpoint-dir", ckpt_dir,
               "--checkpoint-interval", str(CKPT_INTERVAL)],
    )
    out = {"arm": "restart"}
    try:
        out["ready_s"] = round(eng.wait_ready(), 3)
        store.create("nodes", _make_node("dn0"))
        for n in names[: pods // 2]:
            store.create("pods", _make_pod(n, "dn0"))
        time.sleep(1.5)  # second wave: distinct checkpoint residues
        for n in names[pods // 2:]:
            store.create("pods", _make_pod(n, "dn0"))

        victim_lane = 0
        victim_pods = [
            n for n in names if shard_of(("default", n), LANES) == victim_lane
        ]
        ckpt_path = os.path.join(ckpt_dir, f"lane{victim_lane}.ckpt.json")

        def ckpt_armed():
            try:
                with open(ckpt_path, "rb") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                return False
            ents = doc.get("kinds", {}).get("pods", {})
            return len(ents) == len(victim_pods) and all(
                v[2] is not None for v in ents.values()
            )

        if not _wait(ckpt_armed, 30.0):
            raise RuntimeError(
                "lane checkpoint never covered every armed pod"
            )
        time.sleep(CKPT_INTERVAL + 0.2)  # gate against FRESH residues
        with open(ckpt_path, "rb") as f:
            doc = json.load(f)
        residues = {
            ks.split("/", 1)[1]: v[2]
            for ks, v in doc["kinds"]["pods"].items()
        }
        lanes = _lane_pids(eng.proc.pid)
        out["lane_pids"] = lanes
        if len(lanes) < LANES:
            raise RuntimeError(f"expected {LANES} lane processes: {lanes}")
        # mid-delay, no warning: the process-lane twin of restart_soak.
        # _lane_pids sorts by pid = spawn order, so lanes[0] is lane 0.
        os.kill(lanes[victim_lane], signal.SIGKILL)
        out["killed_at_wall"] = time.time()
        out["converged"] = _wait(
            lambda: all(
                ph == "Running"
                for ph in _pod_phases(store, names).values()
            ),
            timeout + DELAY_S + 60,
        )
        out["final_phases"] = _pod_phases(store, names)
        out["running_patches_per_pod"] = store.phase_counts("Running", names)
        m = eng.metrics()
        out["lane_restarts"] = m.get(
            f'kwok_lane_proc_restarts_total{{shard="{victim_lane}"}}', 0
        )
        # residue-resume oracle over the KILLED lane's pods only (the
        # surviving lane never stopped — its fires carry no respawn
        # anchor and would poison the median)
        fires = store.phase_stamps("Running")
        devs = {
            n: fires[n] - residues[n]
            for n in victim_pods
            if n in fires and residues.get(n) is not None
        }
        anchor = statistics.median(devs.values()) if devs else 0.0
        out["resume_pods_measured"] = len(devs)
        out["resume_deviation_s"] = {
            n: round(d - anchor, 4) for n, d in devs.items()
        }
        out["resume_max_abs_dev_s"] = round(
            max((abs(d - anchor) for d in devs.values()), default=999.0), 4
        )
        out["victim_pods"] = len(victim_pods)
        out["sigterm_exit"] = eng.sigterm(timeout=60)
    finally:
        eng.kill_if_alive()
        srv.stop()
    out["shm_leftover"] = _shm_leftovers()
    return out


def gates(single, proc, chaos, restart, pods) -> dict:
    same_keys = set(single["per_key"]) == set(proc["per_key"])
    return {
        # ordering oracle: the process fan-out is invisible on the wire
        "ordering_converged": bool(
            single["converged"] and proc["converged"]
            and single["deleted_ok"] and proc["deleted_ok"]
        ),
        "phases_identical": (
            json.dumps(single["final_phases"], sort_keys=True)
            == json.dumps(proc["final_phases"], sort_keys=True)
        ),
        "per_key_order_identical": same_keys and all(
            single["per_key"][k] == proc["per_key"][k]
            for k in single["per_key"]
        ),
        "ordering_no_double_fire": all(
            c == 1 for c in proc["running_patches_per_pod"].values()
        ),
        # chaos: rotating REAL SIGKILLs, same convergence contract
        "chaos_converged": bool(chaos["converged"]),
        "chaos_no_double_fire": all(
            c == 1 for c in chaos["running_patches_per_pod"].values()
        ) and len(chaos["running_patches_per_pod"]) == pods,
        "chaos_respawns_recorded": (
            chaos["kills_delivered"] >= 2
            and sum(chaos["lane_restarts"].values()) >= 2
        ),
        "chaos_not_degraded": not chaos["readyz_degraded"],
        # restart: mid-delay SIGKILL of one lane PROCESS
        "restart_converged": bool(restart["converged"]),
        "restart_no_double_fire": all(
            c == 1 for c in restart["running_patches_per_pod"].values()
        ) and len(restart["running_patches_per_pod"]) == pods,
        "restart_delays_resumed_within_quantum": (
            restart["resume_pods_measured"] == restart["victim_pods"]
            and restart["resume_max_abs_dev_s"] <= QUANTUM
        ),
        "restart_respawned": restart["lane_restarts"] >= 1,
        "graceful_exit_zero": all(
            a["sigterm_exit"] == 0 for a in (single, proc, chaos, restart)
        ),
        # shm hygiene: nothing left mapped after ANY arm (incl. the
        # SIGKILL-respawn cycles)
        "no_leaked_shm": not any(
            a["shm_leftover"] for a in (single, proc, chaos, restart)
        ),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--pods", type=int, default=24)
    p.add_argument("--timeout", type=float, default=90.0)
    p.add_argument("--out", default=os.path.join(REPO, "PROC_r01.json"))
    p.add_argument("--check", action="store_true",
                   help="CI gate: smaller workload, exit 1 on any "
                   "failed gate")
    args = p.parse_args()
    if args.check:
        args.pods = min(args.pods, 16)

    def stages_file(content, tag):
        f = tempfile.NamedTemporaryFile(
            "w", suffix=".yaml", prefix=f"kwok-proc-{tag}-", delete=False
        )
        f.write(content)
        f.close()
        return f.name

    fast = stages_file(STAGES_FAST, "fast")
    delay = stages_file(STAGES_DELAY, "delay")
    try:
        single = _run_ordering_arm(
            args.pods, fast, args.timeout, procs=False
        )
        proc = _run_ordering_arm(args.pods, fast, args.timeout, procs=True)
        chaos = _run_chaos_arm(args.pods, fast, args.timeout)
        restart = _run_restart_arm(args.pods, delay, args.timeout)
    finally:
        os.unlink(fast)
        os.unlink(delay)
    g = gates(single, proc, chaos, restart, args.pods)
    ok = all(g.values())
    artifact = {
        "bench": "proc_soak",
        "params": {"pods": args.pods, "lanes": LANES,
                   "tick_quantum_s": QUANTUM, "delay_s": DELAY_S,
                   "checkpoint_interval_s": CKPT_INTERVAL,
                   "check": args.check},
        "gates": g,
        "ok": ok,
        "arms": {
            "ordering_single": {k: single.get(k) for k in
                                ("ready_s", "converged", "sigterm_exit")},
            "ordering_proc": {k: proc.get(k) for k in
                              ("ready_s", "converged", "sigterm_exit")},
            "chaos": {k: chaos.get(k) for k in (
                "ready_s", "converged", "kills_delivered", "lane_restarts",
                "wire_faults_injected", "readyz_degraded",
                "sigterm_exit")},
            "restart": {k: restart.get(k) for k in (
                "ready_s", "converged", "lane_restarts",
                "resume_max_abs_dev_s", "resume_pods_measured",
                "victim_pods", "sigterm_exit")},
        },
    }
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(json.dumps({"ok": ok, "gates": g, "out": args.out}))
    if not ok:
        failed = [k for k, v in g.items() if not v]
        print(f"proc_soak: FAILED gates: {failed}", file=sys.stderr)
        if not g["per_key_order_identical"]:
            diffs = {
                k: (single["per_key"].get(k), proc["per_key"].get(k))
                for k in single["per_key"]
                if single["per_key"].get(k) != proc["per_key"].get(k)
            }
            print(f"proc_soak: per-key diffs: {diffs}", file=sys.stderr)
        if not g["restart_delays_resumed_within_quantum"]:
            print(
                "proc_soak: resume deviations: "
                f"{restart.get('resume_deviation_s')}", file=sys.stderr,
            )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
