"""Watcher-fleet survival gate: hundreds of informer-style watchers vs
the native apiserver while the threaded engine drives a real workload
under the PR 6 fault storm.

The apiserver tier's overload protection (ISSUE 8) is only proven if
hostile load cannot corrupt the engine's outcome OR starve it. The fleet
arm runs four watcher cohorts against the native server (admission bands
+ bounded watch buffers configured) while the in-process threaded engine
(native pump + native ingest) converges a creates-only workload through
the same server under the seeded fault storm:

- **normal**: list -> watch with rv resume + allowWatchBookmarks,
  reconnect on EOF, re-list on 410 (client-go reflector shape);
- **slow**: reads a few events, then STALLS (tiny SO_RCVBUF, no reads)
  through the storm + a fat-event filler burst — the server's bounded
  send buffer must overflow and TERMINATE the watch
  (kwok_watch_terminations_total{reason="slow"}), never OOM; the watcher
  then recovers by re-list, 410-class;
- **churn**: short watch cycles via timeoutSeconds + full re-list each
  cycle (connect/disconnect pressure, clean deadline closes);
- **flood**: back-to-back LISTs, no parsing (a mass-resync storm) — the
  cohort that genuinely saturates the readonly band and proves every
  429 is answered with a Retry-After sleep, never a hot retry.

Gates (--check exits nonzero on any failure):

- final pod phases byte-identical to a no-fleet control arm (same
  server config, same storm, no watchers);
- every surviving watcher converged to the final resourceVersion
  (bookmarks push quiet streams there);
- engine patch-RTT p99 within 2x the no-fleet baseline, measured by a
  dedicated post-convergence probe (sequential status patches with the
  fleet still attached) so the storm's injected pump backoffs don't
  pollute the comparison; a 100 ms absolute floor keeps core-starved CI
  hosts from gating on oversubscription (both disclosed in the
  artifact — see P99_FLOOR_S);
- zero unbounded-buffer growth, proven DETERMINISTICALLY from the
  server's own bounded-buffer accounting (ISSUE 11 re-anchor; the old
  RSS-ceiling + unconditional-termination form flaked on the 2-vCPU
  host, where burst timing sometimes let the stall window close before
  any buffer jammed): the `kwok_watch_backlog_events{agg="peak"}`
  high-watermark must never exceed the configured cap — a push onto a
  full buffer terminates the watch instead of growing it, so peak > cap
  is exactly "enforcement failed" regardless of host timing. RSS and
  the termination counters are still recorded in the artifact, but no
  longer gated;
- all 429s throttled, not retried hot: the server rejected requests
  (bands actually saturated), watchers saw 429s, and none issued its
  next request before the Retry-After hint elapsed.

Fleet watchers run in SEPARATE worker processes (this file, --worker)
so their GIL time cannot pollute the engine's RTT measurement; workers
coordinate through a control directory (target-rv file) and report JSON
per process. Emits FLEET_r*.json.
"""

from __future__ import annotations

import argparse
import heapq
import json
import os
import random
import selectors
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the engine-side storm (PR 6 grammar): stream cuts, 410 storms, list
# failures, blackouts, pump drops/partials — seeded, so reruns match
FLEET_STORM = (
    "seed={seed};pump.drop=0.05;pump.partial=0.05;"
    "watch.cut=0.02;watch.expire=0.2;list.fail=0.1;api.blackout=0.01:0.15"
)

# Absolute floor for the p99 ratio gate: on a core-starved CI host (2
# vCPUs here) every probe patch wakes the whole attached fleet, so the
# no-fleet ratio measures core oversubscription, not server starvation.
# 100 ms is the bound that still catches what the gate hunts — lock
# convoys, unbounded queueing, admission livelock — and the 2x ratio
# binds on hosts with cores to spare. At the ISSUE 13 scale the floor
# grows with the cohort (see _p99_floor): delivering one event to 1000
# sockets is ~1000 write syscalls + wakeups sharing 2 cores — per-event
# cost scales with the fleet no matter how cheap the encode got, and a
# fixed 60-watcher floor would gate on arithmetic, not on convoys.
# Both the base and the per-watcher term are disclosed in the artifact.
P99_FLOOR_S = 0.1
P99_FLOOR_PER_WATCHER_S = 2.5e-4


def _p99_floor(watchers: int) -> float:
    return max(P99_FLOOR_S, watchers * P99_FLOOR_PER_WATCHER_S)
# RSS is recorded for the artifact (post-mortem context) but no longer
# gated — the bounded-buffer proof is the backlog peak watermark
RSS_CEILING_BYTES = 512 << 20
FILLER_BYTES = 8192  # fat-event filler payload (jams stalled consumers)


# =========================================================== worker side
# (stdlib only: worker processes must not pay the JAX import)
#
# ONE selector thread per worker process drives every watcher as a
# non-blocking socket state machine (hand-rolled HTTP: request bytes
# out, headers + chunked de-framing in). A thread-per-watcher rig
# convoyed the whole host on every fanned-out event — 60+ wakeups per
# event across the workers polluted the very patch-RTT the gate
# measures, and would only get worse at the 200-watcher scale.

def _extract_rv(line: bytes) -> int:
    """First resourceVersion in the bytes: an event line carries exactly
    one (the object's), and both servers serialize a List's metadata —
    the list revision — BEFORE the items, so `find` (never `rfind`,
    which would grab the first ITEM's stale rv off a list head) reads
    the right one without any JSON parse."""
    i = line.find(b'"resourceVersion":"')
    if i < 0:
        return 0
    j = line.find(b'"', i + 19)
    try:
        return int(line[i + 19:j])
    except ValueError:
        return 0


class _Watcher:
    """One informer-style state machine. States: idle (waiting on a
    timer), connecting, sent (awaiting headers), list-body, stream
    (chunked watch). Tracks the throttling contract: after a 429, the
    NEXT request must not fire before the Retry-After hint elapses."""

    def __init__(self, fw: "_FleetWorker", idx: int, kind: str):
        self.fw = fw
        self.idx = idx
        self.kind = kind  # "normal" | "slow" | "churn" | "flood"
        self.rng = random.Random((fw.seed, idx))
        self.stalled = False  # slow cohort: one stall per lifetime
        self.rv = 0
        self.lists = 0
        self.watches = 0
        self.n429 = 0
        self.throttle_s = 0.0
        self.hot_violations = 0
        self.eofs = 0
        self.terminations_seen = 0
        self.errors = 0
        self.converged = False
        self._next_allowed = 0.0  # monotonic stamp set by a 429
        # connection state
        self.sock: "socket.socket | None" = None
        self.state = "idle"
        self.req = b""
        self.buf = bytearray()
        self.body_left = 0
        self.body_head = b""
        self.chunk_need: "int | None" = None
        self.stream_lines = 0
        self.is_watch = False
        self.flood_window_until = 0.0

    # ------------------------------------------------------------ actions

    def start(self) -> None:
        if self.kind == "flood":
            # mass-resync storm: back-to-back LISTs through the storm +
            # filler window (429s pace it), then settle to a slow poll
            self.flood_window_until = time.monotonic() + self.fw.stall_s
        self._begin(watch=False)

    def _begin(self, watch: bool) -> None:
        """Open a fresh connection for one LIST or watch."""
        now = time.monotonic()
        if now < self._next_allowed:
            # timers always schedule past next_allowed; firing early
            # would BE the hot-retry bug the gate hunts
            self.hot_violations += 1
        self.is_watch = watch
        self.buf.clear()
        self.body_head = b""
        self.chunk_need = None
        self.stream_lines = 0
        s = socket.socket()
        if self.kind == "slow" and not self.stalled:
            # a genuinely slow consumer: tiny receive window (set before
            # connect so the handshake advertises it), so the server's
            # sends jam once the filler burst outruns us
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        s.setblocking(False)
        s.connect_ex((self.fw.host, self.fw.port))
        self.sock = s
        if watch:
            timeout_q = "&timeoutSeconds=2" if self.kind == "churn" else ""
            path = (
                f"/api/v1/pods?watch=true&resourceVersion={self.rv}"
                f"&allowWatchBookmarks=true{timeout_q}"
            )
        else:
            path = "/api/v1/pods"
        self.req = (
            f"GET {path} HTTP/1.1\r\nHost: {self.fw.host}\r\n\r\n"
        ).encode()
        self.state = "connecting"
        self.fw.register(self, selectors.EVENT_WRITE)

    def _close(self) -> None:
        if self.sock is not None:
            self.fw.unregister(self)
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        self.state = "idle"

    def _schedule(self, delay: float, watch: bool) -> None:
        self._close()
        self.fw.schedule(delay, self, "watch" if watch else "list")

    def _throttled(self, retry_after: float) -> None:
        self.n429 += 1
        # full jitter on top of the hint (the RetryPolicy shape): never
        # below the hint, never a synchronized stampede either
        delay = retry_after + self.rng.uniform(0, retry_after)
        self._next_allowed = time.monotonic() + retry_after
        self.throttle_s += delay
        self._schedule(delay, watch=self.is_watch)

    def _next_after_stream(self) -> None:
        """Stream over (EOF/ERROR/churn): what an informer does next."""
        if self._maybe_converged():
            return
        if self.kind == "churn" or (self.eofs % 7 == 3):
            self.rv = 0  # re-list instead of resuming
        self._schedule(0.0 if self.rv else 0.05, watch=bool(self.rv))

    def _maybe_converged(self) -> bool:
        t = self.fw.target
        if t and self.rv >= t:
            self.converged = True
            self._close()
            self.state = "done"
            return True
        return False

    def on_timer(self, action: str) -> None:
        if self.state == "done":
            return
        if action == "resume_read":
            # stall over: drink the backlog; the server most likely
            # terminated us mid-stall (that EOF is the point)
            if self.sock is not None:
                self.fw.register(self, selectors.EVENT_READ)
            return
        if action == "churn_cut":
            if self.state == "stream":
                self.eofs += 0  # voluntary close, not a server EOF
                self._next_after_stream()
            return
        if self._maybe_converged():
            return
        self._begin(watch=(action == "watch"))

    # ---------------------------------------------------------------- io

    def on_io(self) -> None:
        try:
            self._on_io()
        except OSError:
            self.errors += 1
            self._schedule(0.2, watch=False if self.rv == 0 else True)

    def _on_io(self) -> None:
        if self.state == "connecting":
            err = self.sock.getsockopt(
                socket.SOL_SOCKET, socket.SO_ERROR
            )
            if err:
                self.errors += 1
                self._schedule(0.2, watch=self.is_watch)
                return
            self.sock.sendall(self.req)  # small; loopback takes it whole
            self.state = "headers"
            self.fw.register(self, selectors.EVENT_READ)
            return
        data = self.sock.recv(1 << 16)
        if not data:
            self._on_eof()
            return
        self.buf += data
        if self.state == "headers":
            i = self.buf.find(b"\r\n\r\n")
            if i < 0:
                return
            head = bytes(self.buf[:i]).lower()
            del self.buf[:i + 4]
            try:
                status = int(head.split(b" ", 2)[1])
            except (IndexError, ValueError):
                self.errors += 1
                self._schedule(0.2, watch=self.is_watch)
                return
            if status == 429:
                ra = 1.0
                j = head.find(b"retry-after:")
                if j >= 0:
                    try:
                        ra = float(
                            head[j + 12:head.find(b"\r\n", j)].strip() or 1
                        )
                    except ValueError:
                        pass
                self._throttled(ra)
                return
            if status != 200:
                self.errors += 1
                self._schedule(0.5, watch=self.is_watch)
                return
            if self.is_watch:
                self.watches += 1
                self.state = "stream"
                if self.kind == "churn":
                    self.fw.schedule(
                        self.rng.uniform(0.3, 1.5), self, "churn_cut"
                    )
                self._consume_stream()
            else:
                cl = 0
                j = head.find(b"content-length:")
                if j >= 0:
                    try:
                        cl = int(head[j + 15:head.find(b"\r\n", j)])
                    except ValueError:
                        pass
                self.body_left = cl
                self.state = "body"
                self._consume_body()
            return
        if self.state == "body":
            self._consume_body()
        elif self.state == "stream":
            self._consume_stream()

    def _consume_body(self) -> None:
        take = min(len(self.buf), self.body_left)
        if len(self.body_head) < 256:
            self.body_head += bytes(self.buf[:256 - len(self.body_head)])
        del self.buf[:take]
        self.body_left -= take
        if self.body_left > 0:
            return
        # list done: rv rides in the List metadata, which both servers
        # serialize BEFORE items — no JSON parse needed
        self.lists += 1
        rv = _extract_rv(self.body_head)
        if rv:
            self.rv = rv
        if self._maybe_converged():
            return
        if self.kind == "flood":
            if time.monotonic() < self.flood_window_until:
                self._schedule(0.0, watch=False)
            else:
                self._schedule(1.0, watch=False)
            return
        self._schedule(0.0, watch=True)

    def _consume_stream(self) -> None:
        """De-chunk + handle event lines (both servers write one chunk
        per event line)."""
        while True:
            if self.chunk_need is None:
                i = self.buf.find(b"\r\n")
                if i < 0:
                    return
                try:
                    size = int(bytes(self.buf[:i]) or b"0", 16)
                except ValueError:
                    self.errors += 1
                    self._next_after_stream()
                    return
                del self.buf[:i + 2]
                if size == 0:
                    # terminal chunk: the server ENDED the watch cleanly
                    # (timeoutSeconds deadline) — resume from rv
                    self._next_after_stream()
                    return
                self.chunk_need = size
            if len(self.buf) < self.chunk_need + 2:
                return
            line = bytes(self.buf[:self.chunk_need])
            del self.buf[:self.chunk_need + 2]
            self.chunk_need = None
            self.stream_lines += 1
            if line.startswith(b'{"type":"ERROR"'):
                if b'"code":410' in line:
                    self.rv = 0  # compacted: full re-list next
                self._next_after_stream()
                return
            rv = _extract_rv(line)
            if rv:
                self.rv = rv
            if self._maybe_converged():
                return
            if (
                self.kind == "slow" and not self.stalled
                and len(line) > FILLER_BYTES // 2
            ):
                # the stall, keyed on the FIRST fat filler event (a line
                # count would start it during workload creates and let
                # it expire mid-burst on a slow host): stop reading
                # entirely while the rest of the burst fans out (socket
                # stays open, kernel buffers jam); the server must
                # terminate us, never buffer unboundedly
                self.stalled = True
                self.fw.unregister(self)
                self.fw.schedule(self.fw.stall_s, self, "resume_read")
                return

    def _on_eof(self) -> None:
        if self.state == "stream":
            self.eofs += 1
            if self.kind == "slow" and self.stalled:
                self.terminations_seen += 1
            self._next_after_stream()
        else:
            self.errors += 1
            self._schedule(0.2, watch=self.is_watch)


class _FleetWorker:
    """One process's fleet: a single selector loop over every watcher."""

    def __init__(self, args):
        host, port = args.server.rsplit(":", 1)
        self.host = host.split("//")[-1]
        self.port = int(port)
        self.seed = args.seed
        self.stall_s = args.stall
        self.ctl = args.ctl
        self.deadline = time.time() + args.deadline
        self.target = 0
        self.sel = selectors.DefaultSelector()
        self._timers: list = []  # heap of (when, seq, watcher, action)
        self._seq = 0
        kinds = (
            ["slow"] * args.slow + ["churn"] * args.churn
            + ["flood"] * args.flood
            + ["normal"] * (args.n - args.slow - args.churn - args.flood)
        )
        self.watchers = [
            _Watcher(self, i, kinds[i]) for i in range(args.n)
        ]

    def register(self, w: _Watcher, events: int) -> None:
        try:
            self.sel.modify(w.sock, events, w)
        except KeyError:
            self.sel.register(w.sock, events, w)

    def unregister(self, w: _Watcher) -> None:
        try:
            self.sel.unregister(w.sock)
        except (KeyError, ValueError):
            pass

    def schedule(self, delay: float, w: _Watcher, action: str) -> None:
        self._seq += 1
        heapq.heappush(
            self._timers, (time.monotonic() + delay, self._seq, w, action)
        )

    def _read_target(self) -> None:
        if self.target:
            return
        try:
            with open(os.path.join(self.ctl, "target_rv")) as f:
                self.target = int(f.read().strip() or 0)
        except (OSError, ValueError):
            pass

    def run(self) -> dict:
        for w in self.watchers:
            w.start()
        next_target_poll = 0.0
        attached = False
        while time.time() < self.deadline:
            now = time.monotonic()
            while self._timers and self._timers[0][0] <= now:
                _, _, w, action = heapq.heappop(self._timers)
                w.on_timer(action)
            if now >= next_target_poll:
                self._read_target()
                next_target_poll = now + 0.2
                if not attached and all(
                    w.state == "stream" or w.stalled
                    for w in self.watchers if w.kind == "slow"
                ):
                    # the parent holds the fat-event filler burst until
                    # every slow watcher is on a live stream — a 429-
                    # throttled attach racing past the filler would make
                    # the slow-termination gate vacuous
                    attached = True
                    with open(os.path.join(
                        self.ctl, f"attached-{os.getpid()}"
                    ), "w") as f:
                        f.write("1")
                if self.target and all(
                    w.state == "done" for w in self.watchers
                ):
                    break
            timeout = 0.2
            if self._timers:
                timeout = min(
                    timeout, max(0.0, self._timers[0][0] - now)
                )
            for key, _ev in self.sel.select(timeout):
                key.data.on_io()
        ws = self.watchers
        return {
            "n": len(ws),
            "converged": sum(w.converged for w in ws),
            "crashed": 0,  # a raising state machine lands in errors
            "lists": sum(w.lists for w in ws),
            "watches": sum(w.watches for w in ws),
            "n429": sum(w.n429 for w in ws),
            "throttle_s": round(sum(w.throttle_s for w in ws), 3),
            "hot_violations": sum(w.hot_violations for w in ws),
            "eofs": sum(w.eofs for w in ws),
            "slow_terminations_seen": sum(
                w.terminations_seen for w in ws
            ),
            "stalled": sum(w.stalled for w in ws),
            "errors": sum(w.errors for w in ws),
            "by_kind_converged": {
                k: sum(w.converged for w in ws if w.kind == k)
                for k in ("normal", "slow", "churn", "flood")
            },
        }


def _worker_main(args) -> int:
    report = _FleetWorker(args).run()
    with open(
        os.path.join(args.ctl, f"report-{os.getpid()}.json"), "w"
    ) as f:
        json.dump(report, f)
    return 0


# =========================================================== parent side

def _server_env(a) -> dict:
    return {
        "KWOK_TPU_MAX_INFLIGHT": str(a.max_inflight),
        "KWOK_TPU_MAX_MUTATING_INFLIGHT": str(a.max_mutating_inflight),
        "KWOK_TPU_WATCH_BACKLOG": str(a.watch_backlog),
        # quiet streams must reach the final rv promptly at gate close
        "KWOK_TPU_BOOKMARK_INTERVAL": "0.5",
    }


def _retrying(fn, timeout: float = 60.0):
    """Run one client call, honoring 429 Retry-After (the rig is a
    well-behaved client too)."""
    from kwok_tpu.edge.kubeclient import TooManyRequests

    deadline = time.time() + timeout
    while True:
        try:
            return fn()
        except TooManyRequests as e:
            if time.time() > deadline:
                raise
            time.sleep(e.retry_after)


def _probe_rtt(client, n: int = 80) -> dict:
    """Sequential status patches on the (unmanaged, SMALL) probe pod,
    each timed individually — the apiserver-responsiveness probe the p99
    gate compares across arms. Engine-shaped: status patches are small
    (probing the fat filler pod would measure byte-fanout volume, not
    request latency). Throttled attempts sleep OUTSIDE the timed window
    (the gate measures server RTT, not the rig's own pacing)."""
    from kwok_tpu.edge.kubeclient import TooManyRequests

    samples: list = []
    throttled = 0
    for i in range(n):
        while True:
            t0 = time.perf_counter()
            try:
                client.patch_status(
                    "pods", "default", "zz-probe",
                    {"status": {"probe": str(i)}},
                )
            except TooManyRequests as e:
                throttled += 1
                time.sleep(e.retry_after)
                continue
            samples.append(time.perf_counter() - t0)
            break
    samples.sort()
    return {
        "count": len(samples),
        "throttled": throttled,
        "p50_s": round(samples[len(samples) // 2], 6),
        "p99_s": round(samples[max(0, int(len(samples) * 0.99) - 1)], 6),
        "max_s": round(samples[-1], 6),
    }


def _drive(a, url: str, with_storm: bool, before_filler=None):
    """Start the in-process threaded engine against ``url``, create the
    workload (+ the unmanaged filler pod), run the storm window and the
    fat-event filler burst, converge. Returns (engine, client, names,
    result-dict); caller stops both."""
    from benchmarks.rig import make_node, make_pod
    from kwok_tpu.edge.httpclient import HttpKubeClient
    from kwok_tpu.engine import ClusterEngine, EngineConfig

    client = HttpKubeClient(url)
    spec = FLEET_STORM.format(seed=a.seed) if with_storm else ""
    eng = ClusterEngine(
        HttpKubeClient(url),
        EngineConfig(
            manage_all_nodes=True, tick_interval=0.02, drain_shards=2,
            faults=spec,
        ),
    )
    names = [f"fp{i}" for i in range(a.pods)]
    nodes = [f"fn{i}" for i in range(4)]
    eng.start()
    out: dict = {}
    t0 = time.time()
    for n in nodes:
        _retrying(lambda n=n: client.create("nodes", make_node(n)))
    # the filler pod: unbound, so no Stage ever touches it — its fat
    # status patches exist to flood the watch fanout (and later to be
    # the RTT probe target); excluded from the phase oracle
    filler = make_pod("zz-filler", node="")
    filler["spec"]["nodeName"] = ""
    _retrying(lambda: client.create("pods", filler))
    probe = make_pod("zz-probe", node="")
    probe["spec"]["nodeName"] = ""
    _retrying(lambda: client.create("pods", probe))
    for n in names:
        _retrying(
            lambda n=n: client.create(
                "pods", make_pod(n, nodes[hash(n) % len(nodes)])
            )
        )
    if with_storm:
        time.sleep(a.storm_s)
        eng._faults.spec.rates.clear()
        out["faults_injected"] = eng._faults.counts()
    if before_filler is not None:
        before_filler()
    # fat-event filler burst: enough watch-fanout bytes that a stalled
    # consumer's socket jams and its bounded send buffer overflows
    pad = "x" * FILLER_BYTES
    for i in range(a.filler_events):
        _retrying(lambda i=i: client.patch_status(
            "pods", "default", "zz-filler",
            {"status": {"filler": pad, "seq": str(i)}},
        ))
    out["filler_events"] = a.filler_events

    def phases() -> dict:
        return {
            n: ((_retrying(
                lambda n=n: client.get("pods", "default", n)
            ) or {}).get("status") or {}).get("phase")
            for n in names
        }

    deadline = time.time() + a.timeout
    ph: dict = {}
    while time.time() < deadline:
        ph = phases()
        if all(p == "Running" for p in ph.values()):
            break
        time.sleep(0.25)
    out["converged"] = all(p == "Running" for p in ph.values())
    out["final_phases"] = ph
    out["wall_s"] = round(time.time() - t0, 3)
    # settle before probing: terminated slow watchers re-attach and
    # drink their multi-MB replay right after convergence; the probe
    # measures the ATTACHED steady state, not that one-off drain
    time.sleep(3.0)
    out["probe"] = _probe_rtt(client)
    out["p99_s"] = out["probe"]["p99_s"]
    tel = eng.telemetry
    out["client_throttle_s"] = round(tel.client_throttle_seconds, 3)
    out["watch_relists_total"] = eng.metrics["watch_relists_total"]
    return eng, client, names, out


def _run_arm(a, fleet: bool) -> dict:
    from benchmarks.rig import NativeApiserver, scrape_metrics

    srv = NativeApiserver.spawn(env=_server_env(a))
    if srv is None:
        raise RuntimeError("no C++ compiler for the native apiserver")
    out = {"arm": "fleet" if fleet else "control"}
    ctl = tempfile.mkdtemp(prefix="kwok-fleet-")
    workers: list = []
    rss0 = srv.rss_bytes()
    # the slow cohort's stall must outlive the storm + filler burst
    stall_s = a.storm_s + 6.0
    try:
        if fleet:
            per = a.watchers // a.worker_procs
            slow_per = a.slow // a.worker_procs
            churn_per = a.churn // a.worker_procs
            flood_per = a.flood // a.worker_procs
            for _ in range(a.worker_procs):
                workers.append(subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__),
                     "--worker", "--server", srv.url, "--n", str(per),
                     "--slow", str(slow_per), "--churn", str(churn_per),
                     "--flood", str(flood_per),
                     "--stall", str(stall_s),
                     "--seed", str(a.seed), "--ctl", ctl,
                     # workers must outlive the whole parent pipeline
                     # (throttled setup + storm + filler + convergence +
                     # settle + probe + target write): at the 1000-watcher
                     # scale that approaches the convergence timeout
                     # itself on a 2-vCPU host, and a worker dying before
                     # the target lands reads as a false non-convergence
                     "--deadline", str(a.timeout + 240)],
                    cwd=REPO,
                ))
        def wait_attached():
            # hold the filler until every worker's slow cohort is on a
            # live stream (30s fallback: a hung worker must not hang
            # the gate; the termination gate then reports honestly)
            t0 = time.time()
            deadline = t0 + 30
            got = 0
            while fleet and time.time() < deadline:
                got = sum(
                    1 for f in os.listdir(ctl)
                    if f.startswith("attached-")
                )
                if got >= len(workers):
                    break
                time.sleep(0.2)
            out["attach_wait_s"] = round(time.time() - t0, 3)
            out["attached_workers"] = got

        eng = client = None
        try:
            eng, client, names, drive = _drive(
                a, srv.url, with_storm=True, before_filler=wait_attached
            )
            out.update(drive)
            if fleet:
                # the convergence target: the store revision after the
                # last write; bookmarks carry quiet watchers there
                final = _retrying(lambda: client._json(
                    "GET", client.server + "/api/v1/pods?limit=1"
                ))
                target_rv = int(
                    (final.get("metadata") or {}).get("resourceVersion")
                    or 0
                )
                out["target_rv"] = target_rv
                tmp = os.path.join(ctl, "target_rv.tmp")
                with open(tmp, "w") as f:
                    f.write(str(target_rv))
                os.replace(tmp, os.path.join(ctl, "target_rv"))
                for w in workers:
                    w.wait(timeout=a.timeout + 270)
        finally:
            if eng is not None:
                eng.stop()
            if client is not None:
                client.close()
        out["server_metrics"] = {
            k: v for k, v in scrape_metrics(srv.url + "/metrics").items()
            # buckets excluded: the timing histograms would triple the
            # artifact; their _sum/_count series carry the attribution
            if k.startswith("kwok_") and "_bucket{" not in k
        }
        out["server_rss_bytes"] = srv.rss_bytes()
        out["server_rss_growth_bytes"] = out["server_rss_bytes"] - rss0
        if fleet:
            rep = {
                "n": 0, "converged": 0, "crashed": 0, "lists": 0,
                "watches": 0, "n429": 0, "throttle_s": 0.0,
                "hot_violations": 0, "eofs": 0,
                "slow_terminations_seen": 0, "stalled": 0, "errors": 0,
                "by_kind_converged": {},
            }
            for fname in os.listdir(ctl):
                if not fname.startswith("report-"):
                    continue
                with open(os.path.join(ctl, fname)) as f:
                    r = json.load(f)
                for k, v in r.items():
                    if k == "by_kind_converged":
                        for kk, vv in v.items():
                            rep[k][kk] = rep[k].get(kk, 0) + vv
                    else:
                        rep[k] += v
            out["fleet"] = rep
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
        srv.stop()
    return out


def gates(control: dict, fleet: dict, a) -> dict:
    sm = fleet.get("server_metrics", {})
    rep = fleet.get("fleet", {})
    rejected = sum(
        v for k, v in sm.items()
        if k.startswith("kwok_apiserver_rejected_total")
    )
    # the server's bounded-buffer high-watermark (never exceeds the cap
    # while enforcement works); missing scrape = worst case, fails gate
    backlog_peak = sm.get(
        'kwok_watch_backlog_events{agg="peak"}', a.watch_backlog + 1
    )
    fleet_n = rep.get("n", 0)
    p99_bound = max(2 * control["p99_s"], _p99_floor(a.watchers))
    return {
        "control_converged": bool(control["converged"]),
        "fleet_converged": bool(fleet["converged"]),
        # the headline: the fleet cannot corrupt the outcome
        "phases_identical": (
            json.dumps(control["final_phases"], sort_keys=True)
            == json.dumps(fleet["final_phases"], sort_keys=True)
        ),
        # every surviving watcher caught up to the final revision
        "watchers_converged": (
            fleet_n == (a.watchers // a.worker_procs) * a.worker_procs
            and rep.get("crashed", 1) == 0
            and rep.get("converged", 0) == fleet_n
        ),
        # the engine's server stayed responsive despite the fleet
        "patch_rtt_p99_bounded": fleet["p99_s"] <= p99_bound,
        # admission actually engaged, and nobody retried hot
        "429s_throttled_not_hot": (
            rejected > 0
            and rep.get("n429", 0) > 0
            and rep.get("hot_violations", 1) == 0
        ),
        # bounded buffers, deterministically: no per-watcher send buffer
        # ever grew past the cap. peak is the server's own push-time
        # high-watermark, and BY CONSTRUCTION a push onto a full buffer
        # terminates the watch instead of growing it — so peak > cap is
        # exactly "enforcement failed", while peak == cap is a legally
        # full buffer (it may drain, or the NEXT push terminates it; no
        # termination count is owed — requiring one was the old gate's
        # host-timing flake in a new coat). RSS and the termination
        # counters ride the artifact unchecked.
        "no_unbounded_buffer_growth": backlog_peak <= a.watch_backlog,
    }


def main() -> int:
    p = argparse.ArgumentParser()
    # ISSUE 13: the serialize-once broadcast ring made the 200-watcher
    # fleet cheap — the default cohort is now 1000 (same mix, 5x), the
    # scale the ring's one-encode-per-event must hold at
    p.add_argument("--watchers", type=int, default=1000)
    p.add_argument("--slow", type=int, default=120,
                   help="deliberately-slow cohort size")
    p.add_argument("--churn", type=int, default=200,
                   help="connect/disconnect cohort size")
    p.add_argument("--flood", type=int, default=120,
                   help="back-to-back list cohort size (mass resync)")
    p.add_argument("--pods", type=int, default=96)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--worker-procs", type=int, default=4,
                   help="fleet worker processes (keeps watcher GIL time "
                   "out of the engine's RTT measurement)")
    p.add_argument("--max-inflight", type=int, default=8,
                   help="server readonly band (LIST/GET)")
    p.add_argument("--max-mutating-inflight", type=int, default=64,
                   help="server mutating band (engine writes/binds)")
    p.add_argument("--watch-backlog", type=int, default=128,
                   help="server per-watcher send-buffer cap")
    p.add_argument("--filler-events", type=int, default=400,
                   help="fat status patches fanned out to jam stalled "
                   "consumers")
    p.add_argument("--storm-s", type=float, default=3.0,
                   help="fault-storm window length")
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--out", default=os.path.join(REPO, "FLEET_r02.json"))
    p.add_argument("--check", action="store_true",
                   help="CI gate: exit 1 on any failed gate (the full "
                   "1000-watcher cohort — the ring must hold at scale)")
    # internal: worker-process mode
    p.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--server", default="", help=argparse.SUPPRESS)
    p.add_argument("--n", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--ctl", default="", help=argparse.SUPPRESS)
    p.add_argument("--stall", type=float, default=8.0,
                   help=argparse.SUPPRESS)
    p.add_argument("--deadline", type=float, default=180.0,
                   help=argparse.SUPPRESS)
    a = p.parse_args()
    if a.worker:
        return _worker_main(a)
    if a.check:
        # fleet-check gates AT the 1000-watcher scale (ISSUE 13): the
        # cohort mix stays the default; only the engine workload and the
        # admission/backlog knobs shrink to CI size
        a.pods = min(a.pods, 48)
        a.max_inflight = 4
        a.max_mutating_inflight = 32
        a.watch_backlog = 64
        a.filler_events = 300

    from kwok_tpu import native

    if native.apiserver_binary() is None:
        # same skip contract as the parity twins: no C++ compiler means
        # no native apiserver to gate against
        print(json.dumps({
            "ok": True, "skipped": "no C++ compiler for native apiserver",
        }))
        return 0

    control = _run_arm(a, fleet=False)
    fleet = _run_arm(a, fleet=True)
    g = gates(control, fleet, a)
    ok = all(g.values())
    # ISSUE 13: the slow-close MECHANISM changed (per-watcher buffer
    # drops -> ring-cursor lag); record this run's ring-lag terminations
    # against the r01 buffer-drop counts so the contract's continuity is
    # auditable in one place
    sm = fleet.get("server_metrics", {})
    ring_vs_r01: dict = {
        "ring_lag_terminations_slow": sm.get(
            'kwok_watch_terminations_total{reason="slow"}'
        ),
        "ring_lag_peak": sm.get('kwok_watch_ring_lag{agg="peak"}'),
        "ring_encode_total": sm.get("kwok_watch_encode_total"),
        "ring_fanout_total": sm.get("kwok_watch_fanout_total"),
    }
    try:
        with open(os.path.join(REPO, "FLEET_r01.json")) as fh:
            r01 = json.load(fh)
        r01_sm = (r01.get("fleet_arm") or {}).get("server_metrics") or {}
        ring_vs_r01["r01_buffer_drop_terminations_slow"] = r01_sm.get(
            'kwok_watch_terminations_total{reason="slow"}'
        )
        ring_vs_r01["r01_backlog_peak"] = r01_sm.get(
            'kwok_watch_backlog_events{agg="peak"}'
        )
        ring_vs_r01["r01_watchers"] = (r01.get("params") or {}).get(
            "watchers"
        )
    except (OSError, ValueError):
        ring_vs_r01["r01_buffer_drop_terminations_slow"] = None
    artifact = {
        "bench": "watcher_fleet",
        "storm": FLEET_STORM.format(seed=a.seed),
        "params": {
            "watchers": a.watchers, "slow": a.slow, "churn": a.churn,
            "flood": a.flood, "pods": a.pods, "seed": a.seed,
            "worker_procs": a.worker_procs,
            "max_inflight": a.max_inflight,
            "max_mutating_inflight": a.max_mutating_inflight,
            "watch_backlog": a.watch_backlog,
            "filler_events": a.filler_events,
            "filler_bytes": FILLER_BYTES,
            "p99_floor_s": _p99_floor(a.watchers),
            "p99_floor_base_s": P99_FLOOR_S,
            "p99_floor_per_watcher_s": P99_FLOOR_PER_WATCHER_S,
            "rss_ceiling_bytes": RSS_CEILING_BYTES,
            "check": a.check,
        },
        "gates": g,
        "ok": ok,
        "ring_lag_vs_r01_buffer_drops": ring_vs_r01,
        "control": {
            k: control.get(k)
            for k in ("converged", "wall_s", "p99_s", "probe",
                      "client_throttle_s", "watch_relists_total",
                      "server_rss_bytes", "faults_injected")
        },
        "fleet_arm": {
            k: fleet.get(k)
            for k in ("converged", "wall_s", "p99_s", "probe",
                      "client_throttle_s", "watch_relists_total",
                      "server_rss_bytes", "server_rss_growth_bytes",
                      "target_rv", "faults_injected", "server_metrics",
                      "fleet")
        },
    }
    with open(a.out, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(json.dumps({"ok": ok, "gates": g, "out": a.out}))
    if not ok:
        failed = [k for k, v in g.items() if not v]
        print(f"watcher_fleet: FAILED gates: {failed}", file=sys.stderr)
        if not g["phases_identical"]:
            diff = {
                n: (control["final_phases"].get(n),
                    fleet["final_phases"].get(n))
                for n in control["final_phases"]
                if control["final_phases"].get(n)
                != fleet["final_phases"].get(n)
            }
            print(f"watcher_fleet: phase diffs: {diff}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
