"""Router microbench: per-event Python hash+dispatch vs native partition.

The sharded engine's router was the last per-event Python term on the
serial ingest lane: for every parsed record it computed the key, crc32'd
it (rowpool.shard_of) and queue-put one item to the owning lane —
1.8-5.9µs/pod measured across rounds, an absolute ~200-550k pods/s wall
no lane count could cross. Native pre-partitioned routing (ingest.cc
ABI 7) moves the hash + partition into the SAME C call that parses the
batch and hands each lane one zero-copy sub-batch, so the router's cost
stops scaling with the event rate.

This bench measures exactly those two router bodies over the same lines,
hb_micro-style (interleaved best-of windows: single windows on shared
hosts swing far more than the delta under test):

- python arm: parse (eager lists) + the per-record LaneSet.route body —
  key build, shard_of, SimpleQueue put per event.
- native arm: parse with n_shards partition + the LaneSet.route_batch
  body — one (batch, index-run) put per lane with work.

Both arms include the batch parse (the router thread pays it either
way); the delta is the per-event Python routing term. Prints ONE JSON
line; --check mode runs small and exits nonzero if the native arm is not
faster (the regression gate `make lane-check` runs).
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _pod_line(i: int) -> bytes:
    return json.dumps({
        "type": "ADDED",
        "object": {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"rm-{i}", "namespace": "default",
                         "resourceVersion": str(100 + i)},
            "spec": {"nodeName": "rm-node-0",
                     "containers": [{"name": "c", "image": "x"}]},
            "status": {"phase": "Pending"},
        },
    }, separators=(",", ":")).encode()


def run(events: int, shards: int, windows: int) -> dict:
    from kwok_tpu import native
    from kwok_tpu.engine.lanes import iter_recb_items
    from kwok_tpu.engine.rowpool import shard_of

    if not native.available():
        return {"skipped": "native codec unavailable"}
    parser = native.EventParser()
    lines = [_pod_line(i) for i in range(events)]

    def python_arm() -> float:
        sinks = [queue.SimpleQueue() for _ in range(shards)]
        t0 = time.perf_counter()
        batch = parser.parse_raw_batch(lines)
        t = time.monotonic()
        record = batch.record
        for i in range(batch.n):
            rec = record(i)
            key = (rec.namespace or "default", rec.name)
            sinks[shard_of(key, shards)].put(("pods", "REC", rec, t))
        return time.perf_counter() - t0

    def native_arm() -> float:
        sinks = [queue.SimpleQueue() for _ in range(shards)]
        t0 = time.perf_counter()
        batch = parser.parse_raw_batch(lines, kind="pods", n_shards=shards)
        t = time.monotonic()
        for li, _count, item in iter_recb_items("pods", batch, t):
            sinks[li].put(item)
        return time.perf_counter() - t0

    # interleaved best-of pairs (hb_micro rationale): the min of each arm
    # is the honest per-event cost on a noisy shared host
    py_best = nat_best = float("inf")
    for _ in range(windows):
        py_best = min(py_best, python_arm())
        nat_best = min(nat_best, native_arm())
    py_us = 1e6 * py_best / events
    nat_us = 1e6 * nat_best / events
    return {
        "metric": (
            f"router serial cost per event at {events} events x {shards} "
            f"lanes (best of {windows} interleaved windows; both arms "
            "include the batch parse)"
        ),
        "python_route_us_per_event": round(py_us, 3),
        "native_route_us_per_event": round(nat_us, 3),
        "python_routing_term_removed_us": round(py_us - nat_us, 3),
        "speedup": round(py_us / max(nat_us, 1e-9), 2),
        "events": events,
        "shards": shards,
        "windows": windows,
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--events", type=int, default=50000)
    p.add_argument("--shards", type=int, default=8)
    p.add_argument("--windows", type=int, default=5)
    p.add_argument("--check", action="store_true",
                   help="small regression gate: exit 1 unless the native "
                   "arm beats the python arm")
    args = p.parse_args()
    if args.check:
        args.events = min(args.events, 20000)
        args.windows = min(args.windows, 3)
    out = run(args.events, args.shards, args.windows)
    print(json.dumps(out))
    if "skipped" in out:
        return 0  # no compiler: the engine falls back to Python anyway
    if args.check and out["speedup"] < 1.0:
        print("route_micro: native partitioned routing is not faster "
              "than the python route loop", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
