"""Hour-scale endurance soak with asserted ceilings (VERDICT r2 #8).

Real topology (native mock apiserver process + engine process + this
monitor): N nodes heartbeat at a fast interval while a modest pod churn
keeps transitions flowing. The engine's f32 epoch is shrunk via
KWOK_TPU_REBASE_AFTER so several epoch rebases land inside the run, and
the monitor samples engine RSS + counters throughout. At the end it
asserts:
  - >= --min-rebases epoch rebases observed (kwok_epoch_rebases_total)
  - heartbeat delivery >= --hb-floor of line rate over the WHOLE run
  - RSS slope ~ 0: the last-quarter mean RSS within --rss-tolerance of
    the second-quarter mean (the first quarter is warmup)
Prints ONE JSON line; exit 1 if any ceiling is violated.

Usage (the SOAK_r03.json entry runs):
    python benchmarks/endurance.py --nodes 2000 --pods 6000 \
        --heartbeat-interval 2 --duration 3600 --rebase-after 1200
Short smoke (CI): --duration 120 --rebase-after 30
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import urllib.parse
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = os.environ.get("KWOK_TPU_SOAK_PLATFORM", "cpu")

from benchmarks.soak import _child_env, _scrape_metrics, _wait_http  # noqa: E402


def _rss_mb(pid: int) -> float:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=2000)
    p.add_argument("--pods", type=int, default=6000)
    p.add_argument("--heartbeat-interval", type=float, default=2.0)
    p.add_argument("--duration", type=float, default=3600.0)
    p.add_argument("--rebase-after", type=float, default=1200.0,
                   help="KWOK_TPU_REBASE_AFTER for the engine process")
    p.add_argument("--min-rebases", type=int, default=2)
    p.add_argument("--hb-floor", type=float, default=0.99)
    p.add_argument("--rss-tolerance", type=float, default=0.05,
                   help="allowed relative RSS growth, last vs second quarter")
    p.add_argument("--churn-every", type=float, default=60.0,
                   help="every N seconds delete+recreate --churn-pods pods")
    p.add_argument("--churn-pods", type=int, default=50)
    p.add_argument("--sample-every", type=float, default=20.0)
    p.add_argument("--tick-interval", type=float, default=0.02)
    args = p.parse_args()

    from kwok_tpu import native
    from kwok_tpu.kwokctl import netutil

    logdir = os.environ.get("KWOK_TPU_SOAK_LOGDIR", "/tmp/kwok-tpu-endurance")
    os.makedirs(logdir, exist_ok=True)
    procs: list[subprocess.Popen] = []
    try:
        api_port = netutil.get_unused_port()
        url = f"http://127.0.0.1:{api_port}"
        apiserver_bin = native.apiserver_binary()
        api_cmd = (
            [apiserver_bin, "--port", str(api_port)]
            if apiserver_bin
            else [sys.executable, "-m", "kwok_tpu.edge.mockserver",
                  "--port", str(api_port)]
        )
        api_log = open(os.path.join(logdir, "apiserver.log"), "wb")
        procs.append(subprocess.Popen(
            api_cmd, env=_child_env(), stdout=api_log, stderr=api_log
        ))
        _wait_http(url, "/healthz", timeout=60.0)

        metrics_port = netutil.get_unused_port()
        metrics_url = f"http://127.0.0.1:{metrics_port}"
        eng_env = _child_env()
        eng_env["KWOK_TPU_REBASE_AFTER"] = str(args.rebase_after)
        eng_log = open(os.path.join(logdir, "engine.log"), "wb")
        engine = subprocess.Popen(
            [sys.executable, "-m", "kwok_tpu.kwok",
             "--master", url,
             "--manage-all-nodes", "true",
             "--tick-interval", str(args.tick_interval),
             "--heartbeat-interval", str(args.heartbeat_interval),
             "--initial-capacity",
             str(max(4096, args.pods + args.churn_pods, args.nodes)),
             "--server-address", f"127.0.0.1:{metrics_port}"],
            env=eng_env, stdout=eng_log, stderr=eng_log,
        )
        procs.append(engine)
        # readiness (200 only after engine warm-up), not liveness
        _wait_http(metrics_url, "/readyz", timeout=120.0)

        def req(path, obj=None, method=None):
            data = json.dumps(obj).encode() if obj is not None else None
            r = urllib.request.Request(url + path, data=data, method=method)
            if data is not None:
                r.add_header("Content-Type", "application/json")
            with urllib.request.urlopen(r, timeout=10) as resp:
                return resp.read()

        for n in range(args.nodes):
            req("/api/v1/nodes", {
                "apiVersion": "v1", "kind": "Node",
                "metadata": {"name": f"en-{n}"}}, method="POST")
        for i in range(args.pods):
            req("/api/v1/namespaces/default/pods", {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"ep-{i}", "namespace": "default"},
                "spec": {"nodeName": f"en-{i % args.nodes}",
                         "containers": [{"name": "c", "image": "i"}]},
            }, method="POST")

        # wait for full steady state before the measured window opens
        def running() -> int:
            q = urllib.parse.quote("status.phase=Running")
            doc = json.loads(req(f"/api/v1/pods?fieldSelector={q}&limit=1"))
            return len(doc["items"]) + int(
                (doc["metadata"] or {}).get("remainingItemCount") or 0
            )

        deadline = time.monotonic() + 300
        while running() < args.pods:
            if time.monotonic() > deadline:
                raise SystemExit("pods never reached steady state")
            time.sleep(1.0)

        m0 = _scrape_metrics(metrics_url)
        hb0 = m0.get("kwok_heartbeats_total", 0)
        t0 = time.monotonic()
        samples = []  # (t, rss_mb, heartbeats_total, rebases_total)
        next_churn = t0 + args.churn_every
        churn_gen = 0
        while True:
            now = time.monotonic()
            if now - t0 >= args.duration:
                break
            m = _scrape_metrics(metrics_url)
            samples.append((
                now - t0,
                _rss_mb(engine.pid),
                m.get("kwok_heartbeats_total", 0),
                m.get("kwok_epoch_rebases_total", 0),
            ))
            if engine.poll() is not None:
                raise SystemExit("engine process died mid-run")
            if now >= next_churn:
                # graceful delete + recreate a block of pods: the full
                # delete->finalize->recreate->Running path stays exercised
                blocks = max(args.pods // max(args.churn_pods, 1), 1)
                base = churn_gen % blocks
                for i in range(args.churn_pods):
                    idx = base * args.churn_pods + i
                    if idx >= args.pods:
                        break
                    req(f"/api/v1/namespaces/default/pods/ep-{idx}",
                        {"gracePeriodSeconds": 1}, method="DELETE")
                time.sleep(3.0)
                for i in range(args.churn_pods):
                    idx = base * args.churn_pods + i
                    if idx >= args.pods:
                        break
                    body = {
                        "apiVersion": "v1", "kind": "Pod",
                        "metadata": {"name": f"ep-{idx}",
                                     "namespace": "default"},
                        "spec": {"nodeName": f"en-{idx % args.nodes}",
                                 "containers": [{"name": "c", "image": "i"}]},
                    }
                    # the graceful delete may still be finalizing under
                    # load: retry 409 AlreadyExists until the engine's
                    # strip+delete lands (an hour-scale rig must not die
                    # on one slow churn boundary)
                    for attempt in range(40):
                        try:
                            req("/api/v1/namespaces/default/pods", body,
                                method="POST")
                            break
                        except urllib.error.HTTPError as e:
                            if e.code != 409 or attempt == 39:
                                raise
                            time.sleep(0.5)
                churn_gen += 1
                next_churn += args.churn_every
            time.sleep(args.sample_every)

        elapsed = time.monotonic() - t0
        m1 = _scrape_metrics(metrics_url)
        hb_total = m1.get("kwok_heartbeats_total", 0) - hb0
        line_rate = args.nodes / args.heartbeat_interval
        hb_delivery = hb_total / (line_rate * elapsed)
        rebases = int(m1.get("kwok_epoch_rebases_total", 0))

        n_s = len(samples)
        if n_s >= 8:
            # second-quarter mean vs last-quarter mean (first quarter is
            # warmup)
            q = n_s // 4
            ref_s, last_s = samples[q:2 * q], samples[3 * q:]
        else:
            # too few samples for quartiles: halves, so short smokes can't
            # divide by an empty window
            ref_s = samples[: max(n_s // 2, 1)]
            last_s = samples[n_s // 2:] or samples[-1:]
        rss_ref = sum(s[1] for s in ref_s) / len(ref_s)
        rss_last = sum(s[1] for s in last_s) / len(last_s)
        rss_growth = (rss_last - rss_ref) / max(rss_ref, 1e-9)

        ok_rebases = rebases >= args.min_rebases
        ok_hb = hb_delivery >= args.hb_floor
        ok_rss = rss_growth <= args.rss_tolerance
        print(json.dumps({
            "metric": (
                f"endurance: {args.nodes} nodes x {args.pods} pods, "
                f"{elapsed:.0f}s steady state, heartbeat every "
                f"{args.heartbeat_interval}s, churn "
                f"{args.churn_pods}/{args.churn_every:.0f}s, "
                f"rebase epoch every {args.rebase_after:.0f}s"
            ),
            "elapsed_s": round(elapsed, 1),
            "heartbeats_total": int(hb_total),
            "heartbeat_delivery": round(hb_delivery, 4),
            "heartbeat_floor": args.hb_floor,
            "epoch_rebases": rebases,
            "min_rebases": args.min_rebases,
            "rss_ref_mb": round(rss_ref, 1),
            "rss_last_mb": round(rss_last, 1),
            "rss_growth": round(rss_growth, 4),
            "rss_tolerance": args.rss_tolerance,
            "churn_cycles": churn_gen,
            "pass": ok_rebases and ok_hb and ok_rss,
            "failures": [
                name
                for ok, name in ((ok_rebases, "rebases"), (ok_hb, "heartbeats"),
                                 (ok_rss, "rss"))
                if not ok
            ],
        }))
        return 0 if (ok_rebases and ok_hb and ok_rss) else 1
    finally:
        # engine (procs[-1]) before its apiserver: see soak.py teardown
        for proc in reversed(procs):
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    sys.exit(main())
