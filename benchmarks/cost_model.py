"""Per-process cost tables + a predicted pods/s-vs-cores curve.

The round-4 soak roofline showed a 1-core host splits its CPU roughly
half engine / half apiservers and concluded "100k pods/s is a multi-core
statement" — but never MODELED it. This rig measures the microscopic
costs that statement is made of and assembles them:

1. engine per-event CPU: survivor ADDED (full row init), echo MODIFIED
   (fingerprint drop), batch parse, emit render per patch — measured
   in-process against the real ingest/emit code.
2. apiserver per-op CPU: create / status-patch / patch-with-watchers —
   pump-loading the standalone C++ apiserver and sampling its /proc
   stat around each batch (the round-4 8.5us/op probe, now a tool).
3. rig per-request CPU: what the load generator itself burns per issued
   request (pump path).

Model: a pod's life in the homogeneous soak costs
    engine:    survivor + echo + emit + pump-syscall share
    apiserver: create + bind-patch + status-patch + watch fan-out
    rig:       2 pump requests (create + bind)
On 1 core every microsecond serializes: pods/s = 1e6 / sum. On N cores
the processes pipeline and the slowest STAGE bounds throughput: the
engine's tick thread is one serial lane (its pump/executor work offloads
to spare cores), each apiserver is a lane (M members spread their share),
the rig is a lane. Predictions are printed for 1..32 cores, and the
1-core prediction is validated against a measured soak number when one
is supplied (--measured).

Prints ONE JSON line; exits nonzero if the validation misses by more
than --tolerance (default 0.35 — microbench-vs-soak composition error;
the point is the structure of the model, not 3-digit precision).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

_CLK = os.sysconf("SC_CLK_TCK")


def _proc_cpu_s(pid: int) -> float:
    with open(f"/proc/{pid}/stat", "rb") as f:
        parts = f.read().rsplit(b") ", 1)[-1].split()
    return (int(parts[11]) + int(parts[12])) / _CLK


def _pod_line(i: int, type_: str = "ADDED", rv: int = 100) -> bytes:
    return json.dumps({
        "type": type_,
        "object": {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"cm-{i}", "namespace": "default",
                         "resourceVersion": str(rv + i),
                         "creationTimestamp": "2026-07-30T00:00:00Z",
                         "uid": f"u{i}"},
            "spec": {"nodeName": "cm-node-0",
                     "containers": [{"name": "c", "image": "x"}]},
            "status": {"phase": "Pending"},
        },
    }, separators=(",", ":")).encode()


def engine_costs(n: int, trials: int) -> dict:
    """In-process µs/event for the real ingest + emit code paths."""
    from kwok_tpu.engine import ClusterEngine, EngineConfig
    from tests.fake_apiserver import FakeKube

    lines = [_pod_line(i) for i in range(n)]
    m_lines = [_pod_line(i, "MODIFIED", 300000) for i in range(n)]

    surv, echo, emit, parse, route = [], [], [], [], []
    for _ in range(trials):
        eng = ClusterEngine(FakeKube(), EngineConfig(
            manage_all_nodes=True, initial_capacity=n + 128))
        eng._ingest("nodes", "ADDED", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": "cm-node-0"}})
        # batch parse alone
        t0 = time.perf_counter()
        batch = eng._batch_parser.parse_raw_batch(lines)
        parse.append(1e6 * (time.perf_counter() - t0) / n)
        del batch
        # the ROUTER's serial term under native pre-partitioned routing:
        # one C parse+partition call + the per-lane sub-batch handoff
        # (engine/lanes.py route_batch) — everything the router thread
        # pays per window; the lanes pay record materialization in
        # parallel, which survivor/echo below charge to the lane term
        import queue as _q

        from kwok_tpu.engine.lanes import iter_recb_items

        sinks = [_q.SimpleQueue() for _ in range(8)]
        t0 = time.perf_counter()
        b = eng._batch_parser.parse_raw_batch(lines, kind="pods",
                                              n_shards=8)
        tmono = time.monotonic()
        for li, _count, item in iter_recb_items("pods", b, tmono):
            sinks[li].put(item)
        route.append(1e6 * (time.perf_counter() - t0) / n)
        del b, sinks
        # survivor: ADDED -> full row init
        raw_buf: dict = {}
        t0 = time.perf_counter()
        for ln in lines:
            eng._drain_apply(("pods", "RAW", ln, 0.0), raw_buf)
        eng._drain_flush(raw_buf)
        surv.append(1e6 * (time.perf_counter() - t0) / n)
        # echo: MODIFIED with unchanged fingerprints -> dropped
        raw_buf = {}
        t0 = time.perf_counter()
        for ln in m_lines:
            eng._drain_apply(("pods", "RAW", ln, 0.0), raw_buf)
        eng._drain_flush(raw_buf)
        echo.append(1e6 * (time.perf_counter() - t0) / n)
        # emit render: the batch path's Python body-building + C++ render
        # + fingerprints, with the send swallowed (we're costing the
        # engine's CPU, not the network)
        eng.pods.phase_h[: n] = eng._pod_phase_ids["Running"]

        class _NullPump:
            def send(self, reqs):
                import numpy as np
                return np.full(len(reqs), 200, np.int32)

            def emit_spliced(self, native_mod, kw):
                # fused-path representative with the send swallowed: the
                # render+fingerprint C call runs for real, statuses come
                # back 200 — so emit_render_us measures the engine CPU of
                # the ISSUE 14 template path (the wire syscalls are the
                # pump term, measured by emit_pump_costs)
                res = native_mod.emit_pods(**kw)
                if res is None:
                    return None
                bodies, fps, status, need = res
                status[:] = 200
                return bodies, fps, status, need

            def close(self):
                pass

        eng._pump = _NullPump()
        eng._pump_tried = True
        eng._pump_base = ""
        idxs = [eng.pods.pool.lookup(("default", f"cm-{i}"))
                for i in range(n)]
        idxs = [i for i in idxs if i is not None]
        # per-term GC isolation (r08): the 20k-record ingest above (and
        # the previous trial's dropped engine) leaves a collection due
        # that fires INSIDE this window otherwise — ~2µs/pod of ingest
        # garbage mis-attributed to emit, and the dominant trial-to-trial
        # variance (4.3..6.8µs/pod swings on an idle host). survivor/echo
        # pay their own GC, triggered by their own allocation, as before.
        import gc
        gc.collect()
        t0 = time.perf_counter()
        eng._emit_pods_native(eng.pods, idxs)
        emit.append(1e6 * (time.perf_counter() - t0) / max(1, len(idxs)))
    # flush staging + scatter: the ingest writes' path to device state
    flushes = []
    for _ in range(trials):
        eng = ClusterEngine(FakeKube(), EngineConfig(
            manage_all_nodes=True, initial_capacity=n + 128))
        fused = eng._get_fused()
        for k in (eng.nodes, eng.pods):
            k.state = fused.place(k.state)
        for i in range(n):
            eng.pods.buffer.stage_init(i, True, 0, 0, 3, False)
        t0 = time.perf_counter()
        eng.pods.state = eng.pods.buffer.flush(eng.pods.state)
        import jax

        jax.block_until_ready(eng.pods.state.active)
        flushes.append(1e6 * (time.perf_counter() - t0) / n)
    # per-tick kernel CPU at this capacity (CPU backend: the tick math
    # competes for the core; on a TPU it offloads — the model carries it
    # as a separate lane for exactly that reason). Rows must be ACTIVE:
    # an empty pool skips the dispatch entirely.
    eng = ClusterEngine(FakeKube(), EngineConfig(
        manage_all_nodes=True, initial_capacity=n + 128))
    fused = eng._get_fused()
    for k in (eng.nodes, eng.pods):
        k.state = fused.place(k.state)
    for i in range(n):
        eng.pods.pool.acquire(("default", f"k-{i}"))
        eng.pods.buffer.stage_init(i, True, 0, 0, 0, False)
    eng.tick_once()  # flush + compile
    ticks = []
    for _ in range(max(3, trials)):
        t0 = time.perf_counter()
        eng.tick_once()
        ticks.append(1e3 * (time.perf_counter() - t0))
    return {
        "survivor_added_us": round(statistics.median(surv), 2),
        "echo_modified_us": round(statistics.median(echo), 2),
        "batch_parse_us": round(statistics.median(parse), 2),
        "route_batch_us": round(statistics.median(route), 2),
        "emit_render_us": round(statistics.median(emit), 2),
        # ISSUE 14 disclosure: which emit body the render term measured
        "emit_native_templates": eng._emit_tpl is not None,
        "flush_staged_row_us": round(statistics.median(flushes), 2),
        "tick_kernel_ms_at_capacity": round(statistics.median(ticks), 2),
        "capacity": n + 128,
        "events_per_trial": n,
        "trials": trials,
    }


def emit_pump_costs(n: int, trials: int) -> dict:
    """The engine-side pump term of ISSUE 14, measured fresh: µs of THIS
    process's CPU per status patch for (a) the old shape — Python request
    tuples marshalled into pump.send — and (b) the fused template call
    (render+fingerprint+send in one C invocation), with the render-only
    CPU subtracted so `emit_pump_us` is the per-patch cost the send adds
    on top of the already-counted emit_render_us."""
    import numpy as np

    from kwok_tpu import native
    from kwok_tpu.kwokctl import netutil
    from kwok_tpu.models import (
        compile_emit_templates,
        compile_rules,
        default_pod_rules,
    )
    from kwok_tpu.models.lifecycle import ResourceKind

    bin_ = native.apiserver_binary()
    if not bin_:
        return {"skipped": "no native apiserver binary"}
    port = netutil.get_unused_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [bin_, "--port", str(port)], env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        from benchmarks.soak import _wait_http

        _wait_http(f"http://127.0.0.1:{port}", "/healthz", timeout=30)
        pump = native.Pump("127.0.0.1", port, nconn=2)
        creates = [
            ("POST", "/api/v1/namespaces/default/pods", json.dumps({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"pp-{i}", "namespace": "default"},
                "spec": {"nodeName": "n0",
                         "containers": [{"name": "c", "image": "x"}]},
            }, separators=(",", ":")).encode())
            for i in range(n)
        ]
        st = pump.send(creates)
        if not ((st >= 200) & (st < 300)).all():
            return {"skipped": "apiserver rejected the seed creates"}
        ptab = compile_rules(default_pod_rules(), ResourceKind.POD)
        tpl = compile_emit_templates(ptab)
        et = native.EmitTable(tpl)
        t = int(tpl.phase_tpl[ptab.space.phase_id("Running")])
        ids = np.full(n, t, np.int32)
        conds = np.full(n, 7, np.uint32)
        hosts = [b"10.0.0.1"] * n
        ips = [f"10.244.2.{i % 250}".encode() for i in range(n)]
        starts = [b"2026-07-30T00:00:00Z"] * n
        ctrs = [b"c\x1fx"] * n
        ictrs = [b""] * n
        now = b"2026-07-30T00:00:01Z"
        paths = [
            f"/api/v1/namespaces/default/pods/pp-{i}".encode()
            for i in range(n)
        ]
        ctype = "application/strategic-merge-patch+json"
        marshal, fused, render = [], [], []
        for _ in range(trials):
            # (a) old shape: request tuples + pump.send marshalling
            bodies, _f, _s, _need = native.emit_pods(
                et, ids, conds, hosts, ips, starts, ctrs, ictrs, now)
            c0 = time.process_time()
            reqs = [
                ("PATCH", p.decode() + "/status", b, ctype)
                for p, b in zip(paths, bodies)
            ]
            pump.send(reqs)
            marshal.append(1e6 * (time.process_time() - c0) / n)
            # (b) fused render+send, then render-only to subtract
            c0 = time.process_time()
            native.emit_pods(
                et, ids, conds, hosts, ips, starts, ctrs, ictrs, now,
                pump=pump, paths=paths)
            fused.append(1e6 * (time.process_time() - c0) / n)
            c0 = time.process_time()
            native.emit_pods(
                et, ids, conds, hosts, ips, starts, ctrs, ictrs, now)
            render.append(1e6 * (time.process_time() - c0) / n)
        pump.close()
        med = statistics.median
        return {
            "marshal_send_us": round(med(marshal), 2),
            "fused_send_us": round(med(fused), 2),
            "render_only_us": round(med(render), 2),
            "emit_pump_us": round(
                max(0.0, med(fused) - med(render)), 2
            ),
            "ops_per_batch": n,
            "trials": trials,
        }
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def watch_read_costs(n: int, trials: int) -> dict:
    """µs CPU per watch line on the consumer side: chunked-HTTP line
    iteration + the ingest-queue put (the engine's watch threads)."""
    from kwok_tpu import native
    from kwok_tpu.edge.httpclient import HttpKubeClient
    from kwok_tpu.kwokctl import netutil

    bin_ = native.apiserver_binary()
    if not bin_:
        return {"skipped": "no native apiserver binary"}
    port = netutil.get_unused_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [bin_, "--port", str(port)], env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        from benchmarks.soak import _wait_http

        _wait_http(f"http://127.0.0.1:{port}", "/healthz", timeout=30)
        pump = native.Pump("127.0.0.1", port, nconn=2)
        client = HttpKubeClient.from_kubeconfig(
            None, f"http://127.0.0.1:{port}")
        import queue as _q

        vals = []
        native_mode = None
        for t in range(trials):
            w = client.watch("pods")
            # the engine's actual default: native batched reader when
            # available (one queue item per packed batch), else the
            # per-line Python path — cost whichever the engine would run
            reader = w.native_reader()
            if native_mode is None:
                native_mode = reader is not None
            reqs = [
                ("POST", "/api/v1/namespaces/default/pods", json.dumps({
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"w-{t}-{i}",
                                 "namespace": "default"},
                    "spec": {"nodeName": "n0",
                             "containers": [{"name": "c", "image": "x"}]},
                }, separators=(",", ":")).encode())
                for i in range(n)
            ]
            pump.send(reqs)
            qq: "_q.SimpleQueue" = _q.SimpleQueue()
            got = 0
            c0 = time.process_time()
            if reader is not None:
                deadline = time.monotonic() + 60.0
                while got < n:
                    out = reader.read_batch(timeout_s=5.0)
                    if out is None or reader.error is not None:
                        break
                    if time.monotonic() > deadline:
                        break  # stalled stream: fail loudly below
                    buf, off = out
                    if len(off) > 1:
                        qq.put(("pods", "RAWB", (buf, off),
                                time.monotonic()))
                        got += len(off) - 1
            else:
                for line in w.raw_lines():
                    qq.put(("pods", "RAW", line, time.monotonic()))
                    got += 1
                    if got >= n:
                        break
            if got < n:
                # a short trial must fail loudly, not deflate the per-line
                # cost by dividing a partial read by the full n
                raise SystemExit(
                    f"watch probe: stream ended/stalled at {got}/{n} lines "
                    f"(reader error: {getattr(reader, 'error', None)!r})"
                )
            # the native path reads whole batches and can overshoot n:
            # divide by the lines actually processed
            vals.append(1e6 * (time.process_time() - c0) / got)
            if reader is not None:
                reader.close()
            w.stop()
        pump.close()
        client.close()
        return {"watch_line_us": round(statistics.median(vals), 2),
                "native_reader": bool(native_mode),
                "lines_per_trial": n, "trials": trials}
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def apiserver_costs(n: int, trials: int) -> dict:
    """µs CPU per op for the standalone C++ apiserver (pump-loaded)."""
    from kwok_tpu import native
    from kwok_tpu.kwokctl import netutil

    bin_ = native.apiserver_binary()
    if not bin_:
        return {"skipped": "no native apiserver binary"}
    port = netutil.get_unused_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [bin_, "--port", str(port)], env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        from benchmarks.soak import _wait_http

        _wait_http(f"http://127.0.0.1:{port}", "/healthz", timeout=30)
        pump = native.Pump("127.0.0.1", port, nconn=2)

        def batch_cpu(reqs) -> float:
            c0 = _proc_cpu_s(proc.pid)
            st = pump.send(reqs)
            ok = int(((st >= 200) & (st < 300)).sum())
            if ok < len(reqs) * 0.99:
                raise SystemExit(
                    f"apiserver probe: only {ok}/{len(reqs)} ok")
            return 1e6 * (_proc_cpu_s(proc.pid) - c0) / len(reqs)

        def pod_body(i, gen):
            return json.dumps({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"pr-{gen}-{i}",
                             "namespace": "default"},
                "spec": {"containers": [{"name": "c", "image": "x"}]},
                "status": {"phase": "Pending"},
            }, separators=(",", ":")).encode()

        creates, binds, patches, patches_w = [], [], [], []
        for t in range(trials):
            creates.append(batch_cpu([
                ("POST", "/api/v1/namespaces/default/pods",
                 pod_body(i, t)) for i in range(n)]))
            binds.append(batch_cpu([
                ("PATCH", f"/api/v1/namespaces/default/pods/pr-{t}-{i}",
                 b'{"spec":{"nodeName":"cm-node-0"}}',
                 "application/merge-patch+json")
                for i in range(n)]))
            patches.append(batch_cpu([
                ("PATCH", f"/api/v1/namespaces/default/pods/pr-{t}-{i}/status",
                 b'{"status":{"phase":"Running"}}',
                 "application/strategic-merge-patch+json")
                for i in range(n)]))
        # fan-out cost: same patches with 2 live CONSUMING watchers — a
        # watcher that never reads would let the event writes defer into
        # socket buffers and under-measure the fan-out
        import http.client
        import threading

        watchers = []
        stop_w = threading.Event()
        for _ in range(2):
            c = http.client.HTTPConnection("127.0.0.1", port)
            c.request("GET", "/api/v1/pods?watch=true")
            r = c.getresponse()

            def drain_stream(r=r):
                try:
                    while not stop_w.is_set() and r.read(65536):
                        pass
                except Exception:
                    pass

            th = threading.Thread(target=drain_stream, daemon=True)
            th.start()
            watchers.append((c, th))
        for t in range(trials):
            patches_w.append(batch_cpu([
                ("PATCH", f"/api/v1/namespaces/default/pods/pr-{t}-{i}/status",
                 b'{"status":{"phase":"Succeeded"}}',
                 "application/strategic-merge-patch+json")
                for i in range(n)]))
        stop_w.set()
        # shutdown() first: close() needs the response buffer lock, which
        # a drain thread blocked in recv() holds — shutdown wakes it with
        # EOF, then join, then close (observed deadlock otherwise)
        import socket as _socket

        for c, _th in watchers:
            try:
                c.sock.shutdown(_socket.SHUT_RDWR)
            except Exception:
                pass
        for c, th in watchers:
            th.join(timeout=5)
            c.close()
        # progress-poll cost at the FULL store size: the rig polls
        # fieldSelector=status.phase=Running&limit=1 which must count
        # every match for remainingItemCount — an O(store) scan whose
        # soak share the per-op probes above cannot see
        store_size = len(creates) * n  # pods created across trials
        import http.client as _hc

        polls = []
        pc = _hc.HTTPConnection("127.0.0.1", port)
        path = ("/api/v1/pods?fieldSelector=status.phase%3DRunning"
                "&limit=1")
        for _ in range(3):
            c0 = _proc_cpu_s(proc.pid)
            n_polls = 30
            for _i in range(n_polls):
                pc.request("GET", path)
                pc.getresponse().read()
            polls.append(1e6 * (_proc_cpu_s(proc.pid) - c0) / n_polls)
        pc.close()
        pump.close()
        med = statistics.median
        p, pw = med(patches), med(patches_w)
        return {
            "create_pod_us": round(med(creates), 2),
            "bind_patch_us": round(med(binds), 2),
            "patch_status_us": round(p, 2),
            "patch_status_with_2_watchers_us": round(pw, 2),
            "watch_fanout_per_watcher_us": round(max(0.0, (pw - p) / 2), 2),
            "poll_running_count_us": round(med(polls), 2),
            "poll_store_pods": store_size,
            "ops_per_batch": n,
            "trials": trials,
        }
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def rig_costs(n: int, trials: int) -> dict:
    """µs of THIS process's CPU per pump-issued request (the loader's
    own cost: body building + pump syscalls)."""
    from kwok_tpu import native
    from kwok_tpu.kwokctl import netutil

    bin_ = native.apiserver_binary()
    if not bin_:
        return {"skipped": "no native apiserver binary"}
    port = netutil.get_unused_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [bin_, "--port", str(port)], env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        from benchmarks.soak import _wait_http

        _wait_http(f"http://127.0.0.1:{port}", "/healthz", timeout=30)
        pump = native.Pump("127.0.0.1", port, nconn=2)
        vals = []
        for t in range(trials):
            c0 = time.process_time()
            reqs = [
                ("POST", "/api/v1/namespaces/default/pods", json.dumps({
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"rig-{t}-{i}",
                                 "namespace": "default"},
                    "spec": {"containers": [{"name": "c", "image": "x"}]},
                }, separators=(",", ":")).encode())
                for i in range(n)
            ]
            pump.send(reqs)
            vals.append(1e6 * (time.process_time() - c0) / n)
        pump.close()
        return {"issue_request_us": round(statistics.median(vals), 2),
                "ops_per_batch": n, "trials": trials}
    finally:
        proc.terminate()
        proc.wait(timeout=10)


_CONTENTION_SNIPPET = r"""
import json, time
line = json.dumps({"type":"ADDED","object":{"metadata":{"name":"x",
  "namespace":"default","resourceVersion":"1"},"spec":{"nodeName":"n",
  "containers":[{"name":"c","image":"i"}]},"status":{"phase":"Pending"}}})
deadline = time.perf_counter() + %f
n = 0
while time.perf_counter() < deadline:
    json.loads(line); n += 1
print(n)
"""


def _handoff_child(ring_name: str, conn) -> None:
    """proc_handoff_costs consumer: drain descriptors + ring bytes the
    way a lane process does (engine/proclanes.py child loop), acking
    SYNC barriers so the parent can prove the ring drained between
    trials."""
    from kwok_tpu.engine import shm as shm_mod

    ring = shm_mod.RawRing(ring_name)
    try:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                return
            if msg[0] == "STOP":
                return
            if msg[0] == "SYNC":
                conn.send(("ACK",))
                continue
            _op, off, ln, _bounds = msg
            ring.read(off, ln)
    finally:
        ring.close()


def proc_handoff_costs(n: int, trials: int) -> dict:
    """Parent-side cost of the process-lane handoff (ISSUE 15): the
    shared-memory ring write (raw bytes copied exactly once, never
    re-serialized) plus the (offset, length, bounds) descriptor send —
    the work ProcLaneSet._ship does per (lane, kind) window slice,
    measured against a live spawn-context consumer process. The ring is
    sized to hold one whole trial so a lagging consumer (this may run
    on a starved host) can never stall the writer into measuring the
    scheduler instead of the copy; a SYNC barrier between trials proves
    the ring drained."""
    import multiprocessing as mp

    from kwok_tpu.engine import shm as shm_mod

    per_window = 256
    windows = max(1, min(n, 20000) // per_window)
    lines = [_pod_line(i) for i in range(per_window)]
    blob = b"".join(lines)
    ring = shm_mod.RawRing(
        shm_mod.arena_name(f"handoff-{os.getpid()}"),
        (len(blob) + 4096) * windows + (1 << 20), create=True,
    )
    ctx = mp.get_context("spawn")
    parent_conn, child_conn = ctx.Pipe()
    proc = ctx.Process(
        target=_handoff_child, args=(ring.name, child_conn), daemon=True
    )
    proc.start()
    child_conn.close()
    samples = []
    try:
        for _ in range(trials):
            spent = 0.0
            for _w in range(windows):
                t0 = time.perf_counter()
                bounds = [0]
                for p in lines:
                    bounds.append(bounds[-1] + len(p))
                b = b"".join(lines)
                off = ring.try_write(b)
                if off is None:  # sizing failed: disqualify the trial
                    spent = float("nan")
                    break
                parent_conn.send(("RAWB", off, len(b), bounds))
                spent += time.perf_counter() - t0
            parent_conn.send(("SYNC",))
            parent_conn.recv()
            if spent == spent:  # not NaN
                samples.append(spent / (windows * per_window) * 1e6)
    finally:
        try:
            parent_conn.send(("STOP",))
        except (OSError, BrokenPipeError):
            pass
        proc.join(timeout=10)
        if proc.is_alive():
            proc.kill()
            proc.join(timeout=5)
        parent_conn.close()
        ring.close(unlink=True)
    if not samples:
        return {"error": "every trial overflowed the sized ring"}
    return {
        "proc_handoff_us": round(statistics.median(samples), 3),
        "events_per_window": per_window,
        "windows": windows,
        "trials": len(samples),
        "bytes_per_event": round(len(blob) / per_window, 1),
    }


def contention_factor(procs: int = 6, seconds: float = 2.0) -> dict:
    """The multi-process tax the per-process probes cannot see: run the
    same fixed CPU workload in 1 process, then in `procs` concurrent
    processes (the soak's process count), and compare per-process
    throughput. On an ideal scheduler the concurrent run does 1/procs
    the work each with zero loss; the shortfall is context-switch +
    cache-thrash overhead, applied to the model's 1-core total."""
    def run(n_procs: int) -> float:
        script = _CONTENTION_SNIPPET % seconds
        ps = [
            subprocess.Popen(
                [sys.executable, "-c", script], stdout=subprocess.PIPE)
            for _ in range(n_procs)
        ]
        total = 0
        for p in ps:
            out, _ = p.communicate(timeout=seconds * (n_procs + 4))
            total += int(out.strip() or 0)
        return total / seconds  # ops/s across all processes

    solo = run(1)
    crowd = run(procs)
    factor = solo / max(1.0, crowd)
    return {
        "processes": procs,
        "solo_ops_per_s": round(solo, 0),
        "concurrent_ops_per_s_total": round(crowd, 0),
        "factor": round(max(1.0, factor), 3),
    }


def build_model(eng: dict, api: dict, rig: dict, watch: dict,
                members: int, ticks_per_kpod: float = 0.2,
                contention: float = 1.0, drain_shards: int = 1,
                max_drain_shards: int = 0,
                gil_overlap: float = 1.0) -> dict:
    """Assemble per-pod costs and the pods/s-vs-cores curve.

    A pod's life in the homogeneous soak:
      rig:       create + bind                       (2 pump requests)
      apiserver: create + bind patch + status patch, each fanned out to
                 the engine's pod watch (3 fan-outs)
      engine:    2 watch lines read (ADDED + echo) + survivor ingest +
                 echo drop + flush of its staged row + emit render +
                 pump syscalls for its patch + its share of tick kernel
                 CPU (per-TICK cost at capacity, amortized over the pods
                 a tick retires; on a TPU this lane leaves the host)
    """
    # The rig's progress polls are an O(store) count per poll (the
    # remainingItemCount contract). Per-pod share = polls x per-store-pod
    # cost / pods, which depends on the poll interval and phase wall —
    # self-referential, so it is reported as a DIAGNOSTIC, not summed:
    # at the soak's 1s interval and a ~7s phase it is ~3-6us/pod, inside
    # the model's tolerance; at sub-second intervals or much larger
    # stores it would dominate (it scales with store size, not load).
    poll_per_store_pod = (
        api.get("poll_running_count_us", 0.0)
        / max(1, api.get("poll_store_pods", 1))
    )
    # the lane-split pipeline math is shared with bench.py's BENCH-json
    # rider — ONE source of truth (benchmarks/lane_model.py); contention
    # is a MEASURED diagnostic: on this VM the probe shows no
    # multi-process tax (concurrent throughput >= solo — burstable vCPU),
    # so it multiplies as ~1.0; kept in the model so a host where it is
    # real (a true pinned core) scales the 1-core point correctly
    from benchmarks.lane_model import lane_model

    lm = lane_model(eng, api, rig, watch, members=members,
                    contention=contention, drain_shards=drain_shards,
                    ticks_per_kpod=ticks_per_kpod,
                    max_drain_shards=max_drain_shards,
                    gil_overlap=gil_overlap)
    from kwok_tpu.config.types import DEFAULT_MAX_DRAIN_SHARDS

    cap = max_drain_shards if max_drain_shards > 0 else (
        DEFAULT_MAX_DRAIN_SHARDS
    )
    auto_txt = f"auto (min(cores, {cap}))"
    out = {
        "per_pod_us": lm["per_pod_us"],
        "poll_us_per_store_pod": round(poll_per_store_pod, 3),
        "drain_shards": (
            drain_shards if drain_shards > 0 else auto_txt
        ),
        "predicted_pods_per_s_by_cores":
            lm["predicted_pods_per_s_by_cores"],
        "predicted_pods_per_s_by_cores_single_lane":
            lm["predicted_pods_per_s_by_cores_single_lane"],
        "assumptions": (
            "homogeneous soak pod = rig(create+bind) + "
            "apiserver(create+bind-patch+status-patch+3 fanouts) + "
            "engine(2 watch lines + survivor + echo + flush + emit + "
            "pump + tick-kernel share at "
            f"{ticks_per_kpod} ticks/kpod); N-core = slowest lane "
            "(engine drain+emit hash-partitioned over "
            f"{drain_shards if drain_shards > 0 else auto_txt} "
            "shard lanes; with route_batch_us measured the router lane is "
            "the native parse+partition+handoff, the staged-row flush is "
            "the coordinator tick thread's own lane, and pump sends ride "
            "per-lane connection groups; apiservers split across "
            f"max({members}, cores//2) members (the horizontally scaled "
            "tier, sized like the soak topology), rig across 4 loaders; "
            "the tick-kernel lane leaves the host entirely when a TPU is "
            "attached)"
        ),
    }
    if "predicted_pods_per_s_by_cores_proc_lanes" in lm:
        out["predicted_pods_per_s_by_cores_proc_lanes"] = lm[
            "predicted_pods_per_s_by_cores_proc_lanes"
        ]
        out["proc_lanes_note"] = (
            "process lanes (--lane-procs, engine/proclanes.py): the "
            "parent router lane pays parse+partition + the MEASURED "
            "shm-ring+descriptor handoff (proc_handoff_us); each lane "
            "process runs the whole single-lane apply — its slice's "
            "re-parse, drain+emit, flush, CPU tick kernel, and pump — "
            "on a true core at full overlap (no GIL). The threaded "
            "curve honors gil_overlap where supplied: the GIL-holding "
            "(1-g) share of per-lane apply serializes across lanes "
            "(Amdahl, capped at 1/(1-g); LANES r07 measured 2.2x from "
            "4 threaded lanes => g~=0.73, a ~3.7x ceiling); the proc "
            "curve's kernel share stays on the host — children are "
            "host-CPU engines, per-child TPU placement is future work"
        )
    return out


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--events", type=int, default=20000)
    p.add_argument("--trials", type=int, default=5)
    p.add_argument("--members", type=int, default=4)
    p.add_argument("--measured", type=float, default=0.0,
                   help="measured 1-core homogeneous soak pods/s to "
                   "validate the model's 1-core prediction against")
    p.add_argument("--drain-shards", type=int, default=0,
                   help="model the drain+emit lane hash-partitioned over "
                   "N shard lanes (engine --drain-shards); 0 = auto — the "
                   "engine's production default "
                   "(config.types.auto_drain_shards)")
    p.add_argument("--max-drain-shards", type=int, default=0,
                   help="cap on the AUTO lane count, mirroring the "
                   "engine's --max-drain-shards (0 = built-in default)")
    p.add_argument("--gil-overlap", type=float, default=1.0,
                   help="GIL-released fraction g of per-lane apply: "
                   "threaded lanes scale Amdahl-style, capped at 1/(1-g) "
                   "(1.0 = the legacy optimistic full-overlap curve; "
                   "LANES r07 measured 2.2x from 4 threaded lanes => "
                   "g~=0.73, a ~3.7x hard ceiling). The process-lane "
                   "curve ignores it: true cores, no GIL")
    p.add_argument("--remodel", action="append", default=[],
                   help="path to a prior COSTMODEL_r*.json: re-predict "
                   "its measured inputs under the CURRENT model and embed "
                   "the result as remodeled_<name>_inputs — the "
                   "apples-to-apples ceiling trajectory across rounds "
                   "(repeatable)")
    p.add_argument("--tolerance", type=float, default=0.6,
                   help="bottom-up microbenches vs a live multi-process "
                   "soak: the residual (federation layer, GC/allocator "
                   "churn, small-batch socket patterns) is reported "
                   "explicitly; the gate only catches a model that has "
                   "lost the right order of magnitude")
    args = p.parse_args()

    # load every --remodel input BEFORE the measurement: a typo'd path
    # must fail in milliseconds, not after minutes of microbenches whose
    # results would then be discarded unprinted
    priors: "list[tuple[str, dict]]" = []
    for path in args.remodel:
        try:
            with open(path) as f:
                priors.append((path, json.load(f)))
        except (OSError, ValueError) as e:
            print(f"--remodel {path}: {e}", file=sys.stderr)
            return 1

    eng = engine_costs(args.events, args.trials)
    # the fused-send pump term (ISSUE 14): measured against a live native
    # apiserver; folded into the engine inputs so the lane model's pump
    # lane rides the measured number instead of the rig-cost proxy — but
    # ONLY when the engine under measurement actually ran the template
    # path (KWOK_TPU_NATIVE_EMIT=0 must model the legacy marshalling,
    # not a fused send it will never make)
    emit_pump = emit_pump_costs(min(args.events, 20000), args.trials)
    if "emit_pump_us" in emit_pump and eng.get("emit_native_templates"):
        eng["emit_pump_us"] = emit_pump["emit_pump_us"]
    # the cross-process handoff term (ISSUE 15): measured against a live
    # spawned consumer; folded into the engine inputs so the lane model
    # emits the process-lane curve alongside the threaded one
    handoff = proc_handoff_costs(min(args.events, 20000), args.trials)
    if "proc_handoff_us" in handoff:
        eng["proc_handoff_us"] = handoff["proc_handoff_us"]
    api = apiserver_costs(min(args.events, 20000), args.trials)
    rig = rig_costs(min(args.events, 20000), args.trials)
    watch = watch_read_costs(min(args.events, 20000), args.trials)
    # soak process count: engine + members + rig + a loader or two
    cont = contention_factor(procs=args.members + 3)
    # 0 = auto: the curve's N-core point models the engine default on an
    # N-core host (config.types.auto_drain_shards)
    model = build_model(eng, api, rig, watch, args.members,
                        contention=cont["factor"],
                        drain_shards=args.drain_shards,
                        max_drain_shards=args.max_drain_shards,
                        gil_overlap=args.gil_overlap)
    out = {
        "metric": "cost model: per-process us CPU per op + pods/s-vs-cores",
        "engine": eng,
        "emit_pump": emit_pump,
        "proc_handoff": handoff,
        "apiserver": api,
        "rig": rig,
        "watch": watch,
        "contention": cont,
        "model": model,
    }
    for path, prior in priors:
        name = os.path.basename(path).rsplit(".", 1)[0].lower()
        try:
            remodeled = build_model(
                prior.get("engine") or {}, prior.get("apiserver") or {},
                prior.get("rig") or {}, prior.get("watch") or {},
                args.members,
                contention=(prior.get("contention") or {}).get(
                    "factor", 1.0
                ),
                drain_shards=args.drain_shards,
                max_drain_shards=args.max_drain_shards,
                gil_overlap=args.gil_overlap,
            )
        except KeyError as e:
            # a JSON that parses but is not a COSTMODEL artifact (missing
            # engine cost keys) gets the same one-line report as an
            # unreadable file, not a traceback
            print(f"--remodel {path}: missing input key {e}",
                  file=sys.stderr)
            return 1
        out[f"remodeled_{name}_inputs"] = {
            "note": (
                f"the measured per-op inputs of {os.path.basename(path)} "
                "re-predicted under the CURRENT lane model — the "
                "ceiling movement across rounds with the host removed "
                "from the comparison (the fresh measurement above ran on "
                "whatever host this round got). The delta folds in the "
                "whole current model, not just the engine refit: the "
                "auto shard cap and the members-scale-with-cores "
                "topology policy (lane_model.members_at) apply to old "
                "inputs too, so where an old curve was apiserver-bound "
                "at high core counts, part of the rise is that policy"
            ),
            **remodeled,
        }
    ok = True
    if args.measured > 0:
        pred = model["predicted_pods_per_s_by_cores"]["1"]
        err = abs(pred - args.measured) / args.measured
        ok = err <= args.tolerance
        # the bottom-up sum under-counts what only a live soak has:
        # federation-layer overhead, allocator/GC churn over a growing
        # heap, and small-batch socket patterns. Surface the residual
        # explicitly instead of hiding it in a fudge factor.
        measured_us = 1e6 / args.measured
        out["validation"] = {
            "measured_1core_pods_per_s": args.measured,
            "predicted_1core_pods_per_s": pred,
            "measured_us_per_pod": round(measured_us, 1),
            "modeled_us_per_pod": model["per_pod_us"]["total_1core"],
            "unattributed_us_per_pod": round(
                measured_us - model["per_pod_us"]["total_1core"], 1
            ),
            "relative_error": round(err, 3),
            "tolerance": args.tolerance,
            "pass": ok,
        }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
