"""Drift convergence gate: hostile wire + anti-entropy (ISSUE 10).

Three arms against the HTTP mock apiserver (oplog store), sharing one
creates-only workload:

- **control**: no faults, no auditor — the reference final state.
- **storm**: the hostile-wire fault tier corrupts the engine's real
  ingest bytes — ``wire.garble`` (byte flips/inserts in watch lines and
  LIST bodies), ``wire.truncate`` (mid-JSON cuts with no clean close),
  ``wire.dup``/``wire.stale`` (replayed and regressed-rv events) and
  ``clock.jump`` (a skewed engine clock) — while the anti-entropy
  auditor runs. The storm closes the way an outage ends (rates zeroed,
  streams cut, compaction forces the full re-list) and the engine must
  CONVERGE: final pod phases byte-identical to control, per-key patch
  order preserved, every corruption rejected-or-repaired (counted in
  ``kwok_wire_rejects_total`` / repaired by re-list+auditor — proven by
  the byte-identical end state), zero worker crashes outside
  supervision, queues drained, not degraded.
- **seeded divergence** (same storm run, post-convergence, faults off):
  the rig mutates server state *behind the engine's back* — one pod's
  status.phase silently rewound (no watch event, no rv bump) and one
  pod silently deleted (a ghost row) — and the auditor must detect
  (``kwok_drift_detected_total{reason="stale-row"|"ghost-row"}``) and
  repair (server phase re-asserted; ghost row released) within one
  audit pass of the next interval.

Artifact: ``DRIFT_r01.json``. ``--check`` (the ``make drift-check`` /
CI entry) runs a smaller workload and exits nonzero on any failed gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks.rig import (  # noqa: E402 (path bootstrap above)
    MockApiserver,
    make_node as _make_node,
    make_pod as _make_pod,
    pod_phases as _pod_phases,
    silent_delete,
    silent_patch,
    wait_until as _wait,
)

# the hostile-wire storm: every wire.* kind plus a skewed clock, rates
# sized so a ~3s churn window sees each kind fire but recovery (bounded
# integrity resyncs + the closing re-list) still converges quickly
DRIFT_SPEC = (
    "seed={seed};wire.garble=0.08;wire.truncate=0.02;wire.dup=0.10;"
    "wire.stale=0.10;clock.jump=0.5:0.3;watch.cut=0.005"
)

AUDIT_INTERVAL = 1.0

# gate bound for the seeded-divergence repair: worst case the mutation
# lands right after a pass began (one full interval of waiting), plus
# the repairing pass itself (settle re-check + repair enqueue + the
# ingest/patch round trip) — generous for 2-vCPU CI hosts
REPAIR_BOUND_S = AUDIT_INTERVAL + 3.0


def _run(pods: int, lanes: int, seed: int, storm: bool,
         timeout: float) -> dict:
    from kwok_tpu.edge.httpclient import HttpKubeClient
    from kwok_tpu.engine import ClusterEngine, EngineConfig
    from kwok_tpu.telemetry.errors import (
        wire_rejects_total,
        worker_crash_ledger,
    )

    srv = MockApiserver()
    store = srv.store
    names = [f"dp{i}" for i in range(pods)]
    nodes = [f"dn{i}" for i in range(4)]
    spec = DRIFT_SPEC.format(seed=seed) if storm else ""
    rejects0 = wire_rejects_total()
    eng = ClusterEngine(
        HttpKubeClient.from_kubeconfig(None, srv.url),
        EngineConfig(
            manage_all_nodes=True, tick_interval=0.02, drain_shards=lanes,
            faults=spec,
            audit_interval=AUDIT_INTERVAL if storm else 0.0,
        ),
    )
    out: dict = {"mode": "storm" if storm else "control"}
    t_run0 = time.time()
    eng.start()
    try:
        for n in nodes:
            store.create("nodes", _make_node(n))
        # pace the workload across the fault window so the wire tier has
        # live traffic to corrupt (a burst that converges in 200ms would
        # leave most of the storm injecting into an idle stream)
        half = pods // 2
        for n in names[:half]:
            store.create("pods", _make_pod(n, nodes[hash(n) % len(nodes)]))
        if storm:
            time.sleep(1.0)
        for n in names[half:]:
            store.create("pods", _make_pod(n, nodes[hash(n) % len(nodes)]))
        if storm:
            # let the wire tier corrupt live traffic...
            time.sleep(2.5)
            eng._faults.spec.rates.clear()
            out["faults_injected"] = eng._faults.counts()
            # ...then close the window the way an outage ends: compaction
            # + every stream cut, so recovery takes the full 410 ->
            # list+RESYNC path (events eaten by garbled lines or
            # truncated streams have no other way back)
            heal_t0 = time.time()
            store.compact()
            store.stop_watches()
        else:
            heal_t0 = time.time()

        converged = _wait(
            lambda: all(
                ph == "Running" for ph in _pod_phases(store, names).values()
            ),
            timeout,
        )
        out["converged"] = converged
        out["recovery_to_converged_s"] = round(time.time() - heal_t0, 3)
        out["final_phases"] = _pod_phases(store, names)
        out["per_key_order"] = {
            n: store.per_key_collapsed(("default", n)) for n in names
        }
        out["wire_rejects_delta"] = wire_rejects_total() - rejects0
        out["watch_relists_total"] = eng.metrics["watch_relists_total"]
        out["integrity_resyncs_total"] = eng.metrics[
            "watch_integrity_resyncs_total"
        ]
        out["crash_ledger"] = {
            t: list(v) for t, v in worker_crash_ledger().items()
        }
        if eng._lanes is not None:
            out["queues_drained"] = _wait(
                lambda: all(
                    lane.q.qsize() == 0 and lane.emit_q.qsize() == 0
                    for lane in eng._lanes.lanes
                ),
                10.0,
            )
        else:
            out["queues_drained"] = True

        if storm and converged:
            out.update(_seed_divergence(eng, store, names))
        out["degraded_at_end"] = eng.degraded
        out["degraded_reasons"] = list(eng._degradation.reasons)
        if eng._auditor is not None:
            out["audit"] = eng._auditor.snapshot()
            out["drift_detected_by_reason"] = {
                r: eng._auditor.detected_total(reason=r)
                for r in ("missed-event", "double-apply",
                          "stale-row", "ghost-row")
            }
        out["wall_s"] = round(time.time() - t_run0, 3)
    finally:
        eng.stop()
        srv.stop()
    return out


def _watch_quiescent(eng, hold: float = 1.5, timeout: float = 20.0) -> bool:
    """Wait until the watch tier stops re-listing: a storm-era stream cut
    or pending resync request landing DURING the seeded-divergence window
    would repair the seed through the re-list path (upsert repair render
    + RESYNC stale-key prune) before the auditor ever sees it — proving
    the wrong mechanism. Quiescence first makes the auditor the only
    repairer in play."""
    deadline = time.time() + timeout
    last = -1
    stable_since = time.time()
    while time.time() < deadline:
        cur = eng.metrics["watch_relists_total"]
        now = time.time()
        if cur != last:
            last = cur
            stable_since = now
        elif now - stable_since >= hold:
            return True
        time.sleep(0.1)
    return False


def _seed_divergence(eng, store, names) -> dict:
    """Post-convergence, faults off: mutate server state behind the
    engine's back and time the auditor's detect+repair."""
    aud = eng._auditor
    victim, ghost = names[0], names[1]
    quiesced = _watch_quiescent(eng)
    detected0 = aud.detected_total()
    repaired0 = aud.repaired_total

    def rewind(obj):
        (obj.setdefault("status", {}))["phase"] = "Pending"

    assert silent_patch(store, "pods", "default", victim, rewind)
    assert silent_delete(store, "pods", "default", ghost)
    t0 = time.time()

    def ghost_row_gone():
        lanes = eng._lanes
        engines = (
            [ln.engine for ln in lanes.lanes] if lanes is not None
            else [eng]
        )
        return all(
            e.pods.pool.lookup(("default", ghost)) is None for e in engines
        )

    def repaired():
        ph = (store.get("pods", "default", victim) or {}) \
            .get("status", {}).get("phase")
        return ph == "Running" and ghost_row_gone()

    ok = _wait(repaired, REPAIR_BOUND_S + 5.0)
    dt = round(time.time() - t0, 3)
    # post-repair settle: one clean pass clears any transient degraded
    # state and proves the repair is stable
    _wait(lambda: not eng.degraded, 3 * AUDIT_INTERVAL + 2.0)
    return {
        "seeded_watch_quiesced": quiesced,
        "seeded_divergence_repaired": ok,
        "seeded_repair_s": dt,
        "seeded_repair_bound_s": REPAIR_BOUND_S,
        "seeded_repaired_within_bound": ok and dt <= REPAIR_BOUND_S,
        "seeded_detected_delta": aud.detected_total() - detected0,
        "seeded_repaired_delta": aud.repaired_total - repaired0,
    }


def gates(base: dict, storm: dict) -> dict:
    fi = storm.get("faults_injected", {})
    ledger = storm.get("crash_ledger", {})
    return {
        "control_converged": bool(base["converged"]),
        "storm_converged": bool(storm["converged"]),
        # the headline: byte-identical final pod phases through the storm
        "phases_identical": (
            json.dumps(base["final_phases"], sort_keys=True)
            == json.dumps(storm["final_phases"], sort_keys=True)
        ),
        "per_key_order_preserved": (
            base["per_key_order"] == storm["per_key_order"]
        ),
        # every wire kind actually fired, and corruptions were counted
        # (rejected) — the byte-identical end state proves the rest were
        # repaired
        "wire_faults_actually_injected": all(
            fi.get(k, 0) >= 1
            for k in ("wire.garble", "wire.truncate", "wire.dup",
                      "wire.stale", "clock.jump")
        ),
        "corruptions_rejected": storm["wire_rejects_delta"] > 0,
        # no worker died outside supervision: every crash has a restart
        "zero_unsupervised_crashes": all(
            crashes == restarts for crashes, restarts in ledger.values()
        ),
        "queues_drained": bool(storm["queues_drained"]),
        "not_degraded_at_end": not storm["degraded_at_end"],
        # the anti-entropy oracle: both seeded divergences detected with
        # the right class and repaired inside the bound
        "seeded_divergence_repaired_in_bound": bool(
            storm.get("seeded_repaired_within_bound")
        ),
        "seeded_stale_row_detected": (
            storm.get("drift_detected_by_reason", {})
            .get("stale-row", 0) >= 1
        ),
        "seeded_ghost_row_detected": (
            storm.get("drift_detected_by_reason", {})
            .get("ghost-row", 0) >= 1
        ),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--pods", type=int, default=96)
    p.add_argument("--lanes", type=int, default=2)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--timeout", type=float, default=90.0,
                   help="per-arm convergence deadline (s)")
    p.add_argument("--out", default=os.path.join(REPO, "DRIFT_r01.json"))
    p.add_argument("--check", action="store_true",
                   help="CI gate: smaller workload, exit 1 on any failed "
                   "convergence/rejection/repair gate")
    args = p.parse_args()
    if args.check:
        args.pods = min(args.pods, 48)

    base = _run(args.pods, args.lanes, args.seed, storm=False,
                timeout=args.timeout)
    storm = _run(args.pods, args.lanes, args.seed, storm=True,
                 timeout=args.timeout)
    g = gates(base, storm)
    ok = all(g.values())

    artifact = {
        "bench": "drift_soak",
        "spec": DRIFT_SPEC.format(seed=args.seed),
        "audit_interval_s": AUDIT_INTERVAL,
        "params": {"pods": args.pods, "lanes": args.lanes,
                   "seed": args.seed, "check": args.check},
        "gates": g,
        "ok": ok,
        "control": {
            "wall_s": base["wall_s"],
            "watch_relists_total": base["watch_relists_total"],
        },
        "storm": {
            k: storm.get(k)
            for k in (
                "wall_s", "faults_injected", "wire_rejects_delta",
                "integrity_resyncs_total", "watch_relists_total",
                "recovery_to_converged_s", "queues_drained",
                "degraded_at_end", "degraded_reasons", "audit",
                "drift_detected_by_reason", "seeded_repair_s",
                "seeded_repair_bound_s", "seeded_detected_delta",
                "seeded_repaired_delta", "crash_ledger",
            )
        },
    }
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(json.dumps({"ok": ok, "gates": g, "out": args.out}))
    if not ok:
        failed = [k for k, v in g.items() if not v]
        print(f"drift_soak: FAILED gates: {failed}", file=sys.stderr)
        if not g["phases_identical"]:
            diff = {
                n: (base["final_phases"][n], storm["final_phases"][n])
                for n in base["final_phases"]
                if base["final_phases"][n] != storm["final_phases"][n]
            }
            print(f"drift_soak: phase diffs: {diff}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
