"""Weighted-Pallas hardware check: the Mosaic lowering of the weighted
rule draw, exercised on the REAL chip.

Interpret-mode tests (tests/test_weight.py) pin the weighted-draw
semantics but prove nothing about Mosaic lowering — the bug class that
bit three times in round 4 (i1 carries, sub-tile outputs, SMEM scalar
broadcasts) only appears on hardware. This check runs an 8192-row 1:3
weighted table through PallasTickKernel on the default device and
verifies the empirical distribution at 5 sigma. Wired into
hack/tpu-recapture.sh so every on-chip recapture re-proves the lowering.

Prints ONE JSON line; exit 0 on pass, 1 on distribution failure.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import numpy as np

    from kwok_tpu.models import compile_rules
    from kwok_tpu.models.lifecycle import (
        Delay,
        LifecycleRule,
        ResourceKind,
        StatusEffect,
    )
    from kwok_tpu.ops import new_row_state
    from kwok_tpu.ops.pallas_tick import PallasTickKernel
    from kwok_tpu.ops.tick import to_device, to_host

    platform = jax.devices()[0].platform
    rules = [
        LifecycleRule(
            name=f"w{i}", resource=ResourceKind.POD,
            from_phases=("Pending",), effect=StatusEffect(to_phase=to),
            delay=Delay.constant(0.0), weight=w,
        )
        for i, (w, to) in enumerate([(1, "Running"), (3, "Succeeded")])
    ]
    table = compile_rules(rules, ResourceKind.POD)
    n = 8192
    s = new_row_state(n)
    s.active[:] = True
    s.sel_bits[:] = 0b11
    kern = PallasTickKernel(table, interpret=platform == "cpu")
    out = to_host(kern(to_device(s), now=0.0))
    run = int((out.state.phase == table.space.phase_id("Running")).sum())
    suc = int((out.state.phase == table.space.phase_id("Succeeded")).sum())
    sigma = (n * 0.25 * 0.75) ** 0.5
    ok = (run + suc == n) and abs(run - 0.25 * n) < 5 * sigma
    on_chip = platform != "cpu"
    print(json.dumps({
        "metric": (
            f"pallas weighted draw on {platform}: 1:3 weights at {n} rows"
        ),
        "running": run,
        "succeeded": suc,
        "expected_running": n // 4,
        "five_sigma": round(5 * sigma, 1),
        "on_chip": on_chip,
        "pass": ok,
    }))
    if not on_chip:
        # interpret mode proves nothing about Mosaic lowering — this
        # script's whole purpose. A tunnel-down recapture must record a
        # SKIP (exit 3, like bench.py's device gate), not a phantom pass.
        return 3
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
