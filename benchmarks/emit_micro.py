"""Emit microbench: Python body-build vs AOT-template native slab splice.

COSTMODEL_r07 named `emit_render_us` 20.1µs the largest engine term: the
per-row Python gather in `_emit_pods_native` (meta dict walks, .encode()
calls, f-string path building) feeding the hand-rolled C renderer, plus a
per-row `now_rfc3339()` fallback — all serial and GIL-holding on the tick
thread. ISSUE 14 lowers each compiled Stage rule's patch body to a byte
template with hole offsets (models/compiler.compile_emit_templates) and
splices per-row values columnar-ly in ONE C call (codec.cc
kwok_emit_pods), with the pump send foldable into the same call.

This bench measures the render bodies route_micro-style (interleaved
best-of windows — single windows on shared hosts swing far more than the
delta under test):

- python arm: the full Python body build — edge/render.py
  render_pod_status + json.dumps per row, the path the engine takes with
  no native codec at all (and the KWOK_TPU_NATIVE_EMIT=0 slow-path
  renderer).
- legacy arm: the pre-ISSUE-14 native shape — per-row Python gather
  values + kwok_render_pod_statuses + the separate fingerprint call.
- native arm: the template slab splice — columnar gather straight off
  pre-encoded byte columns + ONE kwok_emit_pods call (render +
  fingerprints fused; the send is out of scope here, measured by
  cost_model.emit_pump_costs against a live server).

Prints ONE JSON line; --check mode runs small and exits nonzero unless
the native arm beats the python arm by --min-ratio (the regression gate
`make lane-check` runs).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run(rows: int, windows: int) -> dict:
    import numpy as np

    from kwok_tpu import native
    from kwok_tpu.edge.render import render_pod_status
    from kwok_tpu.models import (
        compile_emit_templates,
        compile_rules,
        default_pod_rules,
    )
    from kwok_tpu.models.lifecycle import POD_PHASES, ResourceKind

    if not native.available():
        return {"skipped": "native codec unavailable"}

    n = rows
    ptab = compile_rules(default_pod_rules(), ResourceKind.POD)
    tpl = compile_emit_templates(ptab)
    et = native.EmitTable(tpl)
    now = "2026-07-30T00:00:00Z"

    # the same logical rows for every arm: 2 containers + 1 init each
    ctr_dicts = [
        [{"name": "app", "image": "registry.local/app:v1"},
         {"name": "sidecar", "image": "envoy:1.29"}]
        for _ in range(n)
    ]
    ictr_dicts = [[{"name": "init", "image": "busybox"}] for _ in range(n)]
    pods = [
        {
            "metadata": {"creationTimestamp": now},
            "spec": {"containers": ctr_dicts[i],
                     "initContainers": ictr_dicts[i]},
            "status": {},
        }
        for i in range(n)
    ]
    hosts_s = [f"10.0.0.{i % 250}" for i in range(n)]
    ips_s = [f"10.244.3.{i % 250}" for i in range(n)]
    # pre-encoded columns, as the ingest path stages them (ISSUE 14
    # satellite: columnar emit inputs)
    hosts = [h.encode() for h in hosts_s]
    ips = [p.encode() for p in ips_s]
    starts = [now.encode()] * n
    ctrs = [b"app\x1fregistry.local/app:v1\x1esidecar\x1fenvoy:1.29"] * n
    ictrs = [b"init\x1fbusybox"] * n
    tpl_ids = np.full(n, int(tpl.phase_tpl[ptab.space.phase_id("Running")]),
                      np.int32)
    conds = np.full(n, 7, np.uint32)
    now_b = now.encode()

    def python_arm() -> float:
        t0 = time.perf_counter()
        bodies = [
            json.dumps(
                {"status": render_pod_status(
                    pods[i], "Running", 7, hosts_s[i], ips_s[i]
                )},
                separators=(",", ":"),
            ).encode()
            for i in range(n)
        ]
        native.fingerprint_statuses(bodies)
        return time.perf_counter() - t0

    def legacy_arm() -> float:
        t0 = time.perf_counter()
        bodies = native.render_pod_statuses(
            np.zeros(n, np.uint8), conds,
            [b"Running"] * n, list(POD_PHASES.conditions[:3]),
            hosts, ips, starts, ctrs, ictrs,
        )
        native.fingerprint_statuses([bytes(b) for b in bodies])
        return time.perf_counter() - t0

    def native_arm() -> float:
        t0 = time.perf_counter()
        native.emit_pods(
            et, tpl_ids, conds, hosts, ips, starts, ctrs, ictrs, now_b
        )
        return time.perf_counter() - t0

    py_best = leg_best = nat_best = float("inf")
    for _ in range(windows):
        py_best = min(py_best, python_arm())
        leg_best = min(leg_best, legacy_arm())
        nat_best = min(nat_best, native_arm())
    py_us = 1e6 * py_best / n
    leg_us = 1e6 * leg_best / n
    nat_us = 1e6 * nat_best / n
    return {
        "metric": (
            f"emit body render cost per pod at {rows} rows (best of "
            f"{windows} interleaved windows; bodies + echo-drop "
            "fingerprints, send excluded)"
        ),
        "python_render_us_per_pod": round(py_us, 3),
        "legacy_native_us_per_pod": round(leg_us, 3),
        "template_splice_us_per_pod": round(nat_us, 3),
        "speedup_vs_python": round(py_us / max(nat_us, 1e-9), 2),
        "speedup_vs_legacy": round(leg_us / max(nat_us, 1e-9), 2),
        "rows": rows,
        "windows": windows,
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=20000)
    p.add_argument("--windows", type=int, default=5)
    p.add_argument("--min-ratio", type=float, default=3.0,
                   help="--check gate: template splice must beat the "
                   "pure-Python body build by at least this factor")
    p.add_argument("--check", action="store_true",
                   help="small regression gate for make lane-check")
    args = p.parse_args()
    if args.check:
        args.rows = min(args.rows, 8000)
        args.windows = min(args.windows, 3)
    out = run(args.rows, args.windows)
    print(json.dumps(out))
    if "skipped" in out:
        return 0  # no compiler: the engine falls back to Python anyway
    if args.check and out["speedup_vs_python"] < args.min_ratio:
        print(
            f"emit_micro: template splice is only "
            f"{out['speedup_vs_python']}x the python body build "
            f"(< {args.min_ratio}x)", file=sys.stderr,
        )
        return 1
    if args.check and out["speedup_vs_legacy"] < 1.0:
        print("emit_micro: template splice regressed vs the legacy "
              "native renderer", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
