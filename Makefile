# Build/test entry points (parity: the reference Makefile's
# unit-test / verify / build targets, hack/releases.sh, hack/e2e-test.sh).
#
# Python children run on CPU JAX with the TPU-claim relay disabled so
# parallel processes don't deadlock on the single tunneled chip.
PYENV := env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu

.PHONY: all build unit-test e2e-test test verify analyze bench obs-check lane-check proc-check chaos-check restart-check fleet-check census-check drift-check attrib-check ha-check image cluster-image clean

all: build

build: ## native codec + wheel
	./hack/releases.sh

unit-test:
	$(PYENV) XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    python3 -m pytest tests/ -x -q

e2e-test:
	./hack/e2e-test.sh

test: unit-test e2e-test

verify:
	./hack/verify-all.sh

analyze: ## kwoklint: lock discipline, kernel purity, exception hygiene, metrics/docs contract (docs/static-analysis.md)
	python3 -m kwok_tpu.analysis

bench: ## the headline benchmark on the real device (ONE process, owns the TPU)
	python3 bench.py

obs-check: ## exposition-format + trace-schema oracle (docs/observability.md)
	$(PYENV) python3 -m pytest tests/test_metrics_exposition.py -q

# lane-check: the per-key patch-order oracle plus the engine tier-1 subset
# under PYTHONDEVMODE, with test_lanes' threading.excepthook fixture failing
# any test whose worker thread swallowed an exception, and the runtime
# lock-order witness (analysis/witness.py) failing any test whose threads
# acquired locks out of the declared order or formed an order-graph cycle.
lane-check: ## sharded-lane ordering oracle + thread-sanity + lock-witness pass + router/emit microbench gates
	$(PYENV) PYTHONDEVMODE=1 KWOK_TPU_LOCK_WITNESS=1 python3 -m pytest \
	    tests/test_lanes.py tests/test_engine.py tests/test_pipeline.py \
	    tests/test_native_emit.py -q
	$(PYENV) python3 benchmarks/route_micro.py --check
	$(PYENV) python3 benchmarks/emit_micro.py --check
	$(PYENV) python3 benchmarks/proc_micro.py --check

# proc-check: the process-lane gate (ISSUE 15 + 17): the proclanes unit
# tier (shm ring/slot/bank semantics, node topology tap, slot-guard
# pump, config/CLI plumbing, fault-plane SIGKILL/SIGSTOP targets,
# per-lane child fault-plane derivation, injected torn-write
# invariants, descriptor bounds-rejection, watchdog budget sharing)
# INCLUDING the slow spawn e2e tier-1 skips, then
# benchmarks/proc_soak.py --check: the per-key patch-order oracle
# byte-compared against the single-lane engine, a rotating lane-process
# SIGKILL chaos arm, and a mid-delay SIGKILL restart arm (delays resumed
# within one tick quantum from lane<i>.ckpt.json), and the ISSUE 17
# chaos+drift storm (full wire + shm/IPC fault tier + rotating
# SIGKILL/SIGSTOP with the shard-scoped child auditors on, then
# post-convergence silent mutations detected + repaired ->
# PROC_r02.json), with /dev/shm proven clean after every arm
# (docs/resilience.md "Process lanes" + "Multi-process fault plane &
# audit"; PROC_r*.json). The pytest tier runs under BOTH runtime
# witnesses: lock-order (analysis/witness.py) and the shm-protocol
# witness (analysis/witness_shm.py) — every bank/ring/slot op is
# checked against the seqlock/slot/ring contract while the shm fault
# tier is injecting torn writes.
proc-check: ## process-lane ordering + chaos/restart gate (PROC_r* artifact, shm-leak proof)
	$(PYENV) KWOK_TPU_LOCK_WITNESS=1 KWOK_TPU_SHM_WITNESS=1 python3 -m pytest tests/test_proclanes.py -q
	$(PYENV) python3 benchmarks/proc_soak.py --check

# chaos-check: the resilience suite (fault plane, retry policy, watchdog,
# pump partial-write recovery, shedding) plus the chaos convergence gate:
# the threaded 4-lane engine through a seeded fault storm — pump drops +
# mid-frame partial writes, watch cuts, 410/compaction storms, apiserver
# blackouts, a killed drain worker AND a killed emit worker — must end
# byte-identical to a fault-free run (docs/resilience.md; CHAOS_r*.json).
# The pytest tier runs under the runtime lock-order witness so the storm
# paths are deadlock-checked, not just convergence-checked.
chaos-check: ## deterministic fault-injection + self-healing convergence gate (+ restore storm)
	$(PYENV) KWOK_TPU_LOCK_WITNESS=1 python3 -m pytest tests/test_resilience.py -q
	$(PYENV) python3 benchmarks/chaos_soak.py --check --restore-storm

# restart-check: the crash-durability RTO gate: a real tpukwok process is
# SIGKILLed mid-lifecycle and cold-restarted against its --checkpoint-dir;
# gates = zero double-fired transitions (server-side oplog oracle), every
# Stage delay resumed within one tick quantum of its checkpointed residue,
# final pod phases byte-identical to an uninterrupted control arm, and the
# recovery-to-caught-up latency recorded in RESTART_r*.json
# (docs/resilience.md).
restart-check: ## SIGKILL + cold-restart crash-durability gate (RTO artifact)
	$(PYENV) python3 benchmarks/restart_soak.py --check

# fleet-check: the apiserver overload-protection gate: a 1000-watcher
# fleet (normal + deliberately-slow + churn + list-flood cohorts, the
# ISSUE 13 scale the serialize-once broadcast ring holds) against the
# native apiserver with max-inflight admission + the ring-cursor lag cap
# configured, while the threaded engine converges a workload under the
# fault storm. Gates = byte-identical final phases vs a no-fleet control
# arm, every watcher at the final resourceVersion, engine patch-RTT p99
# bounded, slow watchers ring-lag-terminated (not buffered unboundedly),
# and all 429s throttled by Retry-After (docs/resilience.md;
# FLEET_r*.json). Skips cleanly when no C++ compiler is available.
fleet-check: ## watcher-fleet survival gate (overload admission + ring-lag slow-watcher eviction)
	$(PYENV) python3 benchmarks/watcher_fleet.py --check

# census-check: the watch-plane census + exposition-parity gate
# (ISSUE 16): sweeps 200->1000 idle watchers against the native
# apiserver recording the per-watcher cost of the thread-per-watcher
# model (RSS/watcher, wake-fanout us, parked threads via GET
# /debug/watchers) — the measured before-photo the C10k epoll-reactor
# rewrite will be graded against — and proves a --lane-procs engine's
# /metrics is family-and-label identical to the threaded engine's
# (the MetricsBank shm merge; docs/observability.md). Emits
# WATCHPLANE_r*.json. Skips cleanly when no C++ compiler is available.
census-check: ## watch-plane census sweep + proc/threaded exposition-parity gate (WATCHPLANE_r* artifact)
	$(PYENV) python3 benchmarks/watchplane_census.py --check

# drift-check: the hostile-wire + anti-entropy gate: the threaded engine
# converges a workload through a byte-corruption storm (wire.garble /
# wire.truncate / wire.dup / wire.stale + clock.jump) byte-identically to
# a clean control arm, with every corruption rejected-or-repaired and
# zero unsupervised crashes; then a divergence seeded BEHIND the engine's
# back (silent status rewind + silent delete) must be detected and
# repaired by the anti-entropy auditor within one audit pass
# (docs/resilience.md "Hostile wire & anti-entropy"; DRIFT_r*.json). The
# unit tier (tests/test_resilience.py wire/clock cases +
# tests/test_antientropy.py) rides tier-1.
drift-check: ## hostile-wire convergence + anti-entropy drift-repair gate
	$(PYENV) python3 -m pytest tests/test_antientropy.py -q
	$(PYENV) python3 benchmarks/drift_soak.py --check

# attrib-check: the latency-attribution gate (ISSUE 11): drives the rig
# workload against the native apiserver with phase timing on and gates on
# (a) per-phase sums reconciling to the request-level totals within the
# disclosed tolerance, (b) the /debug/flight schema + timeline merge,
# (c) KWOK_TPU_APISERVER_TIMING=0 being measurably zero-cost (zeroed
# histograms, empty flight ring, parity-twin patch burst), and (d) the
# route_micro/hb_micro zero-cost contracts still holding with timing
# compiled in. Emits LATENCY_r*.json — the measured before-photo for the
# apiserver 10x tentpole. Skips cleanly when no C++ compiler is available.
attrib-check: ## measured end-to-end latency attribution gate (LATENCY_r* artifact)
	$(PYENV) python3 benchmarks/latency_attrib.py --check

# ha-check: the warm-standby failover gate (ISSUE 12): a real
# primary/standby tpukwok pair (lease-fenced through both mock
# apiservers' coordination.k8s.io Lease dialect) under the PR 6 storm.
# The primary is SIGKILLed AND SIGSTOPped (zombie) mid-delay; gates =
# takeover RTO <= lease duration + one tick quantum (and under the
# measured cold-restart reference), ZERO double-fired transitions on the
# wall-stamped oplog across both holders (the SIGCONT'd zombie provably
# write-dead: client fence + pump fence + server-side fencing header),
# final pod phases byte-identical to the uninterrupted-pair control arm,
# across every seed (docs/resilience.md "Warm-standby failover";
# HA_r*.json).
ha-check: ## lease-fenced warm-standby failover gate (HA_r* artifact)
	$(PYENV) python3 benchmarks/failover_soak.py --check

image:
	./images/kwok/build.sh

cluster-image:
	./images/cluster/build.sh

clean:
	rm -rf build dist *.egg-info kwok_tpu/native/libkwokcodec.so
	find . -name __pycache__ -type d -not -path './.git/*' -exec rm -rf {} +
