#!/usr/bin/env bash
# Build the engine image (parity: images/kwok/build.sh).
set -o errexit -o nounset -o pipefail
ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/../.." && pwd)"
IMAGE="${IMAGE:-kwok-tpu/kwok}"
TAG="${TAG:-latest}"
DOCKER="${DOCKER:-docker}"
exec "${DOCKER}" build -t "${IMAGE}:${TAG}" -f "${ROOT}/images/kwok/Dockerfile" "${ROOT}"
