#!/bin/sh
# Re-create the baked cluster at container start and keep it in the
# foreground (parity: images/cluster/entrypoint.sh).
set -e

# bind 0.0.0.0 so docker-proxy's published-port forward reaches the
# in-container apiserver
export KWOK_BIND_ADDRESS="${KWOK_BIND_ADDRESS:-0.0.0.0}"

python -m kwok_tpu.kwokctl create cluster \
  --runtime "${KWOK_RUNTIME:-mock}" \
  --kube-apiserver-port "${KWOK_KUBE_APISERVER_PORT:-8080}" \
  --bind-address "${KWOK_BIND_ADDRESS}" \
  --wait 60s "$@"

echo "##############################################################"
echo "# The cluster is up; this kubeconfig connects from the host: #"
echo "##############################################################"
cat <<EOF
apiVersion: v1
kind: Config
clusters:
  - name: kwok
    cluster:
      server: http://127.0.0.1:${KWOK_KUBE_APISERVER_PORT:-8080}
contexts:
  - name: kwok
    context:
      cluster: kwok
current-context: kwok
EOF

# keep the components (detached, pid-file supervised) in the foreground
exec tail -f "$HOME"/.kwok/clusters/kwok/logs/*.log
