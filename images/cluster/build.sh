#!/usr/bin/env bash
# Build the all-in-one cluster image (parity: images/cluster/build.sh).
set -o errexit -o nounset -o pipefail
ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/../.." && pwd)"
IMAGE="${IMAGE:-kwok-tpu/cluster}"
TAG="${TAG:-latest}"
DOCKER="${DOCKER:-docker}"
RUNTIME="${KWOK_RUNTIME:-mock}"
exec "${DOCKER}" build -t "${IMAGE}:${TAG}" \
  --build-arg "kwok_runtime=${RUNTIME}" \
  -f "${ROOT}/images/cluster/Dockerfile" "${ROOT}"
