"""CNI hook (the pkg/cni equivalent — stub).

The reference can optionally hand pod-IP allocation to real CNI plugins via
a netns dance on Linux (pkg/cni/cni_linux.go:30-83, netns_linux.go:66-165)
and stubs it elsewhere (cni_other.go:26-36). Real CNI is out of scope for
the TPU build (SURVEY.md §2.3): IPs come from the vectorized CIDR pool
(kwok_tpu.edge.ippool). This module keeps the `--enable-cni` flag honest —
the hook points exist, delegate to a pluggable provider, and default to a
stub that reports unavailability exactly like the reference's non-Linux
build.
"""

from __future__ import annotations

import importlib
import os
from typing import Callable

__all__ = ["available", "setup", "remove", "register", "load_from_env"]

# provider: (setup(ns, name, uid) -> list[str], remove(ns, name, uid) -> None)
_provider: tuple[Callable, Callable] | None = None


def register(setup_fn: Callable, remove_fn: Callable) -> None:
    """Install a real CNI provider (tests / future Linux support)."""
    global _provider
    _provider = (setup_fn, remove_fn)


def load_from_env() -> bool:
    """Install the provider named by KWOK_TPU_CNI_PROVIDER ("module" or
    "module:attr"; the object must expose setup/remove). This is the
    process-boundary analogue of the reference selecting its CNI plugin
    binaries from /etc/cni/net.d at runtime (cni_linux.go:30-83) — an
    external provider gets wired in without code changes here. Returns
    False when the variable is unset."""
    spec = os.environ.get("KWOK_TPU_CNI_PROVIDER")
    if not spec:
        return False
    try:
        modname, _, attr = spec.partition(":")
        obj = importlib.import_module(modname)
        if attr:
            obj = getattr(obj, attr)
        register(obj.setup, obj.remove)
    except (ImportError, AttributeError, ValueError) as e:
        raise RuntimeError(
            f"KWOK_TPU_CNI_PROVIDER={spec!r} could not be loaded: {e} "
            "(expected 'module' or 'module:attr' exposing setup/remove)"
        ) from e
    return True


def available() -> bool:
    return _provider is not None


def setup(namespace: str, name: str, uid: str) -> list[str]:
    """Allocate IPs for a pod via CNI (cni_linux.go:30 Setup).

    Raises RuntimeError when no provider is registered — the engine treats
    that as 'fall back to the IP pool', mirroring cni_other.go:26-36's
    unsupported-platform error.
    """
    if _provider is None:
        raise RuntimeError("cni: no provider registered (unsupported platform)")
    return _provider[0](namespace, name, uid)


def remove(namespace: str, name: str, uid: str) -> None:
    """Release a pod's CNI resources (cni_linux.go Remove)."""
    if _provider is None:
        raise RuntimeError("cni: no provider registered (unsupported platform)")
    _provider[1](namespace, name, uid)
