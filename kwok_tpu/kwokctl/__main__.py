import sys

from kwok_tpu.kwokctl.cli import main

if __name__ == "__main__":
    sys.exit(main())
