"""Project constants (pkg/consts/consts.go).

Download endpoints are the upstream public release channels; the
kwok-controller itself is THIS package (launched via a generated shim), so
there is no controller download.
"""

PROJECT_NAME = "kwok"
CONFIG_NAME = "kwok.yaml"
KWOK_VERSION = "v0.1.0"  # version tag used for this package's engine image

DEFAULT_KUBE_VERSION = "v1.26.0"

KUBE_BINARY_PREFIX = "https://dl.k8s.io/release"
ETCD_BINARY_PREFIX = "https://github.com/etcd-io/etcd/releases/download"
PROMETHEUS_VERSION = "2.41.0"
PROMETHEUS_BINARY_PREFIX = "https://github.com/prometheus/prometheus/releases/download"

RUNTIME_TYPE_BINARY = "binary"
RUNTIME_TYPE_DOCKER = "docker"
RUNTIME_TYPE_NERDCTL = "nerdctl"
RUNTIME_TYPE_KIND = "kind"
RUNTIME_TYPE_MOCK = "mock"  # in-process runtime for tests/CI (no downloads)

# Image registries (consts.go:26-44)
KUBE_IMAGE_PREFIX = "registry.k8s.io"
KWOK_IMAGE_PREFIX = "registry.k8s.io/kwok"
PROMETHEUS_IMAGE_PREFIX = "docker.io/prom"
KIND_NODE_IMAGE_PREFIX = "docker.io/kindest"

DOCKER_COMPOSE_VERSION = "2.13.0"
DOCKER_COMPOSE_BINARY_PREFIX = "https://github.com/docker/compose/releases/download"
KIND_VERSION = "0.17.0"
KIND_BINARY_PREFIX = "https://github.com/kubernetes-sigs/kind/releases/download"

# Mode presets (kwokctl_configuration_types.go ModeStableFeatureGateAndAPI)
MODE_STABLE_FEATURE_GATE_AND_API = "StableFeatureGateAndAPI"

COMPONENT_ETCD = "etcd"
COMPONENT_KUBE_APISERVER = "kube-apiserver"
COMPONENT_KUBE_CONTROLLER_MANAGER = "kube-controller-manager"
COMPONENT_KUBE_SCHEDULER = "kube-scheduler"
COMPONENT_KWOK_CONTROLLER = "kwok-controller"
COMPONENT_PROMETHEUS = "prometheus"
