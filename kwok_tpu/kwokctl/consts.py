"""Project constants (pkg/consts/consts.go).

Download endpoints are the upstream public release channels; the
kwok-controller itself is THIS package (launched via a generated shim), so
there is no controller download.
"""

PROJECT_NAME = "kwok"
CONFIG_NAME = "kwok.yaml"

DEFAULT_KUBE_VERSION = "v1.26.0"

KUBE_BINARY_PREFIX = "https://dl.k8s.io/release"
ETCD_BINARY_PREFIX = "https://github.com/etcd-io/etcd/releases/download"
PROMETHEUS_VERSION = "2.41.0"
PROMETHEUS_BINARY_PREFIX = "https://github.com/prometheus/prometheus/releases/download"

RUNTIME_TYPE_BINARY = "binary"
RUNTIME_TYPE_MOCK = "mock"  # in-process runtime for tests/CI (no downloads)

# Mode presets (kwokctl_configuration_types.go ModeStableFeatureGateAndAPI)
MODE_STABLE_FEATURE_GATE_AND_API = "StableFeatureGateAndAPI"

COMPONENT_ETCD = "etcd"
COMPONENT_KUBE_APISERVER = "kube-apiserver"
COMPONENT_KUBE_CONTROLLER_MANAGER = "kube-controller-manager"
COMPONENT_KUBE_SCHEDULER = "kube-scheduler"
COMPONENT_KWOK_CONTROLLER = "kwok-controller"
COMPONENT_PROMETHEUS = "prometheus"
