"""Self-signed PKI for the local control plane.

Behavioral port of pkg/kwokctl/pki (pki.go:33-91, pkiutil.go:72-141): one CA
plus an admin cert/key pair whose key doubles as the service-account signing
key. ECDSA P-256, ~100-year validity, SANs covering localhost loopback.
Implemented with the `cryptography` package instead of Go crypto/x509.
"""

from __future__ import annotations

import datetime
import ipaddress
import os

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

CA_NAME = "kwok-ca"
ADMIN_NAME = "kwok-admin"
_HUNDRED_YEARS = datetime.timedelta(days=365 * 100)


def _write(path: str, data: bytes, mode: int) -> None:
    with open(path, "wb") as f:
        f.write(data)
    os.chmod(path, mode)


def _key_pem(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def generate_pki(pki_dir: str, sans: tuple[str, ...] = ()) -> None:
    """Write ca.crt / ca.key / admin.crt / admin.key into pki_dir
    (pki.go GeneratePki layout)."""
    os.makedirs(pki_dir, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc) - datetime.timedelta(hours=1)

    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_subject = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, CA_NAME)])
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_subject)
        .issuer_name(ca_subject)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + _HUNDRED_YEARS)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True,
                key_cert_sign=True,
                crl_sign=True,
                content_commitment=False,
                key_encipherment=False,
                data_encipherment=False,
                key_agreement=False,
                encipher_only=False,
                decipher_only=False,
            ),
            critical=True,
        )
        .sign(ca_key, hashes.SHA256())
    )

    admin_key = ec.generate_private_key(ec.SECP256R1())
    # system:masters group grants cluster-admin through the subject's O
    # (pkiutil.go NewCertAndKey admin semantics)
    admin_subject = x509.Name(
        [
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, "system:masters"),
            x509.NameAttribute(NameOID.COMMON_NAME, ADMIN_NAME),
        ]
    )
    alt_names: list[x509.GeneralName] = [
        x509.DNSName("localhost"),
        x509.DNSName("kubernetes"),
        x509.DNSName("kubernetes.default"),
        x509.DNSName("kubernetes.default.svc"),
        x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
        x509.IPAddress(ipaddress.ip_address("::1")),
    ]
    for san in sans:
        try:
            alt_names.append(x509.IPAddress(ipaddress.ip_address(san)))
        except ValueError:
            alt_names.append(x509.DNSName(san))
    admin_cert = (
        x509.CertificateBuilder()
        .subject_name(admin_subject)
        .issuer_name(ca_subject)
        .public_key(admin_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + _HUNDRED_YEARS)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
        .add_extension(
            x509.ExtendedKeyUsage(
                [ExtendedKeyUsageOID.SERVER_AUTH, ExtendedKeyUsageOID.CLIENT_AUTH]
            ),
            critical=False,
        )
        .add_extension(x509.SubjectAlternativeName(alt_names), critical=False)
        .sign(ca_key, hashes.SHA256())
    )

    _write(os.path.join(pki_dir, "ca.crt"), ca_cert.public_bytes(serialization.Encoding.PEM), 0o644)
    _write(os.path.join(pki_dir, "ca.key"), _key_pem(ca_key), 0o600)
    _write(os.path.join(pki_dir, "admin.crt"), admin_cert.public_bytes(serialization.Encoding.PEM), 0o644)
    _write(os.path.join(pki_dir, "admin.key"), _key_pem(admin_key), 0o600)
