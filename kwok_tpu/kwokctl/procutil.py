"""Detached-process supervision with a restartable on-disk layout.

Behavioral port of pkg/utils/exec (cmd.go:35-137, cmd_other.go:28-49):
components run as daemonized children whose state survives the orchestrator
exiting — `<workdir>/pids/<name>.pid`, `<workdir>/cmdline/<name>` (NUL-joined
argv, so `fork_exec_restart` can replay the exact command after a host
reboot), `<workdir>/logs/<name>.log`. Liveness = signal 0 on the stored pid.
The layout is byte-compatible with the reference so its clusters could be
adopted in place.
"""

from __future__ import annotations

import os
import signal
import subprocess


def _pid_path(workdir: str, name: str) -> str:
    return os.path.join(workdir, "pids", os.path.basename(name) + ".pid")


def _cmdline_path(workdir: str, name: str) -> str:
    return os.path.join(workdir, "cmdline", os.path.basename(name))


def log_path(workdir: str, name: str) -> str:
    return os.path.join(workdir, "logs", os.path.basename(name) + ".log")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    except OSError:
        return False
    return True


def _read_pid(workdir: str, name: str) -> int | None:
    try:
        with open(_pid_path(workdir, name)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def is_running(workdir: str, name: str) -> bool:
    pid = _read_pid(workdir, name)
    return pid is not None and _pid_alive(pid)


def fork_exec(workdir: str, binary: str, *args: str) -> None:
    """Start `binary args...` detached; no-op if the pid file still points at
    a live process (cmd.go:35-92)."""
    pid = _read_pid(workdir, binary)
    if pid is not None and _pid_alive(pid):
        return

    argv = [binary, *args]
    lp = log_path(workdir, binary)
    cp = _cmdline_path(workdir, binary)
    pp = _pid_path(workdir, binary)
    for p in (lp, cp, pp):
        os.makedirs(os.path.dirname(p), exist_ok=True)

    with open(cp, "w") as f:
        f.write("\x00".join(argv))
    logf = open(lp, "wb")
    try:
        proc = subprocess.Popen(
            argv,
            cwd=workdir,
            stdout=logf,
            stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL,
            start_new_session=True,  # Setsid detach (cmd_other.go:28-35)
        )
    finally:
        logf.close()
    with open(pp, "w") as f:
        f.write(str(proc.pid))


def fork_exec_restart(workdir: str, name: str) -> None:
    """Replay the stored cmdline (cmd.go:95-106)."""
    with open(_cmdline_path(workdir, name)) as f:
        argv = f.read().split("\x00")
    fork_exec(workdir, argv[0], *argv[1:])


def fork_exec_kill(workdir: str, name: str, timeout: float = 10.0) -> None:
    """SIGTERM (grace) then SIGKILL the stored pid; remove the pid file
    (cmd.go:109-137; the reference SIGKILLs immediately — we give components
    a short grace so etcd can fsync)."""
    import time

    pid = _read_pid(workdir, name)
    if pid is None:
        return
    if _pid_alive(pid):
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            pass
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and _pid_alive(pid):
            time.sleep(0.05)
        if _pid_alive(pid):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        # reap if it was our child; ignore ECHILD for adopted processes
        try:
            os.waitpid(pid, 0)
        except ChildProcessError:
            pass
        except OSError:
            pass
    try:
        os.remove(_pid_path(workdir, name))
    except FileNotFoundError:
        pass


def exec_foreground(argv: list[str], workdir: str = "", **kwargs) -> int:
    """Run a command in the foreground wired to our stdio (cmd.go Exec)."""
    return subprocess.call(argv, cwd=workdir or None, **kwargs)
