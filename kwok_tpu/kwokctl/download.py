"""Download-with-cache for control-plane binaries.

Behavioral port of pkg/utils/file/download.go:35-112: a sha256(url)-keyed
cache directory, atomic rename into place, optional single-member extraction
from .tar.gz / .zip archives (DownloadWithCacheAndExtract). Uses stdlib
urllib; zero-egress environments simply fail with a clear error, and local
`file://` or absolute paths bypass the network entirely (the e2e path in CI
pre-seeds the cache or points at binaries already on disk).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tarfile
import tempfile
import urllib.request
import zipfile


def _cache_key(url: str) -> str:
    return hashlib.sha256(url.encode()).hexdigest()


def _fetch_to_cache(cache_dir: str, url: str, quiet: bool = False) -> str:
    """Return a local path for url: as-is for local files, else the cache
    entry (downloading on miss)."""
    if url.startswith("file://"):
        return url[len("file://") :]
    if os.path.sep in url and os.path.exists(url):
        return url
    os.makedirs(cache_dir, exist_ok=True)
    cached = os.path.join(cache_dir, _cache_key(url))
    if os.path.exists(cached):
        return cached
    if not quiet:
        print(f"Downloading {url}")
    tmp = cached + ".tmp"
    try:
        with urllib.request.urlopen(url) as resp, open(tmp, "wb") as out:
            shutil.copyfileobj(resp, out)
    except OSError as e:
        try:
            os.remove(tmp)
        except FileNotFoundError:
            pass
        raise RuntimeError(
            f"failed to download {url}: {e} "
            "(offline? pre-seed the cache dir or pass a local path)"
        ) from e
    os.replace(tmp, cached)
    return cached


def download_with_cache(
    cache_dir: str, src: str, dest: str, mode: int = 0o755, quiet: bool = False
) -> None:
    """Fetch src (url or local path) to dest with the cache in between."""
    local = _fetch_to_cache(cache_dir, src, quiet)
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
    if os.path.abspath(local) != os.path.abspath(dest):
        tmp = dest + ".tmp"
        shutil.copyfile(local, tmp)
        os.replace(tmp, dest)
    os.chmod(dest, mode)


def download_with_cache_and_extract(
    cache_dir: str,
    src: str,
    dest: str,
    member: str,
    mode: int = 0o755,
    quiet: bool = False,
) -> None:
    """Fetch an archive and extract the single file whose basename is
    `member` to dest (download.go:85-112)."""
    local = _fetch_to_cache(cache_dir, src, quiet)
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
    with tempfile.TemporaryDirectory(dir=os.path.dirname(dest)) as td:
        extracted = _extract_member(local, member, td)
        os.replace(extracted, dest)
    os.chmod(dest, mode)


def _extract_member(archive: str, member: str, outdir: str) -> str:
    if archive.endswith(".zip"):
        with zipfile.ZipFile(archive) as z:
            for info in z.infolist():
                if os.path.basename(info.filename) == member:
                    z.extract(info, outdir)
                    return os.path.join(outdir, info.filename)
    else:
        with tarfile.open(archive) as t:
            for info in t:
                if info.isfile() and os.path.basename(info.name) == member:
                    t.extract(info, outdir, filter="data")
                    return os.path.join(outdir, info.name)
    raise FileNotFoundError(f"member {member!r} not found in {archive}")
