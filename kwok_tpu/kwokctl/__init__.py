"""kwokctl: the orchestration plane (SURVEY.md layers 4-6).

Stands up a full simulated control plane — etcd, kube-apiserver,
kube-controller-manager, kube-scheduler, the TPU simulation engine, and
optionally Prometheus — as supervised host processes (`binary` runtime) or
generated shims (`mock` runtime, for air-gapped environments).
"""
