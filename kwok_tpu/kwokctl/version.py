"""Component version detection (pkg/utils/version/version.go:55-104 parity).

The reference learns a component's real version by running `<binary>
--version` (ParseFromBinary) or reading an image tag (ParseFromImage), so
version-keyed arg matrices stay correct when users supply custom binaries.
"""

from __future__ import annotations

import logging
import re
import subprocess

logger = logging.getLogger("kwok_tpu.kwokctl.version")

_VERSION_RE = re.compile(r"v?(\d+\.\d+\.\d+(?:-[0-9A-Za-z.+-]+)?)")


def parse_from_output(text: str) -> str | None:
    """First semantic version in arbitrary `--version` output
    (handles `Kubernetes v1.26.0`, `etcd Version: 3.5.6`, bare `v1.2.3`)."""
    m = _VERSION_RE.search(text or "")
    return "v" + m.group(1) if m else None


def parse_from_binary(path: str, timeout: float = 10.0) -> str | None:
    """Run `<path> --version` and parse (version.go:55-78). Returns None for
    missing/unrunnable binaries or unparseable output."""
    try:
        out = subprocess.run(
            [path, "--version"],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except (OSError, subprocess.SubprocessError) as e:
        logger.debug("version probe of %s failed: %s", path, e)
        return None
    return parse_from_output(out.stdout + "\n" + out.stderr)


def parse_from_image(image: str) -> str | None:
    """Version from an image tag (version.go:80-104): text after the last
    ':' that is not part of a registry port."""
    if not image:
        return None
    tag = image.rsplit(":", 1)
    if len(tag) != 2 or "/" in tag[1]:
        return None
    return parse_from_output(tag[1])
