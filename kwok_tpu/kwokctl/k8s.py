"""Version-aware Kubernetes matrices + kubeconfig rendering.

Behavioral port of pkg/kwokctl/k8s: get_feature_gates (feature_gates.go:28-60
"enable only the beta features that eventually went GA"), get_runtime_config
(runtime_config.go:19), get_etcd_version (etcd.go:47-73 kubeadm constants
table), build_kubeconfig (kubeconfig.go:32-47 + kubeconfig.yaml.tpl).
"""

from __future__ import annotations

import re

from kwok_tpu.kwokctl.feature_gates_data import BETA, DEPRECATED, GA, FEATURE_GATES


def parse_release(version: str) -> int:
    """'v1.26.0' / '1.26' -> 26; unparseable -> -1 (vars.go parseRelease)."""
    m = re.match(r"^v?\d+\.(\d+)", version.strip())
    return int(m.group(1)) if m else -1


def get_feature_gates(release: int) -> str:
    """Stable-mode gate string for k8s 1.<release>.

    Policy (feature_gates.go:39-61): every gate that is Beta in this release
    is pinned — to true only if some later stage of that gate reached GA
    (i.e. the beta eventually graduated), else to false. Alpha gates are
    never enabled.
    """
    if release < 0:
        return ""
    went_ga: dict[str, bool] = {}
    for name, stage, _since, _until in FEATURE_GATES:
        if stage == GA:
            went_ga.setdefault(name, True)
        elif stage == DEPRECATED:
            went_ga[name] = False
    enables: dict[str, bool] = {}
    for name, stage, since, until in FEATURE_GATES:
        if since <= release and (until < 0 or release <= until):
            if stage == BETA:
                enables[name] = went_ga.get(name, False)
    return ",".join(
        f"{name}={str(val).lower()}" for name, val in sorted(enables.items())
    )


def get_runtime_config(release: int) -> str:
    """Stable-mode --runtime-config (runtime_config.go:19-24)."""
    if release < 17:
        return ""
    return "api/legacy=false,api/alpha=false"


# kubeadm's etcd-per-k8s-minor constants (etcd.go:28-45); '-0' image-tag
# suffixes dropped since the binary runtime downloads plain release tars.
_ETCD_VERSIONS = {
    8: "3.0.17",
    9: "3.1.12",
    10: "3.1.12",
    11: "3.2.18",
    12: "3.2.24",
    13: "3.2.24",
    14: "3.3.10",
    15: "3.3.10",
    16: "3.3.17",
    17: "3.4.3",
    18: "3.4.3",
    19: "3.4.13",
    20: "3.4.13",
    21: "3.4.13",
    22: "3.5.6",
    23: "3.5.6",
    24: "3.5.6",
    25: "3.5.6",
}


def get_etcd_version(release: int) -> str:
    """etcd version for k8s 1.<release>, clamped to the table's range
    (etcd.go:47-73)."""
    if release < 0:
        return "unknown"
    if release in _ETCD_VERSIONS:
        return _ETCD_VERSIONS[release]
    lo, hi = min(_ETCD_VERSIONS), max(_ETCD_VERSIONS)
    return _ETCD_VERSIONS[min(max(release, lo), hi)]


def build_kubeconfig(
    project_name: str,
    address: str,
    secure_port: bool = False,
    admin_crt_path: str = "",
    admin_key_path: str = "",
    token: str = "",
) -> str:
    """Render a kubeconfig document (kubeconfig.yaml.tpl semantics: client
    certs + skip-tls-verify only on the secure path; `token` carries the
    bearer credential for the mock runtime's --kube-authorization mode)."""
    lines = [
        "apiVersion: v1",
        "kind: Config",
        "preferences: {}",
        f"current-context: {project_name}",
        "clusters:",
        f"  - name: {project_name}",
        "    cluster:",
        f"      server: {address}",
    ]
    if secure_port:
        lines.append("      insecure-skip-tls-verify: true")
    lines += [
        "contexts:",
        f"  - name: {project_name}",
        "    context:",
        f"      cluster: {project_name}",
    ]
    if secure_port or token:
        lines += [
            f"      user: {project_name}",
            "users:",
            f"  - name: {project_name}",
            "    user:",
        ]
    if secure_port:
        lines += [
            f"      client-certificate: {admin_crt_path}",
            f"      client-key: {admin_key_path}",
        ]
    if token:
        lines.append(f"      token: {token}")
    return "\n".join(lines) + "\n"
