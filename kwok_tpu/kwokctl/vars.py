"""Workdir layout + option defaulting with KWOK_* env overrides.

Behavioral port of pkg/config/vars.go:28-51 (workdir), :100-445 (defaults +
GetEnvWithPrefix): every option falls back file -> env -> computed default.
"""

from __future__ import annotations

import os
import platform

from kwok_tpu.config.ctl import KwokctlConfigurationOptions
from kwok_tpu.config.types import parse_bool
from kwok_tpu.kwokctl import consts, k8s

ENV_PREFIX = "KWOK_"


def work_dir() -> str:
    env = os.environ.get(ENV_PREFIX + "WORKDIR")
    if env:
        return env
    home = os.path.expanduser("~")
    return os.path.join(home, "." + consts.PROJECT_NAME)


def clusters_dir() -> str:
    return os.path.join(work_dir(), "clusters")


def cluster_workdir(name: str) -> str:
    return os.path.join(clusters_dir(), name)


def cluster_name(name: str) -> str:
    return f"{consts.PROJECT_NAME}-{name}"


def _env(key: str, default):
    raw = os.environ.get(ENV_PREFIX + key)
    if raw is None:
        return default
    if isinstance(default, bool):
        return parse_bool(raw)
    if isinstance(default, int) and not isinstance(default, bool):
        return int(raw)
    return raw


def _goarch() -> str:
    m = platform.machine().lower()
    return {"x86_64": "amd64", "aarch64": "arm64", "arm64": "arm64"}.get(m, m)


def set_defaults(opts: KwokctlConfigurationOptions) -> KwokctlConfigurationOptions:
    """Fill every empty option from env or computed default
    (vars.go setKwokctlConfigurationDefaults)."""
    goos = "linux" if os.name == "posix" else os.name
    arch = _goarch()

    opts.kubeVersion = _env(
        "KUBE_VERSION", opts.kubeVersion or consts.DEFAULT_KUBE_VERSION
    )
    if not opts.kubeVersion.startswith("v"):
        opts.kubeVersion = "v" + opts.kubeVersion
    release = k8s.parse_release(opts.kubeVersion)

    opts.runtime = _env("RUNTIME", opts.runtime or consts.RUNTIME_TYPE_BINARY)

    if opts.securePort is None:
        # insecure serving was removed after 1.19; the reference's cutover
        # (vars.go:118) keys on >1.12. The mock runtime defaults to plain
        # HTTP (the native lab apiserver is plaintext-only); an explicit
        # --secure-port=true still turns on mTLS with the cluster PKI.
        opts.securePort = (
            release > 12 and opts.runtime != consts.RUNTIME_TYPE_MOCK
        )
    opts.securePort = _env("SECURE_PORT", opts.securePort)
    opts.mode = _env("MODE", opts.mode)
    opts.quietPull = _env("QUIET_PULL", opts.quietPull)
    opts.disableKubeScheduler = _env(
        "DISABLE_KUBE_SCHEDULER", opts.disableKubeScheduler
    )
    opts.disableKubeControllerManager = _env(
        "DISABLE_KUBE_CONTROLLER_MANAGER", opts.disableKubeControllerManager
    )
    opts.kubeAuthorization = _env("KUBE_AUTHORIZATION", opts.kubeAuthorization)
    opts.kubeApiserverPort = _env("KUBE_APISERVER_PORT", opts.kubeApiserverPort)
    opts.bindAddress = _env("BIND_ADDRESS", opts.bindAddress)
    opts.kubeAuditPolicy = _env("KUBE_AUDIT_POLICY", opts.kubeAuditPolicy)

    if not opts.kubeFeatureGates and opts.mode == consts.MODE_STABLE_FEATURE_GATE_AND_API:
        opts.kubeFeatureGates = k8s.get_feature_gates(release)
    opts.kubeFeatureGates = _env("KUBE_FEATURE_GATES", opts.kubeFeatureGates)

    if not opts.kubeRuntimeConfig and opts.mode == consts.MODE_STABLE_FEATURE_GATE_AND_API:
        opts.kubeRuntimeConfig = k8s.get_runtime_config(release)
    opts.kubeRuntimeConfig = _env("KUBE_RUNTIME_CONFIG", opts.kubeRuntimeConfig)

    if not opts.cacheDir:
        opts.cacheDir = os.path.join(work_dir(), "cache")

    if not opts.kubeBinaryPrefix:
        opts.kubeBinaryPrefix = (
            f"{consts.KUBE_BINARY_PREFIX}/{opts.kubeVersion}/bin/{goos}/{arch}"
        )
    opts.kubeBinaryPrefix = _env("KUBE_BINARY_PREFIX", opts.kubeBinaryPrefix)
    for field, name in (
        ("kubeApiserverBinary", "kube-apiserver"),
        ("kubeControllerManagerBinary", "kube-controller-manager"),
        ("kubeSchedulerBinary", "kube-scheduler"),
        ("kubectlBinary", "kubectl"),
    ):
        if not getattr(opts, field):
            setattr(opts, field, f"{opts.kubeBinaryPrefix}/{name}{opts.binSuffix}")
    opts.kubeApiserverBinary = _env("KUBE_APISERVER_BINARY", opts.kubeApiserverBinary)
    opts.kubeControllerManagerBinary = _env(
        "KUBE_CONTROLLER_MANAGER_BINARY", opts.kubeControllerManagerBinary
    )
    opts.kubeSchedulerBinary = _env("KUBE_SCHEDULER_BINARY", opts.kubeSchedulerBinary)
    opts.kubectlBinary = _env("KUBECTL_BINARY", opts.kubectlBinary)

    if not opts.etcdVersion:
        opts.etcdVersion = k8s.get_etcd_version(release)
    opts.etcdVersion = _env("ETCD_VERSION", opts.etcdVersion)
    if not opts.etcdBinaryPrefix:
        opts.etcdBinaryPrefix = consts.ETCD_BINARY_PREFIX
    if not opts.etcdBinaryTar:
        v = opts.etcdVersion
        ext = "zip" if goos == "windows" else "tar.gz"
        opts.etcdBinaryTar = (
            f"{opts.etcdBinaryPrefix}/v{v}/etcd-v{v}-{goos}-{arch}.{ext}"
        )
    opts.etcdBinary = _env("ETCD_BINARY", opts.etcdBinary)
    opts.etcdBinaryTar = _env("ETCD_BINARY_TAR", opts.etcdBinaryTar)

    if not opts.prometheusVersion:
        opts.prometheusVersion = consts.PROMETHEUS_VERSION
    opts.prometheusVersion = _env("PROMETHEUS_VERSION", opts.prometheusVersion)
    if not opts.prometheusBinaryPrefix:
        opts.prometheusBinaryPrefix = consts.PROMETHEUS_BINARY_PREFIX
    if not opts.prometheusBinaryTar:
        v = opts.prometheusVersion
        opts.prometheusBinaryTar = (
            f"{opts.prometheusBinaryPrefix}/v{v}/prometheus-{v}.{goos}-{arch}.tar.gz"
        )
    opts.prometheusBinary = _env("PROMETHEUS_BINARY", opts.prometheusBinary)
    opts.prometheusBinaryTar = _env("PROMETHEUS_BINARY_TAR", opts.prometheusBinaryTar)

    _set_image_defaults(opts, goos, arch)

    return opts


def _join_image_uri(prefix: str, name: str, version: str) -> str:
    """vars.go joinImageURI: <prefix>/<name>:<version>."""
    return f"{prefix}/{name}:{version}"


def _set_image_defaults(opts: KwokctlConfigurationOptions, goos: str, arch: str) -> None:
    """Container-image + compose/kind tool defaults (vars.go:226-345).
    Only consulted by the compose/kind runtimes."""
    opts.kubeImagePrefix = _env(
        "KUBE_IMAGE_PREFIX", opts.kubeImagePrefix or consts.KUBE_IMAGE_PREFIX
    )
    if not opts.kubeApiserverImage:
        opts.kubeApiserverImage = _join_image_uri(
            opts.kubeImagePrefix, "kube-apiserver", opts.kubeVersion
        )
    opts.kubeApiserverImage = _env("KUBE_APISERVER_IMAGE", opts.kubeApiserverImage)
    if not opts.kubeControllerManagerImage:
        opts.kubeControllerManagerImage = _join_image_uri(
            opts.kubeImagePrefix, "kube-controller-manager", opts.kubeVersion
        )
    opts.kubeControllerManagerImage = _env(
        "KUBE_CONTROLLER_MANAGER_IMAGE", opts.kubeControllerManagerImage
    )
    if not opts.kubeSchedulerImage:
        opts.kubeSchedulerImage = _join_image_uri(
            opts.kubeImagePrefix, "kube-scheduler", opts.kubeVersion
        )
    opts.kubeSchedulerImage = _env("KUBE_SCHEDULER_IMAGE", opts.kubeSchedulerImage)

    opts.etcdImagePrefix = _env(
        "ETCD_IMAGE_PREFIX", opts.etcdImagePrefix or opts.kubeImagePrefix
    )
    if not opts.etcdImage:
        # registry.k8s.io publishes kubeadm-style tags ("3.5.6-0"); the
        # version table stores bare versions for binary downloads
        tag = opts.etcdVersion
        if "-" not in tag:
            tag += "-0"
        opts.etcdImage = _join_image_uri(opts.etcdImagePrefix, "etcd", tag)
    opts.etcdImage = _env("ETCD_IMAGE", opts.etcdImage)

    opts.kwokImagePrefix = _env(
        "IMAGE_PREFIX", opts.kwokImagePrefix or consts.KWOK_IMAGE_PREFIX
    )
    if not opts.kwokVersion:
        opts.kwokVersion = consts.KWOK_VERSION
    if not opts.kwokControllerImage:
        opts.kwokControllerImage = _join_image_uri(
            opts.kwokImagePrefix, "kwok", opts.kwokVersion
        )
    opts.kwokControllerImage = _env("CONTROLLER_IMAGE", opts.kwokControllerImage)

    opts.prometheusImagePrefix = _env(
        "PROMETHEUS_IMAGE_PREFIX",
        opts.prometheusImagePrefix or consts.PROMETHEUS_IMAGE_PREFIX,
    )
    if not opts.prometheusImage:
        opts.prometheusImage = _join_image_uri(
            opts.prometheusImagePrefix, "prometheus", "v" + opts.prometheusVersion
        )
    opts.prometheusImage = _env("PROMETHEUS_IMAGE", opts.prometheusImage)

    opts.kindNodeImagePrefix = _env(
        "KIND_NODE_IMAGE_PREFIX",
        opts.kindNodeImagePrefix or consts.KIND_NODE_IMAGE_PREFIX,
    )
    if not opts.kindNodeImage:
        opts.kindNodeImage = _join_image_uri(
            opts.kindNodeImagePrefix, "node", opts.kubeVersion
        )
    opts.kindNodeImage = _env("KIND_NODE_IMAGE", opts.kindNodeImage)

    if not opts.dockerComposeVersion:
        opts.dockerComposeVersion = consts.DOCKER_COMPOSE_VERSION
    opts.dockerComposeVersion = _env("DOCKER_COMPOSE_VERSION", opts.dockerComposeVersion)
    if not opts.dockerComposeBinaryPrefix:
        opts.dockerComposeBinaryPrefix = (
            f"{consts.DOCKER_COMPOSE_BINARY_PREFIX}/v{opts.dockerComposeVersion}"
        )
    opts.dockerComposeBinaryPrefix = _env(
        "DOCKER_COMPOSE_BINARY_PREFIX", opts.dockerComposeBinaryPrefix
    )
    if not opts.dockerComposeBinary:
        # docker/compose release assets use uname-style arch names
        compose_arch = {"amd64": "x86_64", "arm64": "aarch64"}.get(arch, arch)
        opts.dockerComposeBinary = (
            f"{opts.dockerComposeBinaryPrefix}/docker-compose-{goos}-{compose_arch}"
            f"{opts.binSuffix}"
        )
    opts.dockerComposeBinary = _env("DOCKER_COMPOSE_BINARY", opts.dockerComposeBinary)

    if not opts.kindVersion:
        opts.kindVersion = consts.KIND_VERSION
    opts.kindVersion = _env("KIND_VERSION", opts.kindVersion)
    if not opts.kindBinaryPrefix:
        opts.kindBinaryPrefix = f"{consts.KIND_BINARY_PREFIX}/v{opts.kindVersion}"
    opts.kindBinaryPrefix = _env("KIND_BINARY_PREFIX", opts.kindBinaryPrefix)
    if not opts.kindBinary:
        opts.kindBinary = f"{opts.kindBinaryPrefix}/kind-{goos}-{arch}"
    opts.kindBinary = _env("KIND_BINARY", opts.kindBinary)
