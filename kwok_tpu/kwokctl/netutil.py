"""Small networking helpers (pkg/utils/net)."""

from __future__ import annotations

import socket


def get_unused_port() -> int:
    """Bind port 0, return the kernel-assigned port (net.GetUnusedPort)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def is_port_open(host: str, port: int, timeout: float = 0.5) -> bool:
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return True
    except OSError:
        return False
