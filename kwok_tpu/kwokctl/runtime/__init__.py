"""Runtime registry (pkg/kwokctl/runtime/registry.go).

`get` builds a runtime by name; `load` re-reads a saved cluster's config to
pick the runtime that created it (registry.go:50-66), so every later verb
(start/stop/logs/snapshot/delete) works without repeating --runtime.
"""

from __future__ import annotations

import os

from kwok_tpu.config.ctl import KwokctlConfiguration
from kwok_tpu.config.types import first_of, load_documents
from kwok_tpu.kwokctl.runtime.base import CONFIG_NAME, Cluster
from kwok_tpu.kwokctl.runtime.binary import BinaryCluster
from kwok_tpu.kwokctl.runtime.compose import ComposeCluster, NerdctlCluster
from kwok_tpu.kwokctl.runtime.kindcluster import KindCluster
from kwok_tpu.kwokctl.runtime.mock import MockCluster

_REGISTRY: dict[str, type[Cluster]] = {}


def register(name: str, cls: type[Cluster]) -> None:
    _REGISTRY[name] = cls


def get(runtime: str, name: str, workdir: str) -> Cluster:
    try:
        cls = _REGISTRY[runtime]
    except KeyError:
        raise ValueError(
            f"unknown runtime {runtime!r}; known: {sorted(_REGISTRY)}"
        ) from None
    return cls(name, workdir)


def load(name: str, workdir: str) -> Cluster:
    """Pick the runtime from the cluster's saved config."""
    conf = first_of(
        load_documents(os.path.join(workdir, CONFIG_NAME)), KwokctlConfiguration
    )
    if conf is None:
        raise FileNotFoundError(f"cluster {name!r} does not exist (no {CONFIG_NAME})")
    rt = get(conf.options.runtime, name, workdir)
    rt.set_config(conf)
    return rt


def known_runtimes() -> list[str]:
    return sorted(_REGISTRY)


register(BinaryCluster.RUNTIME, BinaryCluster)
register(ComposeCluster.RUNTIME, ComposeCluster)
register(NerdctlCluster.RUNTIME, NerdctlCluster)
register(KindCluster.RUNTIME, KindCluster)
register(MockCluster.RUNTIME, MockCluster)
