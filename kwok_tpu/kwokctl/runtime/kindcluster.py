"""Kind runtime: attach the engine to a kind-provisioned control plane.

Behavioral port of pkg/kwokctl/runtime/kind: install() renders a kind
Cluster config (kind.yaml.tpl — apiserver/prometheus port mappings, feature
gates, runtime config, audit wiring, kwok.yaml extraMount), a kwok-controller
**static pod** manifest, and a prometheus in-cluster manifest set. up() runs
`kind create cluster`, side-loads the images (`kind load docker-image`,
cluster.go:288-304), then docker-cp's the static pod into the control-plane's
/etc/kubernetes/manifests so kubelet runs the engine (cluster.go:210).
Component stop/start = moving the static-pod manifest out of/back into the
manifests dir (cluster.go:407-421). This runtime proves "attach the TPU
engine to an existing cluster" — the engine itself still runs as a container
image serving 0.0.0.0:8080 with --manage-all-nodes=false + the fake-node
annotation selector (kwok_controller_pod.yaml.tpl).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess

from kwok_tpu.kwokctl import consts, download
from kwok_tpu.kwokctl.runtime import base
from kwok_tpu.kwokctl.runtime.base import Cluster

KIND_NAME = "kind.yaml"
KWOK_POD_NAME = "kwok-controller-pod.yaml"
PROMETHEUS_DEPLOY_NAME = "prometheus-deployment.yaml"


def build_kind_yaml(
    kube_apiserver_port: int = 0,
    prometheus_port: int = 0,
    feature_gates: list[str] | None = None,
    runtime_config: list[str] | None = None,
    audit_policy: str = "",
    audit_log: str = "",
    config_path: str = "",
) -> str:
    """kind Cluster document (kind.yaml.tpl semantics)."""
    out = [
        "kind: Cluster",
        "apiVersion: kind.x-k8s.io/v1alpha4",
        "networking:",
        '  apiServerAddress: "0.0.0.0"',
    ]
    if kube_apiserver_port:
        out.append(f"  apiServerPort: {kube_apiserver_port}")
    out.append("nodes:")
    out.append("- role: control-plane")
    if prometheus_port:
        out += [
            "  extraPortMappings:",
            "  - containerPort: 9090",
            f"    hostPort: {prometheus_port}",
            '    listenAddress: "0.0.0.0"',
            "    protocol: TCP",
        ]
    if audit_policy:
        out += [
            "  kubeadmConfigPatches:",
            "  - |",
            "    kind: ClusterConfiguration",
            "    apiServer:",
            "      extraArgs:",
            "        audit-log-path: /var/log/kubernetes/audit.log",
            "        audit-policy-file: /etc/kubernetes/audit/audit.yaml",
            "      extraVolumes:",
            "      - name: audit-policies",
            "        hostPath: /etc/kubernetes/audit",
            "        mountPath: /etc/kubernetes/audit",
            "        readOnly: true",
            '        pathType: "DirectoryOrCreate"',
            '      - name: "audit-logs"',
            '        hostPath: "/var/log/kubernetes"',
            '        mountPath: "/var/log/kubernetes"',
            "        readOnly: false",
            "        pathType: DirectoryOrCreate",
        ]
    out += [
        "  extraMounts:",
        f"  - hostPath: {config_path}",
        "    containerPath: /etc/kwok/kwok.yaml",
        "    readOnly: true",
    ]
    if audit_policy:
        out += [
            f"  - hostPath: {audit_policy}",
            "    containerPath: /etc/kubernetes/audit/audit.yaml",
            "    readOnly: true",
            f"  - hostPath: {audit_log}",
            "    containerPath: /var/log/kubernetes/audit.log",
            "    readOnly: false",
        ]
    if feature_gates:
        out.append("featureGates:")
        out += [f"  {g}" for g in feature_gates]
    if runtime_config:
        out.append("runtimeConfig:")
        out += [f"  {r}" for r in runtime_config]
    return "\n".join(out) + "\n"


def build_kwok_controller_pod(image: str) -> str:
    """Static-pod manifest for the engine (kwok_controller_pod.yaml.tpl):
    hostNetwork, kubelet-supervised, fake-node annotation selectors and the
    disregard-status escape hatch preconfigured."""
    return f"""apiVersion: v1
kind: Pod
metadata:
  labels:
    app: kwok-controller
  name: kwok-controller
  namespace: kube-system
spec:
  containers:
  - args:
    - --config=/etc/kwok/kwok.yaml
    - --manage-all-nodes=false
    - --manage-nodes-with-annotation-selector=kwok.x-k8s.io/node=fake
    - --manage-nodes-with-label-selector=
    - --disregard-status-with-annotation-selector=kwok.x-k8s.io/status=custom
    - --disregard-status-with-label-selector=
    - --server-address=0.0.0.0:8080
    - --kubeconfig=/etc/kubernetes/admin.conf
    - --node-ip=$(POD_IP)
    env:
    - name: POD_IP
      valueFrom:
        fieldRef:
          fieldPath: status.podIP
    image: '{image}'
    imagePullPolicy: IfNotPresent
    livenessProbe:
      failureThreshold: 3
      httpGet:
        path: /healthz
        port: 8080
        scheme: HTTP
      initialDelaySeconds: 2
      periodSeconds: 10
      timeoutSeconds: 2
    name: kwok-controller
    readinessProbe:
      failureThreshold: 5
      httpGet:
        # readiness is gated on engine warm-up (503 until the fused tick
        # kernel compiled); liveness above stays on the ungated /healthz
        path: /readyz
        port: 8080
        scheme: HTTP
      initialDelaySeconds: 2
      periodSeconds: 20
      timeoutSeconds: 2
    volumeMounts:
    - mountPath: /etc/kubernetes/admin.conf
      name: kubeconfig
      readOnly: true
    - mountPath: /etc/kwok/kwok.yaml
      name: config
      readOnly: true
  hostNetwork: true
  restartPolicy: Always
  volumes:
  - hostPath:
      path: /etc/kubernetes/admin.conf
      type: FileOrCreate
    name: kubeconfig
  - hostPath:
      path: /etc/kwok/kwok.yaml
      type: FileOrCreate
    name: config
"""


def build_prometheus_deployment(name: str, image: str) -> str:
    """In-cluster prometheus: RBAC + ConfigMap + hostNetwork Pod pinned to
    the control-plane node (prometheus_deployment.yaml.tpl). All targets are
    localhost because every control-plane process shares the node's netns."""
    scrape = """    global:
      scrape_interval: 15s
      scrape_timeout: 10s
      evaluation_interval: 15s
    scrape_configs:
      - job_name: "prometheus"
        scheme: http
        metrics_path: /metrics
        static_configs:
          - targets: ["localhost:9090"]
      - job_name: "etcd"
        scheme: https
        metrics_path: /metrics
        tls_config:
          cert_file: /etc/kubernetes/pki/apiserver-etcd-client.crt
          key_file: /etc/kubernetes/pki/apiserver-etcd-client.key
          insecure_skip_verify: true
        static_configs:
          - targets: ["localhost:2379"]
      - job_name: "kwok-controller"
        scheme: http
        metrics_path: /metrics
        static_configs:
          - targets: ["localhost:8080"]
      - job_name: "kube-apiserver"
        scheme: https
        metrics_path: /metrics
        tls_config:
          cert_file: /etc/kubernetes/pki/apiserver-etcd-client.crt
          key_file: /etc/kubernetes/pki/apiserver-etcd-client.key
          insecure_skip_verify: true
        static_configs:
          - targets: ["localhost:6443"]
      - job_name: "kube-controller-manager"
        scheme: https
        metrics_path: /metrics
        tls_config:
          insecure_skip_verify: true
        bearer_token_file: /var/run/secrets/kubernetes.io/serviceaccount/token
        static_configs:
          - targets: ["localhost:10257"]
      - job_name: "kube-scheduler"
        scheme: https
        metrics_path: /metrics
        tls_config:
          insecure_skip_verify: true
        bearer_token_file: /var/run/secrets/kubernetes.io/serviceaccount/token
        static_configs:
          - targets: ["localhost:10259"]
"""
    return f"""apiVersion: rbac.authorization.k8s.io/v1
kind: ClusterRole
metadata:
  name: prometheus
rules:
  - nonResourceURLs: ["/metrics"]
    verbs: ["get"]
---
apiVersion: v1
kind: ServiceAccount
metadata:
  name: prometheus
  namespace: kube-system
---
apiVersion: rbac.authorization.k8s.io/v1
kind: ClusterRoleBinding
metadata:
  name: prometheus
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: ClusterRole
  name: prometheus
subjects:
  - kind: ServiceAccount
    name: prometheus
    namespace: kube-system
---
apiVersion: v1
kind: ConfigMap
metadata:
  name: prometheus-configmap
  namespace: kube-system
data:
  prometheus.yaml: |
{scrape}
---
apiVersion: v1
kind: Pod
metadata:
  name: prometheus
  namespace: kube-system
spec:
  containers:
    - name: prometheus
      image: {image}
      args:
        - --config.file
        - /etc/prometheus/prometheus.yaml
      ports:
        - name: web
          containerPort: 9090
      securityContext:
        runAsUser: 0
      volumeMounts:
        - name: config-volume
          mountPath: /etc/prometheus/
          readOnly: true
        - mountPath: /etc/kubernetes/pki
          name: k8s-certs
          readOnly: true
  volumes:
    - name: config-volume
      configMap:
        name: prometheus-configmap
    - hostPath:
        path: /etc/kubernetes/pki
        type: DirectoryOrCreate
      name: k8s-certs
  serviceAccountName: prometheus
  restartPolicy: Always
  hostNetwork: true
  nodeName: {name}-control-plane
"""


class KindCluster(Cluster):
    RUNTIME = consts.RUNTIME_TYPE_KIND
    # kind drives kubectl with config view/--context/cordon — beyond the
    # built-in shim's surface, so kubectl download failures must propagate
    KUBECTL_SHIM_OK = False

    # --- helpers ----------------------------------------------------------

    def _control_plane(self) -> str:
        return f"{self.name}-control-plane"

    def _component_pod(self, name: str) -> str:
        # control-plane static pods get the node-name suffix; prometheus is
        # a plain pod (cluster.go getComponentName)
        if name == "prometheus":
            return name
        return f"{name}-{self._control_plane()}"

    def _kind_path(self) -> str:
        found = shutil.which("kind")
        if found:
            return found
        conf = self.config().options
        path = self.bin_path("kind" + conf.binSuffix)
        if not os.path.exists(path):
            download.download_with_cache(
                conf.cacheDir, conf.kindBinary, path, quiet=conf.quietPull
            )
        return path

    # --- install ----------------------------------------------------------

    def install(self) -> None:
        from kwok_tpu.kwokctl import netutil

        config = self.config()
        conf = config.options
        os.makedirs(self.workdir_path("logs"), exist_ok=True)
        if not conf.kubeApiserverPort:
            # pin the host port kind publishes the apiserver on, else base
            # ready()/wait_ready would poll 127.0.0.1:0
            conf.kubeApiserverPort = netutil.get_unused_port()
        audit_policy = audit_log = ""
        if conf.kubeAuditPolicy:
            audit_policy = self.workdir_path(base.AUDIT_POLICY_NAME)
            shutil.copyfile(conf.kubeAuditPolicy, audit_policy)
            audit_log = self.log_path(base.AUDIT_LOG_NAME)
            open(audit_log, "a").close()
        # `a=b,c=d` -> yaml mapping entries `a: b` (cluster.go:59-66)
        fg = [s.replace("=", ": ") for s in conf.kubeFeatureGates.split(",") if s]
        rc = [s.replace("=", ": ") for s in conf.kubeRuntimeConfig.split(",") if s]
        with open(self.workdir_path(KIND_NAME), "w") as f:
            f.write(build_kind_yaml(
                kube_apiserver_port=conf.kubeApiserverPort,
                prometheus_port=conf.prometheusPort,
                feature_gates=fg,
                runtime_config=rc,
                audit_policy=audit_policy,
                audit_log=audit_log,
                config_path=self.workdir_path(base.CONFIG_NAME),
            ))
        with open(self.workdir_path(KWOK_POD_NAME), "w") as f:
            f.write(build_kwok_controller_pod(conf.kwokControllerImage))
        if conf.prometheusPort:
            with open(self.workdir_path(PROMETHEUS_DEPLOY_NAME), "w") as f:
                f.write(build_prometheus_deployment(self.name, conf.prometheusImage))
        self._pull_images()
        self.save()

    def _pull_images(self) -> None:
        for image in self.list_images():
            if not image:
                continue
            if subprocess.run(["docker", "image", "inspect", image],
                              capture_output=True).returncode == 0:
                continue
            self._run(["docker", "pull", image])

    # --- up/down ----------------------------------------------------------

    def up(self, timeout: float = 120.0) -> None:
        from kwok_tpu.config.ctl import Component

        config = self.config()
        conf = config.options
        # the component list is rebuilt below; clear any previously saved one
        # so the disable-component path doesn't trip the existence guard
        config.components = []
        kind = self._kind_path()
        self._run([
            kind, "create", "cluster",
            "--config", self.workdir_path(KIND_NAME),
            "--name", self.name,
            "--image", conf.kindNodeImage,
            "--wait", f"{max(int(timeout), 60)}s",
        ])
        images = [conf.kwokControllerImage]
        if conf.prometheusPort:
            images.append(conf.prometheusImage)
        for image in images:
            self._run([kind, "load", "docker-image", image, "--name", self.name])
        # snapshot the kubeconfig kind just wrote into the default config
        res = self._run(
            [self.kubectl_path(), "config", "view", "--minify=true", "--raw=true",
             "--context", f"kind-{self.name}"],
            capture=True,
        )
        with open(self.workdir_path(base.IN_HOST_KUBECONFIG_NAME), "w") as f:
            f.write(res.stdout)
        # the engine enters as a kubelet static pod
        self._run([
            "docker", "cp", self.workdir_path(KWOK_POD_NAME),
            f"{self._control_plane()}:/etc/kubernetes/manifests/kwok-controller.yaml",
        ])
        components = ["etcd", "kube-apiserver", "kwok-controller"]
        if conf.prometheusPort:
            self._run([self.kubectl_path(), "--context", f"kind-{self.name}",
                       "apply", "-f", self.workdir_path(PROMETHEUS_DEPLOY_NAME)])
            components.append("prometheus")
        # nothing schedules onto the real node; fake nodes only
        self._run([self.kubectl_path(), "--context", f"kind-{self.name}",
                   "cordon", self._control_plane()], check=False)
        if conf.disableKubeScheduler:
            self.stop_component("kube-scheduler")
        else:
            components.append("kube-scheduler")
        if conf.disableKubeControllerManager:
            self.stop_component("kube-controller-manager")
        else:
            components.append("kube-controller-manager")
        config.components = [Component(name=n) for n in components]
        self.save()

    def down(self) -> None:
        self._run([self._kind_path(), "delete", "cluster", "--name", self.name],
                  check=False)

    def start(self) -> None:
        self._run(["docker", "start", self._control_plane()])

    def stop(self) -> None:
        self._run(["docker", "stop", self._control_plane()])

    def start_component(self, name: str) -> None:
        """Static pods: move the parked manifest back (cluster.go:407-413).
        prometheus is a kubectl-applied plain pod, so re-apply it."""
        if self.config().components:
            self.get_component(name)
        if name == "prometheus":
            self._run([self.kubectl_path(), "--context", f"kind-{self.name}",
                       "apply", "-f", self.workdir_path(PROMETHEUS_DEPLOY_NAME)])
            return
        self._run(["docker", "exec", self._control_plane(), "mv",
                   f"/etc/kubernetes/{name}.yaml.bak",
                   f"/etc/kubernetes/manifests/{name}.yaml"])

    def stop_component(self, name: str) -> None:
        """Park the static-pod manifest outside the manifests dir
        (cluster.go:415-421); delete the plain prometheus pod."""
        if self.config().components:
            self.get_component(name)
        if name == "prometheus":
            self._run([self.kubectl_path(), "--context", f"kind-{self.name}",
                       "delete", "pod", "-n", "kube-system", "prometheus",
                       "--ignore-not-found"])
            return
        self._run(["docker", "exec", self._control_plane(), "mv",
                   f"/etc/kubernetes/manifests/{name}.yaml",
                   f"/etc/kubernetes/{name}.yaml.bak"])

    # --- readiness --------------------------------------------------------

    def ready(self) -> bool:
        """Apiserver healthy AND every kube-system pod Running AND Ready
        (cluster.go:327-372 checks the phase; the Ready condition is what
        the kwok-controller's /readyz-gated readiness probe feeds, so a
        Running pod still warming up must hold WaitReady back)."""
        if not super().ready():
            return False
        res = self._run(
            [self.kubectl_path(), "--kubeconfig",
             self.workdir_path(base.IN_HOST_KUBECONFIG_NAME),
             "get", "pod", "--namespace=kube-system", "--output=json"],
            capture=True, check=False,
        )
        if res.returncode != 0:
            return False
        try:
            data = json.loads(res.stdout)
        except json.JSONDecodeError:
            return False
        for pod in data.get("items") or []:
            status = pod.get("status") or {}
            if status.get("phase") != "Running":
                return False
            conds = {
                c.get("type"): c.get("status")
                for c in status.get("conditions") or []
            }
            if conds.get("Ready") != "True":
                return False
        return True

    # --- logs -------------------------------------------------------------

    def logs(self, name: str, out, follow: bool = False) -> None:
        args = [self.kubectl_path(), "--context", f"kind-{self.name}",
                "logs", "-n", "kube-system"]
        if follow:
            args.append("-f")
        args.append(self._component_pod(name))
        proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        try:
            assert proc.stdout is not None
            for line in proc.stdout:
                out.write(line)
        finally:
            if proc.poll() is None:
                proc.terminate()
            proc.wait()

    # --- artifacts --------------------------------------------------------

    def list_binaries(self) -> list[str]:
        conf = self.config().options
        return [conf.kubectlBinary]

    def list_images(self) -> list[str]:
        conf = self.config().options
        images = [conf.kindNodeImage, conf.kwokControllerImage]
        if conf.prometheusPort:
            images.append(conf.prometheusImage)
        return images

    # --- etcdctl / snapshot ----------------------------------------------

    _ETCDCTL_CERTS = [
        "--endpoints=127.0.0.1:2379",
        "--cert=/etc/kubernetes/pki/etcd/server.crt",
        "--key=/etc/kubernetes/pki/etcd/server.key",
        "--cacert=/etc/kubernetes/pki/etcd/ca.crt",
    ]

    def etcdctl_in_cluster(self, args: list[str], **kwargs) -> int:
        from kwok_tpu.kwokctl import procutil

        return procutil.exec_foreground(
            [self.kubectl_path(), "--kubeconfig",
             self.workdir_path(base.IN_HOST_KUBECONFIG_NAME),
             "exec", "-i", "-n", "kube-system", self._component_pod("etcd"), "--",
             "etcdctl", *self._ETCDCTL_CERTS, *args],
            **kwargs,
        )

    def snapshot_save(self, path: str) -> None:
        """etcdctl save into /var/lib/etcd (the one dir shared with the kind
        node container), docker cp out, clean up (cluster_snapshot.go:30-58)."""
        tmp = "/var/lib/etcd/snapshot.db"
        rc = self.etcdctl_in_cluster(["snapshot", "save", tmp])
        if rc != 0:
            raise RuntimeError(f"etcdctl snapshot save failed with {rc}")
        try:
            self._run(["docker", "cp", f"{self._control_plane()}:{tmp}", path])
        finally:
            self._run(["docker", "exec", "-i", self._control_plane(),
                       "rm", "-f", tmp], check=False)

    def snapshot_restore(self, path: str) -> None:
        """Host etcdctl restore -> docker cp into /var/lib/ around an etcd
        static-pod stop/start (cluster_snapshot.go:61-110)."""
        etcdctl = self.etcdctl_path()
        self.stop_component("etcd")
        # stage under a different name, then swap atomically: a failed cp
        # must leave the original /var/lib/etcd untouched
        tmp_dir = self.workdir_path("etcd.new")
        shutil.rmtree(tmp_dir, ignore_errors=True)
        try:
            self._run([etcdctl, "snapshot", "restore", path, "--data-dir", tmp_dir])
            # a previously interrupted restore may have left /var/lib/etcd.new
            # in the container; docker cp would merge into it
            self._run(["docker", "exec", self._control_plane(),
                       "rm", "-rf", "/var/lib/etcd.new"], check=False)
            self._run(["docker", "cp", tmp_dir, f"{self._control_plane()}:/var/lib/"])
            self._run(["docker", "exec", self._control_plane(), "sh", "-c",
                       "rm -rf /var/lib/etcd && mv /var/lib/etcd.new /var/lib/etcd"])
        finally:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            self.start_component("etcd")
