"""Binary runtime: real control-plane processes around the TPU engine.

Behavioral port of pkg/kwokctl/runtime/binary/cluster.go: download the
upstream etcd/kube-apiserver/kube-controller-manager/kube-scheduler binaries
with a shared cache (:56-116), generate PKI (:125-131), allocate free ports
for port-0 options (:156-167), build declarative Component specs (:169-453),
then start them in link-order waves with pid-file supervision and retry
until the apiserver reports healthy (:455-520); stop in reverse (:526-545).

The kwok-controller component is THIS package's engine: install() writes a
`kwok-controller` shim script that execs `python -m kwok_tpu.kwok`, so the
component model (binary + argv + pid/log files) stays uniform with the
reference while the engine itself runs JAX.
"""

from __future__ import annotations

import logging
import os
import stat
import subprocess
import sys
import time

from kwok_tpu.kwokctl import components as comp
from kwok_tpu.kwokctl import download, k8s, netutil, pki, procutil
from kwok_tpu.kwokctl.runtime import base
from kwok_tpu.kwokctl.runtime.base import Cluster

LOCAL = "127.0.0.1"

logger = logging.getLogger("kwok_tpu.kwokctl.binary")


class BinaryCluster(Cluster):
    RUNTIME = "binary"

    # --- install ----------------------------------------------------------

    def install(self) -> None:
        conf = self.config().options
        self._download_binaries()
        self._setup_workdir()
        self._setup_ports()
        self._build_components()
        self._write_kubeconfig()
        self.save()

    def _download_binaries(self) -> None:
        conf = self.config().options
        cache = conf.cacheDir
        quiet = conf.quietPull
        download.download_with_cache(
            cache, conf.kubeApiserverBinary, self.bin_path("kube-apiserver"), quiet=quiet
        )
        if not conf.disableKubeControllerManager:
            download.download_with_cache(
                cache,
                conf.kubeControllerManagerBinary,
                self.bin_path("kube-controller-manager"),
                quiet=quiet,
            )
        if not conf.disableKubeScheduler:
            download.download_with_cache(
                cache, conf.kubeSchedulerBinary, self.bin_path("kube-scheduler"), quiet=quiet
            )
        if conf.etcdBinary:
            download.download_with_cache(
                cache, conf.etcdBinary, self.bin_path("etcd"), quiet=quiet
            )
        else:
            download.download_with_cache_and_extract(
                cache, conf.etcdBinaryTar, self.bin_path("etcd"), "etcd", quiet=quiet
            )
        if conf.prometheusPort:
            if conf.prometheusBinary:
                download.download_with_cache(
                    cache, conf.prometheusBinary, self.bin_path("prometheus"), quiet=quiet
                )
            else:
                download.download_with_cache_and_extract(
                    cache,
                    conf.prometheusBinaryTar,
                    self.bin_path("prometheus"),
                    "prometheus",
                    quiet=quiet,
                )
        self._write_kwok_shim()
        self._verify_versions()

    def _verify_versions(self) -> None:
        """Probe `<bin> --version` on the fetched control-plane binaries and
        warn when a custom binary disagrees with the configured version —
        version-keyed arg matrices (feature gates, etcd prefix) would be
        wrong (pkg/utils/version ParseFromBinary usage)."""
        from kwok_tpu.kwokctl import version as verlib

        conf = self.config().options
        detected = verlib.parse_from_binary(self.bin_path("kube-apiserver"))
        if detected and conf.kubeVersion and not detected.startswith(
            conf.kubeVersion.split("-")[0]
        ):
            logger.warning(
                "kube-apiserver reports %s but the cluster is configured "
                "for %s; version-keyed defaults may not match",
                detected,
                conf.kubeVersion,
            )

    def _write_kwok_shim(self) -> None:
        """The engine 'binary': a generated script running this package's
        kwok CLI under the installing interpreter (with its module paths
        baked in, so it works however the orchestrator was launched)."""
        shim = self.bin_path("kwok-controller")
        os.makedirs(os.path.dirname(shim), exist_ok=True)
        paths = [p for p in sys.path if p]
        with open(shim, "w") as f:
            f.write(
                f"#!{sys.executable}\n"
                "# generated kwok-controller shim (kwok_tpu binary runtime)\n"
                "import sys\n"
                f"sys.path[:0] = {paths!r}\n"
                "from kwok_tpu.kwok.cli import main\n"
                "sys.exit(main(sys.argv[1:]))\n"
            )
        os.chmod(shim, os.stat(shim).st_mode | stat.S_IEXEC | stat.S_IXGRP | stat.S_IXOTH)

    def _setup_workdir(self) -> None:
        conf = self.config().options
        pki_path = self.workdir_path(base.PKI_NAME)
        if not os.path.exists(os.path.join(pki_path, "ca.crt")):
            pki.generate_pki(pki_path)
        os.makedirs(self.workdir_path(base.ETCD_DATA_DIR_NAME), exist_ok=True)
        os.makedirs(self.workdir_path("logs"), exist_ok=True)
        if conf.kubeAuditPolicy:
            self._setup_audit_files(conf.kubeAuditPolicy)

    def _setup_ports(self) -> None:
        conf = self.config().options
        for field in (
            "etcdPeerPort",
            "etcdPort",
            "kubeApiserverPort",
            "kwokControllerPort",
            "kubeControllerManagerPort",
            "kubeSchedulerPort",
        ):
            if field == "kubeControllerManagerPort" and conf.disableKubeControllerManager:
                continue
            if field == "kubeSchedulerPort" and conf.disableKubeScheduler:
                continue
            if not getattr(conf, field):
                setattr(conf, field, netutil.get_unused_port())

    def _build_components(self) -> None:
        config = self.config()
        conf = config.options
        workdir = self.workdir
        pki_dir = self.workdir_path(base.PKI_NAME)
        ca_crt = os.path.join(pki_dir, "ca.crt")
        admin_crt = os.path.join(pki_dir, "admin.crt")
        admin_key = os.path.join(pki_dir, "admin.key")
        kubeconfig = self.workdir_path(base.IN_HOST_KUBECONFIG_NAME)
        audit_policy = audit_log = ""
        if conf.kubeAuditPolicy:
            audit_policy = self.workdir_path(base.AUDIT_POLICY_NAME)
            audit_log = self.log_path(base.AUDIT_LOG_NAME)

        cs = [
            comp.build_etcd(
                binary=self.bin_path("etcd"),
                data_path=self.workdir_path(base.ETCD_DATA_DIR_NAME),
                workdir=workdir,
                version=conf.etcdVersion,
                address=LOCAL,
                port=conf.etcdPort,
                peer_port=conf.etcdPeerPort,
            ),
            comp.build_kube_apiserver(
                binary=self.bin_path("kube-apiserver"),
                workdir=workdir,
                port=conf.kubeApiserverPort,
                version=conf.kubeVersion,
                # 0.0.0.0 makes a containerized cluster reachable through
                # published ports (images/cluster); clients still use
                # 127.0.0.1 via the kubeconfig
                address=conf.bindAddress or LOCAL,
                etcd_port=conf.etcdPort,
                runtime_config=conf.kubeRuntimeConfig,
                feature_gates=conf.kubeFeatureGates,
                secure_port=bool(conf.securePort),
                authorization=conf.kubeAuthorization,
                audit_policy_path=audit_policy,
                audit_log_path=audit_log,
                ca_cert_path=ca_crt,
                admin_cert_path=admin_crt,
                admin_key_path=admin_key,
            ),
        ]
        if not conf.disableKubeControllerManager:
            cs.append(
                comp.build_kube_controller_manager(
                    binary=self.bin_path("kube-controller-manager"),
                    workdir=workdir,
                    kubeconfig_path=kubeconfig,
                    port=conf.kubeControllerManagerPort,
                    version=conf.kubeVersion,
                    address=LOCAL,
                    secure_port=bool(conf.securePort),
                    authorization=conf.kubeAuthorization,
                    feature_gates=conf.kubeFeatureGates,
                    ca_cert_path=ca_crt,
                    admin_key_path=admin_key,
                )
            )
        if not conf.disableKubeScheduler:
            cs.append(
                comp.build_kube_scheduler(
                    binary=self.bin_path("kube-scheduler"),
                    workdir=workdir,
                    kubeconfig_path=kubeconfig,
                    port=conf.kubeSchedulerPort,
                    version=conf.kubeVersion,
                    address=LOCAL,
                    secure_port=bool(conf.securePort),
                    feature_gates=conf.kubeFeatureGates,
                )
            )
        cs.append(
            comp.build_kwok_controller(
                binary=self.bin_path("kwok-controller"),
                workdir=workdir,
                kubeconfig_path=kubeconfig,
                config_path=self.workdir_path(base.CONFIG_NAME),
                port=conf.kwokControllerPort,
                address=LOCAL,
            )
        )
        if conf.prometheusPort:
            prom_cfg = comp.build_prometheus_config(
                project_name=self.name,
                etcd_port=conf.etcdPort,
                kube_apiserver_port=conf.kubeApiserverPort,
                kube_controller_manager_port=0
                if conf.disableKubeControllerManager
                else conf.kubeControllerManagerPort,
                kube_scheduler_port=0
                if conf.disableKubeScheduler
                else conf.kubeSchedulerPort,
                kwok_controller_port=conf.kwokControllerPort,
                secure_port=bool(conf.securePort),
                admin_crt_path=admin_crt,
                admin_key_path=admin_key,
            )
            prom_path = self.workdir_path(base.PROMETHEUS_NAME)
            with open(prom_path, "w") as f:
                f.write(prom_cfg)
            cs.append(
                comp.build_prometheus(
                    binary=self.bin_path("prometheus"),
                    workdir=workdir,
                    config_path=prom_path,
                    port=conf.prometheusPort,
                    version=conf.prometheusVersion,
                    address=LOCAL,
                    links=[c.name for c in cs],
                )
            )
        config.components = cs

    def _write_kubeconfig(self) -> None:
        conf = self.config().options
        pki_dir = self.workdir_path(base.PKI_NAME)
        scheme = "https" if conf.securePort else "http"
        data = k8s.build_kubeconfig(
            project_name=self.name,
            address=f"{scheme}://{LOCAL}:{conf.kubeApiserverPort}",
            secure_port=bool(conf.securePort),
            admin_crt_path=os.path.join(pki_dir, "admin.crt"),
            admin_key_path=os.path.join(pki_dir, "admin.key"),
        )
        with open(self.workdir_path(base.IN_HOST_KUBECONFIG_NAME), "w") as f:
            f.write(data)

    # --- up/down ----------------------------------------------------------

    def up(self, timeout: float = 120.0) -> None:
        """Start all components in link waves; retry the whole sequence until
        the apiserver is healthy and every pid is live (cluster.go:455-520)."""
        config = self.config()
        groups = comp.group_by_links(config.components)
        deadline = time.monotonic() + timeout
        while True:
            for group in groups:
                for c in group:
                    procutil.fork_exec(c.workDir or self.workdir, c.binary, *c.args)
            if self.ready() and all(
                procutil.is_running(c.workDir or self.workdir, c.binary)
                for g in groups
                for c in g
            ):
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"cluster {self.name} failed to come up within {timeout}s; "
                    f"see {self.workdir_path('logs')}"
                )
            time.sleep(1.0)

    def down(self) -> None:
        config = self.config()
        groups = comp.group_by_links(config.components)
        for group in reversed(groups):
            for c in group:
                procutil.fork_exec_kill(c.workDir or self.workdir, c.binary)

    def start_component(self, name: str) -> None:
        c = self.get_component(name)
        procutil.fork_exec(c.workDir or self.workdir, c.binary, *c.args)

    def stop_component(self, name: str) -> None:
        c = self.get_component(name)
        procutil.fork_exec_kill(c.workDir or self.workdir, c.binary)

    # --- artifacts --------------------------------------------------------

    def list_binaries(self) -> list[str]:
        conf = self.config().options
        return [
            conf.etcdBinaryTar,
            conf.kubeApiserverBinary,
            conf.kubeControllerManagerBinary,
            conf.kubeSchedulerBinary,
            conf.kubectlBinary,
            conf.prometheusBinaryTar,
        ]

    # --- etcdctl / snapshot ----------------------------------------------

    def etcdctl_in_cluster(self, args: list[str], **kwargs) -> int:
        conf = self.config().options
        return procutil.exec_foreground(
            [
                self.etcdctl_path(),
                "--endpoints",
                f"{LOCAL}:{conf.etcdPort}",
                *args,
            ],
            **kwargs,
        )

    def snapshot_save(self, path: str) -> None:
        """etcdctl snapshot save (cluster_snapshot.go:31-51)."""
        rc = self.etcdctl_in_cluster(["snapshot", "save", path])
        if rc != 0:
            raise RuntimeError(f"etcdctl snapshot save failed with {rc}")

    def snapshot_restore(self, path: str) -> None:
        """Stop etcd -> restore into a fresh data dir -> swap -> restart
        (cluster_snapshot.go:54-100)."""
        import shutil

        self.stop_component("etcd")
        data_dir = self.workdir_path(base.ETCD_DATA_DIR_NAME)
        tmp_dir = data_dir + ".restore"
        shutil.rmtree(tmp_dir, ignore_errors=True)
        rc = subprocess.call(
            [self.etcdctl_path(), "snapshot", "restore", path, "--data-dir", tmp_dir]
        )
        if rc != 0:
            raise RuntimeError(f"etcdctl snapshot restore failed with {rc}")
        shutil.rmtree(data_dir, ignore_errors=True)
        os.replace(tmp_dir, data_dir)
        self.start_component("etcd")
