"""Compose runtime: the control plane as docker/nerdctl containers.

Behavioral port of pkg/kwokctl/runtime/compose: install() builds the same
declarative Component specs as the binary runtime but in image mode
(in-container paths + published ports), converts them to a docker-compose v3
document (compose.go:28-85: entrypoint=command, command=args, restart:
always, bind volumes, ingress ports, links, per-project network), and
up/down/start/stop shells out to `<runtime> compose` with the reference's
nerdctl quirks (cluster.go:525-566: nerdctl start = `up -d`, stop = `down`
plus an etcd snapshot round-trip so state survives `down`).

Liveness is `compose ps --format=json`: every service must be "running"
(cluster.go:463-505). Snapshots: save = etcdctl inside the etcd container +
`cp` out (cluster_snapshot.go:30-52); restore = host etcdctl rebuilds a data
dir which is `cp`'d back in (:55-140).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess

from kwok_tpu.config.ctl import Component
from kwok_tpu.kwokctl import components as comp
from kwok_tpu.kwokctl import consts, download, k8s, netutil, pki
from kwok_tpu.kwokctl.runtime import base
from kwok_tpu.kwokctl.runtime.base import Cluster

COMPOSE_NAME = "docker-compose.yaml"
IN_CLUSTER_KUBECONFIG_NAME = "kubeconfig"


def components_to_compose(project: str, components: list[Component]) -> dict:
    """Component list -> docker-compose v3 document (compose.go:28-85)."""
    services: dict[str, dict] = {}
    for c in components:
        svc: dict = {
            "container_name": f"{project}-{c.name}",
            "image": c.image,
            "restart": "always",
            "entrypoint": list(c.command),
        }
        if c.links:
            svc["links"] = list(c.links)
        if c.args:
            svc["command"] = list(c.args)
        if c.ports:
            svc["ports"] = [
                {
                    "mode": "ingress",
                    "target": p.port,
                    "published": str(p.hostPort),
                    "protocol": p.protocol.lower(),
                }
                for p in c.ports
            ]
        if c.envs:
            svc["environment"] = {e.name: e.value for e in c.envs}
        if c.volumes:
            svc["volumes"] = [
                {
                    "type": "bind",
                    "source": v.hostPath,
                    "target": v.mountPath,
                    **({"read_only": True} if v.readOnly else {}),
                }
                for v in c.volumes
            ]
        services[c.name] = svc
    return {
        "version": "3",
        "services": services,
        "networks": {"default": {"name": project}},
    }


def dump_compose_yaml(doc: dict) -> str:
    import yaml

    return yaml.safe_dump(doc, sort_keys=False)


class ComposeCluster(Cluster):
    """Shared docker/nerdctl backend; `options.runtime` picks the CLI."""

    RUNTIME = consts.RUNTIME_TYPE_DOCKER

    # --- helpers ----------------------------------------------------------

    def _runtime_bin(self) -> str:
        return self.config().options.runtime or consts.RUNTIME_TYPE_DOCKER

    def _project(self) -> str:
        return f"{consts.PROJECT_NAME}-{self.name}"

    def _container(self, component: str) -> str:
        return f"{self._project()}-{component}"

    def _run(self, args: list, capture: bool = False, check: bool = True,
             cwd: str | None = None):
        """Container-CLI commands run from the workdir (where the compose
        file lives)."""
        return super()._run(args, capture=capture, check=check,
                            cwd=cwd or self.workdir)

    _compose_prefix: list[str] | None = None

    def _compose_cmd(self, *args: str) -> list[str]:
        """`<runtime> compose <args>`, falling back to a downloaded
        docker-compose binary when the docker CLI lacks the subcommand
        (cluster.go buildComposeCommands). The probe result is cached per
        instance — up()'s retry loop calls this every second."""
        if self._compose_prefix is None:
            rt = self._runtime_bin()
            prefix = [rt, "compose"]
            if rt == consts.RUNTIME_TYPE_DOCKER:
                probe = subprocess.run(
                    [rt, "compose", "version"], capture_output=True, text=True
                )
                if probe.returncode != 0:
                    conf = self.config().options
                    path = self.bin_path("docker-compose" + conf.binSuffix)
                    if not os.path.exists(path):
                        download.download_with_cache(
                            conf.cacheDir, conf.dockerComposeBinary, path,
                            quiet=conf.quietPull,
                        )
                    prefix = [path]
            self._compose_prefix = prefix
        return [*self._compose_prefix, *args]

    # --- install ----------------------------------------------------------

    def install(self) -> None:
        config = self.config()
        conf = config.options
        self._setup_workdir()
        if not conf.kubeApiserverPort:
            conf.kubeApiserverPort = netutil.get_unused_port()
        if not conf.kwokControllerPort:
            conf.kwokControllerPort = netutil.get_unused_port()
        self._pull_images()
        self._build_components()
        self._write_kubeconfigs()
        with open(self.workdir_path(COMPOSE_NAME), "w") as f:
            f.write(dump_compose_yaml(
                components_to_compose(self._project(), config.components)
            ))
        self.save()

    def _setup_workdir(self) -> None:
        conf = self.config().options
        pki_path = self.workdir_path(base.PKI_NAME)
        if not os.path.exists(os.path.join(pki_path, "ca.crt")):
            pki.generate_pki(pki_path)
        # no host etcd dir: image mode keeps data at /etcd-data in-container
        os.makedirs(self.workdir_path("logs"), exist_ok=True)
        if conf.kubeAuditPolicy:
            shutil.copyfile(conf.kubeAuditPolicy, self.workdir_path(base.AUDIT_POLICY_NAME))
            open(self.log_path(base.AUDIT_LOG_NAME), "a").close()

    def _pull_images(self) -> None:
        conf = self.config().options
        for image in self.list_images():
            if not image:
                continue
            inspect = subprocess.run(
                [self._runtime_bin(), "image", "inspect", image],
                capture_output=True,
            )
            if inspect.returncode == 0:
                continue
            self._run([self._runtime_bin(), "pull", image], check=True)

    def _build_components(self) -> None:
        config = self.config()
        conf = config.options
        workdir = self.workdir
        pki_dir = self.workdir_path(base.PKI_NAME)
        ca_crt = os.path.join(pki_dir, "ca.crt")
        admin_crt = os.path.join(pki_dir, "admin.crt")
        admin_key = os.path.join(pki_dir, "admin.key")
        in_cluster_kubeconfig = self.workdir_path(IN_CLUSTER_KUBECONFIG_NAME)
        audit_policy = audit_log = ""
        if conf.kubeAuditPolicy:
            audit_policy = self.workdir_path(base.AUDIT_POLICY_NAME)
            audit_log = self.log_path(base.AUDIT_LOG_NAME)

        cs = [
            comp.build_etcd(
                image=conf.etcdImage,
                workdir=workdir,
                version=conf.etcdVersion,
                address="0.0.0.0",
            ),
            comp.build_kube_apiserver(
                image=conf.kubeApiserverImage,
                workdir=workdir,
                port=conf.kubeApiserverPort,
                version=conf.kubeVersion,
                etcd_address=self._container("etcd"),
                etcd_port=2379,
                runtime_config=conf.kubeRuntimeConfig,
                feature_gates=conf.kubeFeatureGates,
                secure_port=bool(conf.securePort),
                authorization=conf.kubeAuthorization,
                audit_policy_path=audit_policy,
                audit_log_path=audit_log,
                ca_cert_path=ca_crt,
                admin_cert_path=admin_crt,
                admin_key_path=admin_key,
            ),
        ]
        if not conf.disableKubeControllerManager:
            cs.append(
                comp.build_kube_controller_manager(
                    image=conf.kubeControllerManagerImage,
                    workdir=workdir,
                    kubeconfig_path=in_cluster_kubeconfig,
                    version=conf.kubeVersion,
                    secure_port=bool(conf.securePort),
                    authorization=conf.kubeAuthorization,
                    feature_gates=conf.kubeFeatureGates,
                    ca_cert_path=ca_crt,
                    admin_cert_path=admin_crt,
                    admin_key_path=admin_key,
                )
            )
        if not conf.disableKubeScheduler:
            cs.append(
                comp.build_kube_scheduler(
                    image=conf.kubeSchedulerImage,
                    workdir=workdir,
                    kubeconfig_path=in_cluster_kubeconfig,
                    version=conf.kubeVersion,
                    secure_port=bool(conf.securePort),
                    feature_gates=conf.kubeFeatureGates,
                    admin_cert_path=admin_crt,
                    admin_key_path=admin_key,
                )
            )
        cs.append(
            comp.build_kwok_controller(
                image=conf.kwokControllerImage,
                workdir=workdir,
                kubeconfig_path=in_cluster_kubeconfig,
                config_path=self.workdir_path(base.CONFIG_NAME),
                port=conf.kwokControllerPort,
                version=conf.kwokVersion,
                admin_cert_path=admin_crt,
                admin_key_path=admin_key,
            )
        )
        if conf.prometheusPort:
            prom_cfg = comp.build_prometheus_config_compose(
                project_name=self._project(),
                secure_port=bool(conf.securePort),
                kube_controller_manager=not conf.disableKubeControllerManager,
                kube_scheduler=not conf.disableKubeScheduler,
            )
            prom_path = self.workdir_path(base.PROMETHEUS_NAME)
            with open(prom_path, "w") as f:
                f.write(prom_cfg)
            cs.append(
                comp.build_prometheus(
                    image=conf.prometheusImage,
                    workdir=workdir,
                    config_path=prom_path,
                    port=conf.prometheusPort,
                    version=conf.prometheusVersion,
                    links=[c.name for c in cs],
                    admin_cert_path=admin_crt,
                    admin_key_path=admin_key,
                )
            )
        config.components = cs

    def _write_kubeconfigs(self) -> None:
        conf = self.config().options
        pki_dir = self.workdir_path(base.PKI_NAME)
        scheme = "https" if conf.securePort else "http"
        host_port = conf.kubeApiserverPort
        data = k8s.build_kubeconfig(
            project_name=self.name,
            address=f"{scheme}://127.0.0.1:{host_port}",
            secure_port=bool(conf.securePort),
            admin_crt_path=os.path.join(pki_dir, "admin.crt"),
            admin_key_path=os.path.join(pki_dir, "admin.key"),
        )
        with open(self.workdir_path(base.IN_HOST_KUBECONFIG_NAME), "w") as f:
            f.write(data)
        # in-cluster flavor: container DNS name + in-container port and
        # in-container cert paths (compose/cluster.go:341-352)
        in_port = 6443 if conf.securePort else 8080
        in_data = k8s.build_kubeconfig(
            project_name=self.name,
            address=f"{scheme}://{self._container('kube-apiserver')}:{in_port}",
            secure_port=bool(conf.securePort),
            admin_crt_path=f"{comp.IN_CONTAINER_PKI}/admin.crt",
            admin_key_path=f"{comp.IN_CONTAINER_PKI}/admin.key",
        )
        with open(self.workdir_path(IN_CLUSTER_KUBECONFIG_NAME), "w") as f:
            f.write(in_data)

    # --- up/down/start/stop ----------------------------------------------

    def up(self, timeout: float = 120.0) -> None:
        import time

        conf = self.config().options
        args = ["up", "-d"]
        if conf.quietPull:
            args.append("--quiet-pull")
        deadline = time.monotonic() + timeout
        while True:
            res = self._run(self._compose_cmd(*args), check=False)
            if res.returncode == 0 and self.is_running():
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"cluster {self.name} failed to come up within {timeout}s"
                )
            time.sleep(1.0)

    def is_running(self) -> bool:
        """All compose services report state running
        (cluster.go:463-505). Accepts both a JSON array (docker compose
        v2.20 and earlier) and NDJSON (later)."""
        res = self._run(self._compose_cmd("ps", "--format=json"),
                        capture=True, check=False)
        if res.returncode != 0:
            return False
        text = (res.stdout or "").strip()
        if not text:
            return False
        try:
            items = json.loads(text)
            if isinstance(items, dict):
                items = [items]
        except json.JSONDecodeError:
            items = []
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    items.append(json.loads(line))
                except json.JSONDecodeError:
                    return False  # garbled output counts as not-ready
        if not items:
            return False
        states = {
            str(i.get("Service", i.get("Name", ""))):
            str(i.get("State", i.get("state", ""))).lower()
            for i in items
        }
        # `ps` omits exited containers entirely, so also require every
        # expected component to be present (cluster.go checks each one)
        for c in self.config().components:
            name = c.name
            state = states.get(name) or next(
                (s for n, s in states.items() if name in n), None
            )
            if state != "running":
                return False
        return True

    def down(self) -> None:
        self._run(self._compose_cmd("down"), check=False)

    def start(self) -> None:
        conf = self.config().options
        if conf.runtime == consts.RUNTIME_TYPE_NERDCTL:
            # nerdctl lacks `compose start` (cluster.go:525-531)
            self._run(self._compose_cmd("up", "-d"))
            backup = self.workdir_path("restart.db")
            if os.path.isfile(backup):
                self.snapshot_restore(backup)
                os.remove(backup)
        else:
            self._run(self._compose_cmd("start"))

    def stop(self) -> None:
        conf = self.config().options
        if conf.runtime == consts.RUNTIME_TYPE_NERDCTL:
            # nerdctl lacks `compose stop`; snapshot so `down` loses nothing
            # (cluster.go:570-580)
            self.snapshot_save(self.workdir_path("restart.db"))
            self._run(self._compose_cmd("down"))
        else:
            self._run(self._compose_cmd("stop"))

    def start_component(self, name: str) -> None:
        self.get_component(name)
        self._run([self._runtime_bin(), "start", self._container(name)])

    def stop_component(self, name: str) -> None:
        self.get_component(name)
        self._run([self._runtime_bin(), "stop", self._container(name)])

    # --- logs -------------------------------------------------------------

    def logs(self, name: str, out, follow: bool = False) -> None:
        """Stream `<runtime> logs [-f]`; -f never exits, so output must be
        piped through as it arrives, not captured."""
        self.get_component(name)
        args = [self._runtime_bin(), "logs"]
        if follow:
            args.append("-f")
        args.append(self._container(name))
        proc = subprocess.Popen(
            args, cwd=self.workdir, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        try:
            assert proc.stdout is not None
            for line in proc.stdout:
                out.write(line)
        finally:
            if proc.poll() is None:
                proc.terminate()
            proc.wait()

    # --- artifacts --------------------------------------------------------

    def list_binaries(self) -> list[str]:
        conf = self.config().options
        return [conf.kubectlBinary]

    def list_images(self) -> list[str]:
        conf = self.config().options
        images = [conf.etcdImage, conf.kubeApiserverImage, conf.kwokControllerImage]
        if not conf.disableKubeControllerManager:
            images.append(conf.kubeControllerManagerImage)
        if not conf.disableKubeScheduler:
            images.append(conf.kubeSchedulerImage)
        if conf.prometheusPort:
            images.append(conf.prometheusImage)
        return images

    # --- etcdctl / snapshot ----------------------------------------------

    def etcdctl_in_cluster(self, args: list[str], **kwargs) -> int:
        from kwok_tpu.kwokctl import procutil

        return procutil.exec_foreground(
            [self._runtime_bin(), "exec", "-i", self._container("etcd"), "etcdctl",
             *args],
            **kwargs,
        )

    def snapshot_save(self, path: str) -> None:
        """etcdctl snapshot save inside the container, then cp out
        (cluster_snapshot.go:30-52)."""
        tmp = "/snapshot.db"
        self._run([self._runtime_bin(), "exec", "-i", self._container("etcd"),
                   "etcdctl", "snapshot", "save", tmp])
        self._run([self._runtime_bin(), "cp", f"{self._container('etcd')}:{tmp}", path])

    def snapshot_restore(self, path: str) -> None:
        """Host etcdctl rebuilds a data dir; cp it into the container
        around an etcd restart (cluster_snapshot.go:55-140)."""
        etcdctl = self.etcdctl_path()
        tmp_dir = self.workdir_path("etcd-data")
        shutil.rmtree(tmp_dir, ignore_errors=True)
        self._run([etcdctl, "snapshot", "restore", path, "--data-dir", tmp_dir])
        rt = self._runtime_bin()
        etcd_ctr = self._container("etcd")
        try:
            # Freeze the only writer, then swap the data dir underneath the
            # (still-running) etcd and bounce it. `cp` into a live container
            # works on docker AND nerdctl (nerdctl cp can't touch stopped
            # containers), and the exec rm first matters: `cp dir ctr:/`
            # MERGES into an existing /etcd-data, which would leave stale
            # WAL/snap files alongside the restored ones.
            self.stop_component("kube-apiserver")
            try:
                self._run([rt, "exec", etcd_ctr, "rm", "-rf", "/etcd-data"],
                          check=False)
                self._run([rt, "cp", tmp_dir, f"{etcd_ctr}:/"])
                self.stop_component("etcd")
                self.start_component("etcd")
            finally:
                self.start_component("kube-apiserver")
        finally:
            shutil.rmtree(tmp_dir, ignore_errors=True)


class NerdctlCluster(ComposeCluster):
    RUNTIME = consts.RUNTIME_TYPE_NERDCTL
