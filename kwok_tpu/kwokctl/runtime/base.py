"""Runtime contract + base cluster implementation.

Behavioral port of pkg/kwokctl/runtime/{config.go,cluster.go}: the Runtime
interface is the 24-method lifecycle contract every backend implements; the
base Cluster provides the workdir layout (`kwok.yaml` config round-trip,
bin/ logs/ pki/ subdirs), readiness = GET /healthz == "ok" against the
apiserver (cluster.go:164-182, via direct HTTP instead of shelling to
kubectl), WaitReady polling (:184-207), kubectl passthrough and log access.
"""

from __future__ import annotations

import logging
import os
import shutil
import ssl
import time
import urllib.error
import urllib.request

from kwok_tpu.config.ctl import Component, KwokctlConfiguration
from kwok_tpu.config.types import load_documents, save_documents, first_of
from kwok_tpu.kwokctl import procutil

logger = logging.getLogger("kwok_tpu.kwokctl")

CONFIG_NAME = "kwok.yaml"
IN_HOST_KUBECONFIG_NAME = "kubeconfig.yaml"
ETCD_DATA_DIR_NAME = "etcd"
PKI_NAME = "pki"
PROMETHEUS_NAME = "prometheus.yaml"
AUDIT_POLICY_NAME = "audit.yaml"
AUDIT_LOG_NAME = "audit.log"


class ComponentNotFoundError(KeyError):
    pass


class Cluster:
    """Base runtime; backends subclass and override the lifecycle verbs."""

    def __init__(self, name: str, workdir: str) -> None:
        self.name = name
        self.workdir = workdir
        self._conf: KwokctlConfiguration | None = None

    # --- workdir layout ---------------------------------------------------

    def workdir_path(self, *names: str) -> str:
        return os.path.join(self.workdir, *names)

    def bin_path(self, name: str) -> str:
        return os.path.join(self.workdir, "bin", name)

    def log_path(self, name: str) -> str:
        return os.path.join(self.workdir, "logs", name)

    # --- config round-trip ------------------------------------------------

    def config(self) -> KwokctlConfiguration:
        if self._conf is None:
            conf = first_of(
                load_documents(self.workdir_path(CONFIG_NAME)), KwokctlConfiguration
            )
            if conf is None:
                raise FileNotFoundError(
                    f"no cluster config at {self.workdir_path(CONFIG_NAME)}"
                )
            self._conf = conf
        return self._conf

    def set_config(self, conf: KwokctlConfiguration) -> None:
        self._conf = conf

    def save(self, extra_docs: list | None = None) -> None:
        if self._conf is None:
            return
        docs: list = [self._conf]
        if extra_docs:
            docs += extra_docs
        save_documents(self.workdir_path(CONFIG_NAME), docs)

    # --- lifecycle (overridden by backends) -------------------------------

    def install(self) -> None:
        raise NotImplementedError

    def uninstall(self) -> None:
        """Remove the whole workdir (cluster.go Uninstall)."""
        shutil.rmtree(self.workdir, ignore_errors=True)

    def up(self) -> None:
        raise NotImplementedError

    def down(self) -> None:
        raise NotImplementedError

    def start(self) -> None:
        self.up()

    def stop(self) -> None:
        self.down()

    def start_component(self, name: str) -> None:
        raise NotImplementedError

    def stop_component(self, name: str) -> None:
        raise NotImplementedError

    def get_component(self, name: str) -> Component:
        for c in self.config().components:
            if c.name == name:
                return c
        raise ComponentNotFoundError(name)

    # --- subprocess helper ------------------------------------------------

    def _run(self, args: list, capture: bool = False, check: bool = True,
             cwd: str | None = None):
        """Run a tool command, raising with stderr context on failure."""
        import subprocess

        if capture:
            res = subprocess.run(args, cwd=cwd, capture_output=True, text=True)
        else:
            res = subprocess.run(args, cwd=cwd)
        if check and res.returncode != 0:
            err = (res.stderr or "") if capture else ""
            raise RuntimeError(f"{' '.join(args)} failed ({res.returncode}): {err}")
        return res

    # --- readiness --------------------------------------------------------

    def apiserver_url(self) -> str:
        conf = self.config().options
        scheme = "https" if conf.securePort else "http"
        return f"{scheme}://127.0.0.1:{conf.kubeApiserverPort}"

    def client_ssl_context(self) -> "ssl.SSLContext | None":
        """Client TLS context for the cluster's secure port: skip server
        verification (self-signed CA, kubeconfig.yaml.tpl semantics) and
        present the admin client cert when the PKI exists. None when the
        cluster serves plain HTTP."""
        if not self.config().options.securePort:
            return None
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        pki = self.workdir_path(PKI_NAME)
        admin_crt = os.path.join(pki, "admin.crt")
        if os.path.exists(admin_crt):
            ctx.load_cert_chain(admin_crt, os.path.join(pki, "admin.key"))
        return ctx

    def ready(self) -> bool:
        """GET /healthz == b"ok" (cluster.go:164-182)."""
        url = self.apiserver_url() + "/healthz"
        try:
            with urllib.request.urlopen(
                url, timeout=2, context=self.client_ssl_context()
            ) as r:
                return r.read() == b"ok"
        except (urllib.error.URLError, OSError):
            return False

    def wait_ready(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.ready():
                return
            time.sleep(1.0)
        raise TimeoutError(f"cluster {self.name} not ready after {timeout}s")

    # --- tool passthrough -------------------------------------------------

    def kubectl_path(self) -> str:
        """PATH kubectl, else download into the workdir on first use
        (cluster.go kubectlPath download-or-find); in zero-egress
        environments the download cannot succeed, so fall back to the
        built-in shim (kwok_tpu/kubectl.py) rather than leaving the
        kubectl verb dead."""
        found = shutil.which("kubectl")
        if found:
            return found
        path = self.bin_path("kubectl")
        if not os.path.exists(path):
            from kwok_tpu.kwokctl import download

            conf = self.config().options
            try:
                download.download_with_cache(
                    conf.cacheDir, conf.kubectlBinary, path, quiet=conf.quietPull
                )
            except Exception as e:
                if not self.KUBECTL_SHIM_OK:
                    # e.g. kind drives kubectl with config/--context/cordon,
                    # which the shim does not speak — surface the real error
                    raise
                logger.warning(
                    "kubectl download failed (%s); using the built-in shim", e
                )
                self._write_builtin_kubectl(path)
        return path

    # runtimes whose kubectl usage goes beyond the built-in shim's surface
    # (kwok_tpu/kubectl.py) opt out and let download failures propagate
    KUBECTL_SHIM_OK = True

    def _write_builtin_kubectl(self, path: str) -> None:
        import stat
        import sys

        repo_paths = [p for p in sys.path if p]
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # two files: the python entry plus a /bin/sh wrapper — a direct
        # `#!{python}` shebang truncates at the first space in the
        # interpreter path (venvs under spaced dirs)
        impl = path + "-builtin.py"
        with open(impl, "w") as f:
            f.write(
                "# generated built-in kubectl shim (kwok_tpu air-gapped fallback)\n"
                "import sys\n"
                f"sys.path[:0] = {repo_paths!r}\n"
                "from kwok_tpu.kubectl import main\n"
                "sys.exit(main(sys.argv[1:]))\n"
            )
        with open(path, "w") as f:
            f.write(
                "#!/bin/sh\n"
                f'exec "{sys.executable}" "{impl}" "$@"\n'
            )
        os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC | stat.S_IXGRP | stat.S_IXOTH)

    def etcdctl_path(self) -> str:
        """Workdir etcdctl, extracted from the etcd release tar on first use
        (shared by the binary/compose/kind snapshot paths)."""
        from kwok_tpu.kwokctl import download

        conf = self.config().options
        path = self.bin_path("etcdctl")
        if not os.path.exists(path):
            download.download_with_cache_and_extract(
                conf.cacheDir, conf.etcdBinaryTar, path, "etcdctl",
                quiet=conf.quietPull,
            )
        return path

    def kubectl(self, args: list[str], **kwargs) -> int:
        return procutil.exec_foreground([self.kubectl_path(), *args], **kwargs)

    def kubectl_in_cluster(self, args: list[str], **kwargs) -> int:
        return self.kubectl(
            ["--kubeconfig", self.workdir_path(IN_HOST_KUBECONFIG_NAME), *args],
            **kwargs,
        )

    def etcdctl_in_cluster(self, args: list[str], **kwargs) -> int:
        raise NotImplementedError

    # --- logs -------------------------------------------------------------

    def logs(self, name: str, out, follow: bool = False) -> None:
        self.get_component(name)  # raise if unknown
        self._cat(self.log_path(os.path.basename(name) + ".log"), out, follow)

    def _setup_audit_files(self, policy_path: str) -> None:
        """Copy the audit policy into the workdir and pre-create the log so
        `audit-logs` works before the apiserver's first write (shared by the
        binary and mock runtimes)."""
        import shutil

        shutil.copyfile(policy_path, self.workdir_path(AUDIT_POLICY_NAME))
        open(self.log_path(AUDIT_LOG_NAME), "a").close()

    def audit_logs(self, out, follow: bool = False) -> None:
        self._cat(self.log_path(AUDIT_LOG_NAME), out, follow)

    @staticmethod
    def _cat(path: str, out, follow: bool) -> None:
        with open(path, "rb") as f:
            while True:
                chunk = f.read(65536)
                if chunk:
                    out.write(chunk.decode(errors="replace"))
                    continue
                if not follow:
                    return
                time.sleep(0.2)

    # --- artifacts --------------------------------------------------------

    def list_binaries(self) -> list[str]:
        return []

    def list_images(self) -> list[str]:
        return []

    # --- snapshot ---------------------------------------------------------

    def snapshot_save(self, path: str) -> None:
        raise NotImplementedError

    def snapshot_restore(self, path: str) -> None:
        raise NotImplementedError
