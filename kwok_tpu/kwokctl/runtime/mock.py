"""Mock runtime: a zero-download cluster for tests and air-gapped demos.

Stands in for the binary runtime where real control-plane binaries cannot be
downloaded (CI has no egress). Its "kube-apiserver" is a generated python
shim serving the kube-apiserver wire protocol from an in-memory store
(tests/http_fake_apiserver.py's protocol: list/watch/get/patch/delete on
/api/v1 paths plus /healthz), and the kwok-controller is the real TPU engine
— so `kwokctl create cluster --runtime mock` exercises the full
create -> up -> Ready -> simulate -> down lifecycle with genuine detached
processes and pid-file supervision, just no upstream Kubernetes binaries.
"""

from __future__ import annotations

import os
import stat
import sys

from kwok_tpu.config.ctl import Component
from kwok_tpu.kwokctl import components as comp
from kwok_tpu.kwokctl import consts, k8s
from kwok_tpu.kwokctl.runtime import base
from kwok_tpu.kwokctl.runtime.binary import BinaryCluster

LOCAL = "127.0.0.1"

_APISERVER_MAIN = """\
#!{python}
# generated mock kube-apiserver (kwok_tpu mock runtime)
import sys
sys.path[:0] = {syspath!r}
from kwok_tpu.edge.mockserver import main
sys.exit(main(sys.argv[1:]))
"""

_APISERVER_NATIVE = """\
#!/bin/sh
# generated mock kube-apiserver shim -> native binary (kwok_tpu mock runtime)
exec {binary} "$@"
"""


class MockCluster(BinaryCluster):
    """BinaryCluster with downloads replaced by generated shims."""

    RUNTIME = consts.RUNTIME_TYPE_MOCK

    def _download_binaries(self) -> None:
        conf = self.config().options
        conf.disableKubeControllerManager = True
        conf.disableKubeScheduler = True
        self._write_kwok_shim()
        self._write_apiserver_shim()

    def _write_apiserver_shim(self) -> None:
        conf = self.config().options
        shim = self.bin_path("kube-apiserver")
        os.makedirs(os.path.dirname(shim), exist_ok=True)
        # Prefer the compiled apiserver (same wire protocol, native speed,
        # see native/apiserver.cc); fall back to the Python mockserver shim
        # when no compiler is available or KWOK_TPU_NATIVE=0. Secure mode
        # always uses the Python server: it terminates TLS with the cluster
        # PKI and requires client certs, like the binary runtime's
        # kube-apiserver secure port (the native binary is plaintext-only).
        from kwok_tpu import native

        binary = None if conf.securePort else native.apiserver_binary()
        if binary:
            content = _APISERVER_NATIVE.format(binary=binary)
        else:
            repo_paths = [p for p in sys.path if p]
            content = _APISERVER_MAIN.format(
                python=sys.executable, syspath=repo_paths
            )
        with open(shim, "w") as f:
            f.write(content)
        os.chmod(shim, os.stat(shim).st_mode | stat.S_IEXEC | stat.S_IXGRP | stat.S_IXOTH)

    def _setup_workdir(self) -> None:
        conf = self.config().options
        os.makedirs(self.workdir_path("logs"), exist_ok=True)
        if conf.kubeAuditPolicy:
            self._setup_audit_files(conf.kubeAuditPolicy)
        if conf.securePort:
            pki_dir = self.workdir_path(base.PKI_NAME)
            if not os.path.exists(os.path.join(pki_dir, "ca.crt")):
                from kwok_tpu.kwokctl import pki

                pki.generate_pki(pki_dir)

    def _build_components(self) -> None:
        config = self.config()
        conf = config.options
        kubeconfig = self.workdir_path(base.IN_HOST_KUBECONFIG_NAME)
        args = [
            f"--port={conf.kubeApiserverPort}",
            f"--address={conf.bindAddress}",
            # the mock's etcd data dir: store survives stop/start
            f"--data-file={self.workdir_path('apiserver-state.json')}",
        ]
        if conf.kubeAuditPolicy:
            # policy/log files are prepared by _setup_workdir; the mock
            # apiserver emits audit.k8s.io/v1 Event lines per request
            args.append(f"--audit-log={self.log_path(base.AUDIT_LOG_NAME)}")
        if conf.kubeAuthorization:
            # --kube-authorization on the mock: rbac.authorization.k8s.io/v1
            # with bootstrap policy, plus bearer-token authn; the token is
            # generated per cluster and carried by the kubeconfig (the mock
            # analogue of --authorization-mode=Node,RBAC + client certs,
            # create/cluster/cluster.go --kube-authorization flag)
            args += [
                "--authorization",
                f"--token-auth-file={self._ensure_token_file()}",
            ]
        if conf.securePort:
            # serve HTTPS with the cluster PKI + require client certs
            # (kube-apiserver secure-port semantics; PKI minted in
            # _setup_workdir, reused as server cert like the reference)
            pki_dir = self.workdir_path(base.PKI_NAME)
            args += [
                f"--tls-cert-file={os.path.join(pki_dir, 'admin.crt')}",
                f"--tls-private-key-file={os.path.join(pki_dir, 'admin.key')}",
                f"--client-ca-file={os.path.join(pki_dir, 'ca.crt')}",
            ]
        apiserver = Component(
            name="kube-apiserver",
            binary=self.bin_path("kube-apiserver"),
            workDir=self.workdir,
            args=args,
        )
        kwok = comp.build_kwok_controller(
            binary=self.bin_path("kwok-controller"),
            workdir=self.workdir,
            kubeconfig_path=kubeconfig,
            config_path=self.workdir_path(base.CONFIG_NAME),
            port=conf.kwokControllerPort,
            address=LOCAL,
        )
        config.components = [apiserver, kwok]

    def _ensure_token_file(self) -> str:
        """Generate (once) the cluster's admin token file, kube-apiserver
        --token-auth-file CSV format: token,user,uid,groups."""
        path = self.workdir_path("admin-token.csv")
        if not os.path.exists(path):
            import secrets

            token = secrets.token_hex(16)
            with open(path, "w") as f:
                f.write(f'{token},kwok-admin,uid-kwok-admin,"system:masters"\n')
            os.chmod(path, 0o600)
        return path

    def _admin_token(self) -> str | None:
        path = self.workdir_path("admin-token.csv")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            first = f.readline().strip()
        return first.split(",", 1)[0] if first else None

    def _write_kubeconfig(self) -> None:
        conf = self.config().options
        token = ""
        if conf.kubeAuthorization:
            self._ensure_token_file()
            token = self._admin_token() or ""
        pki_dir = self.workdir_path(base.PKI_NAME)
        data = k8s.build_kubeconfig(
            project_name=self.name,
            address=self._apiserver_url(),
            secure_port=bool(conf.securePort),
            admin_crt_path=os.path.join(pki_dir, "admin.crt"),
            admin_key_path=os.path.join(pki_dir, "admin.key"),
            token=token,
        )
        with open(self.workdir_path(base.IN_HOST_KUBECONFIG_NAME), "w") as f:
            f.write(data)

    def _apiserver_url(self) -> str:
        return self.apiserver_url()  # base: scheme follows securePort

    def _auth_headers(self) -> dict[str, str]:
        token = self._admin_token()
        return {"Authorization": f"Bearer {token}"} if token else {}

    def snapshot_save(self, path: str) -> None:
        """GET /snapshot — the mock analogue of `etcdctl snapshot save`
        (cluster state IS apiserver-store state, SURVEY.md section 3.5)."""
        import urllib.request

        req = urllib.request.Request(
            self._apiserver_url() + "/snapshot", headers=self._auth_headers()
        )
        with urllib.request.urlopen(req, context=self.client_ssl_context()) as r:
            data = r.read()
        with open(path, "wb") as f:
            f.write(data)

    def snapshot_restore(self, path: str) -> None:
        """POST /restore — replaces the store and closes watches, so the
        engine re-lists, exactly like watchers after an etcd restore."""
        import urllib.request

        with open(path, "rb") as f:
            data = f.read()
        req = urllib.request.Request(
            self._apiserver_url() + "/restore",
            data=data,
            headers={"Content-Type": "application/json", **self._auth_headers()},
            method="POST",
        )
        urllib.request.urlopen(req, context=self.client_ssl_context()).read()
