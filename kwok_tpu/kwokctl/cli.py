"""kwokctl: stand up a whole simulated control plane in one command.

Behavioral port of pkg/kwokctl/cmd (root.go:56-67 verb tree,
create/cluster/cluster.go:115-230 create flow): create/delete/start/stop
cluster, get clusters/kubeconfig/artifacts, logs, kubectl/etcdctl
passthrough, snapshot save/restore. `--name` is persistent; per-cluster
state lives in ~/.kwok/clusters/<name> exactly like the reference so the
workdir layouts interoperate.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

from kwok_tpu.config.ctl import KwokctlConfiguration, KwokctlConfigurationOptions
from kwok_tpu.config.types import first_of, load_documents, parse_bool
from kwok_tpu.kwokctl import runtime as runtime_registry
from kwok_tpu.kwokctl import vars as ctlvars
from kwok_tpu.kwokctl.runtime.base import IN_HOST_KUBECONFIG_NAME


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kwokctl", description="kwokctl is a tool to streamline the "
        "creation and management of simulated clusters (TPU-native engine)."
    )
    p.add_argument("--name", default="kwok", help="cluster name")
    from kwok_tpu import log

    log.add_flags(p)
    sub = p.add_subparsers(dest="verb", required=True)

    # create cluster
    create = sub.add_parser("create", help="Creates one of [cluster]")
    create_sub = create.add_subparsers(dest="noun", required=True)
    cc = create_sub.add_parser("cluster", help="Create a cluster")
    cc.add_argument("--config", default="", help="extra config file (Stages etc.)")
    cc.add_argument("--wait", default="", help="wait for ready, e.g. 120s")
    opts = KwokctlConfigurationOptions()
    for f in dataclasses.fields(opts):
        flag = "--" + _kebab(f.name)
        default = getattr(opts, f.name)
        if isinstance(default, bool) or default is None:
            cc.add_argument(flag, dest=f.name, default=default, type=_bool_arg)
        elif isinstance(default, int):
            cc.add_argument(flag, dest=f.name, default=default, type=int)
        elif isinstance(default, float):
            cc.add_argument(flag, dest=f.name, default=default, type=float)
        else:
            cc.add_argument(flag, dest=f.name, default=default)

    # delete cluster
    delete = sub.add_parser("delete", help="Deletes one of [cluster]")
    delete_sub = delete.add_subparsers(dest="noun", required=True)
    delete_sub.add_parser("cluster", help="Delete a cluster")

    # start/stop cluster
    for verb, help_ in (("start", "Start a cluster"), ("stop", "Stop a cluster")):
        v = sub.add_parser(verb, help=help_)
        v_sub = v.add_subparsers(dest="noun", required=True)
        v_sub.add_parser("cluster", help=help_)

    # get
    get = sub.add_parser("get", help="Gets one of [artifacts, clusters, kubeconfig]")
    get_sub = get.add_subparsers(dest="noun", required=True)
    get_sub.add_parser("clusters", help="List existing clusters")
    get_sub.add_parser("kubeconfig", help="Print the cluster kubeconfig path")
    ga = get_sub.add_parser("artifacts", help="List binaries or images used by the cluster")
    ga.add_argument("--filter", default="", choices=["", "binary", "image"])

    # logs
    logs = sub.add_parser("logs", help="Logs one of [etcd, kube-apiserver, ...]")
    logs.add_argument("component")
    logs.add_argument("-f", "--follow", action="store_true")

    # audit-logs (reference: logs audit)
    audit = sub.add_parser("audit-logs", help="Audit logs of the apiserver")
    audit.add_argument("-f", "--follow", action="store_true")

    # kubectl / etcdctl passthrough
    for tool in ("kubectl", "etcdctl"):
        t = sub.add_parser(tool, help=f"{tool} in cluster", add_help=False)
        t.add_argument("tool_args", nargs=argparse.REMAINDER)

    # snapshot
    snap = sub.add_parser("snapshot", help="Snapshot [save, restore] one of cluster")
    snap_sub = snap.add_subparsers(dest="noun", required=True)
    for action in ("save", "restore"):
        sp = snap_sub.add_parser(action)
        sp.add_argument("--path", required=True)
        sp.add_argument("--format", default="etcd", choices=["etcd"])
    return p


def _kebab(camel: str) -> str:
    out = []
    for ch in camel:
        if ch.isupper():
            out.append("-")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def _bool_arg(v):
    if v is None or isinstance(v, bool):
        return v
    return parse_bool(v)


def _parse_wait(s: str) -> float:
    from kwok_tpu.config.stages import parse_duration

    return parse_duration(s) if s else 0.0


def cmd_create(args) -> int:
    name = args.name
    workdir = ctlvars.cluster_workdir(name)

    # precedence: flags > config file > computed defaults. Merge flags over
    # file options FIRST, then derive defaults once, so derived fields
    # (binary URLs, etcdVersion, securePort) see the effective kubeVersion.
    # "Set" means "differs from the dataclass default" — for both layers.
    opts = KwokctlConfigurationOptions()
    extra_docs = []
    file_conf = None
    if args.config:
        docs = load_documents(args.config)
        file_conf = first_of(docs, KwokctlConfiguration)
        extra_docs = [d for d in docs if not isinstance(d, KwokctlConfiguration)]
    for f in dataclasses.fields(opts):
        flag_v = getattr(args, f.name)
        if flag_v != f.default:
            setattr(opts, f.name, flag_v)
        elif file_conf is not None:
            file_v = getattr(file_conf.options, f.name)
            if file_v != f.default:
                setattr(opts, f.name, file_v)
    ctlvars.set_defaults(opts)

    exists = os.path.exists(os.path.join(workdir, "kwok.yaml"))
    if exists:
        print(f"Cluster {name!r} already exists, reinstalling", file=sys.stderr)
        rt = runtime_registry.load(name, workdir)
        try:
            rt.down()
        except Exception as e:
            print(
                f"warning: teardown of existing cluster failed ({e}); "
                "reinstalling anyway", file=sys.stderr,
            )
    rt = runtime_registry.get(opts.runtime, name, workdir)
    conf = KwokctlConfiguration(options=opts, name=name)
    rt.set_config(conf)
    os.makedirs(workdir, exist_ok=True)
    rt.save(extra_docs)
    print(f"Creating cluster {name!r} (runtime {opts.runtime})", file=sys.stderr)
    rt.install()
    rt.save(extra_docs)
    rt.up()
    wait = _parse_wait(args.wait)
    if wait:
        rt.wait_ready(wait)
    kc = os.path.join(workdir, IN_HOST_KUBECONFIG_NAME)
    print(f"Cluster {name!r} is ready; kubeconfig: {kc}", file=sys.stderr)
    print(f'> kubectl --kubeconfig {kc} get nodes', file=sys.stderr)
    return 0


def _loaded(args):
    return runtime_registry.load(args.name, ctlvars.cluster_workdir(args.name))


def cmd_delete(args) -> int:
    rt = _loaded(args)
    try:
        rt.down()
    except Exception as e:
        print(
            f"warning: cluster teardown failed ({e}); uninstalling anyway",
            file=sys.stderr,
        )
    rt.uninstall()
    print(f"Cluster {args.name!r} deleted", file=sys.stderr)
    return 0


def cmd_get(args) -> int:
    if args.noun == "clusters":
        base_dir = ctlvars.clusters_dir()
        if os.path.isdir(base_dir):
            for entry in sorted(os.listdir(base_dir)):
                if os.path.exists(os.path.join(base_dir, entry, "kwok.yaml")):
                    print(entry)
        return 0
    if args.noun == "kubeconfig":
        print(
            os.path.join(ctlvars.cluster_workdir(args.name), IN_HOST_KUBECONFIG_NAME)
        )
        return 0
    rt = _loaded(args)
    arts = []
    if args.filter in ("", "binary"):
        arts += rt.list_binaries()
    if args.filter in ("", "image"):
        arts += rt.list_images()
    for a in arts:
        if a:
            print(a)
    return 0


# Read-only verbs that stream to stdout: a downstream reader closing the pipe
# early (`kwokctl get ... | grep -q`) means "got what I needed", not failure.
_PIPE_TOLERANT_VERBS = frozenset({"get", "logs", "audit-logs"})


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from kwok_tpu import log

    log.setup(args.verbosity)
    try:
        rc = _dispatch(args)
        # Flush inside the try: with a block-buffered pipe the EPIPE often
        # only surfaces here (or at interpreter-exit teardown, where it
        # becomes an unhandled "Exception ignored" + exit 120).
        sys.stdout.flush()
        return rc
    except BrokenPipeError:
        # Point stdout at devnull so interpreter-exit flush stays quiet.
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        if args.verb in _PIPE_TOLERANT_VERBS:
            return 0
        # A mutating verb (snapshot, create, kubectl passthrough) may raise
        # BrokenPipeError from a network socket, not stdout — never report
        # success. 141 = shell convention for death-by-SIGPIPE.
        print(f"kwokctl {args.verb}: broken pipe", file=sys.stderr)
        return 141


def _dispatch(args) -> int:
    verb = args.verb
    if verb == "create":
        return cmd_create(args)
    if verb == "delete":
        return cmd_delete(args)
    if verb == "start":
        _loaded(args).start()
        return 0
    if verb == "stop":
        _loaded(args).stop()
        return 0
    if verb == "get":
        return cmd_get(args)
    if verb == "logs":
        _loaded(args).logs(args.component, sys.stdout, follow=args.follow)
        return 0
    if verb == "audit-logs":
        _loaded(args).audit_logs(sys.stdout, follow=args.follow)
        return 0
    if verb == "kubectl":
        return _loaded(args).kubectl_in_cluster(list(args.tool_args))
    if verb == "etcdctl":
        return _loaded(args).etcdctl_in_cluster(list(args.tool_args))
    if verb == "snapshot":
        rt = _loaded(args)
        if args.noun == "save":
            rt.snapshot_save(args.path)
        else:
            rt.snapshot_restore(args.path)
        return 0
    raise AssertionError(verb)
