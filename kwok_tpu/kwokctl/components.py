"""Declarative component builders + start-order grouping.

Behavioral port of pkg/kwokctl/components: each build_* function is a pure
function from a config to a Component spec (binary path + argv + links);
group_by_links is the reference's topological batching (utils.go:33-65) that
yields waves of components safe to start concurrently.

Arg matrices follow the reference builders (etcd.go:36-92,
kube_apiserver.go:45-195, kube_controller_manager.go:40-160,
kube_scheduler.go:39-140, kwok_controller.go:37-99, prometheus.go:38-133),
host-process ("binary") flavor only — the container branches belong to the
compose runtime.
"""

from __future__ import annotations

from kwok_tpu.config.ctl import Component

LOCAL_ADDRESS = "127.0.0.1"


class BrokenLinksError(ValueError):
    pass


def group_by_links(components: list[Component]) -> list[list[Component]]:
    """Batch components into start waves: a component joins the earliest wave
    after all of its links (utils.go GroupByLinks)."""
    placed: set[str] = set()
    remaining = list(components)
    groups: list[list[Component]] = []
    while remaining:
        wave = [c for c in remaining if all(l in placed for l in c.links)]
        if not wave:
            raise BrokenLinksError(
                f"broken links dependency detected: {[c.name for c in remaining]}"
            )
        remaining = [c for c in remaining if c not in wave]
        placed.update(c.name for c in wave)
        groups.append(wave)
    return groups


def build_etcd(
    binary: str,
    data_path: str,
    workdir: str,
    version: str = "",
    address: str = LOCAL_ADDRESS,
    port: int = 2379,
    peer_port: int = 2380,
) -> Component:
    return Component(
        name="etcd",
        version=version,
        binary=binary,
        command=["etcd"],
        workDir=workdir,
        args=[
            "--name=node0",
            f"--initial-advertise-peer-urls=http://{address}:{peer_port}",
            f"--listen-peer-urls=http://{address}:{peer_port}",
            f"--advertise-client-urls=http://{address}:{port}",
            f"--listen-client-urls=http://{address}:{port}",
            f"--initial-cluster=node0=http://{address}:{peer_port}",
            "--auto-compaction-retention=1",
            "--quota-backend-bytes=8589934592",
            f"--data-dir={data_path}",
        ],
    )


def build_kube_apiserver(
    binary: str,
    workdir: str,
    port: int,
    version: str = "",
    address: str = LOCAL_ADDRESS,
    etcd_address: str = LOCAL_ADDRESS,
    etcd_port: int = 2379,
    runtime_config: str = "",
    feature_gates: str = "",
    secure_port: bool = False,
    authorization: bool = False,
    audit_policy_path: str = "",
    audit_log_path: str = "",
    ca_cert_path: str = "",
    admin_cert_path: str = "",
    admin_key_path: str = "",
) -> Component:
    args = [
        "--admission-control=",
        f"--etcd-servers=http://{etcd_address}:{etcd_port}",
        "--etcd-prefix=/registry",
        "--allow-privileged=true",
    ]
    if runtime_config:
        args.append(f"--runtime-config={runtime_config}")
    if feature_gates:
        args.append(f"--feature-gates={feature_gates}")
    if secure_port:
        if authorization:
            args.append("--authorization-mode=Node,RBAC")
        args += [
            f"--bind-address={address}",
            f"--secure-port={port}",
            f"--tls-cert-file={admin_cert_path}",
            f"--tls-private-key-file={admin_key_path}",
            f"--client-ca-file={ca_cert_path}",
            f"--service-account-key-file={admin_key_path}",
            f"--service-account-signing-key-file={admin_key_path}",
            "--service-account-issuer=https://kubernetes.default.svc.cluster.local",
        ]
    else:
        args += [
            f"--insecure-bind-address={address}",
            f"--insecure-port={port}",
        ]
    if audit_policy_path:
        args += [
            f"--audit-policy-file={audit_policy_path}",
            f"--audit-log-path={audit_log_path}",
        ]
    return Component(
        name="kube-apiserver",
        version=version,
        links=["etcd"],
        binary=binary,
        command=["kube-apiserver"],
        workDir=workdir,
        args=args,
    )


def build_kube_controller_manager(
    binary: str,
    workdir: str,
    kubeconfig_path: str,
    port: int,
    version: str = "",
    address: str = LOCAL_ADDRESS,
    secure_port: bool = False,
    authorization: bool = False,
    feature_gates: str = "",
    ca_cert_path: str = "",
    admin_key_path: str = "",
    node_monitor_period_s: float = 0.0,
    node_monitor_grace_period_s: float = 0.0,
) -> Component:
    args = []
    if feature_gates:
        args.append(f"--feature-gates={feature_gates}")
    args.append(f"--kubeconfig={kubeconfig_path}")
    if secure_port:
        args += [
            "--authorization-always-allow-paths=/healthz,/readyz,/livez,/metrics",
            f"--bind-address={address}",
            f"--secure-port={port}",
        ]
    else:
        args += [
            f"--address={address}",
            f"--port={port}",
            "--secure-port=0",
        ]
    if authorization:
        args += [
            f"--root-ca-file={ca_cert_path}",
            f"--service-account-private-key-file={admin_key_path}",
        ]
    # accelerated node-failure detection for simulation scenarios
    # (kube_controller_manager.go NodeMonitor options)
    if node_monitor_period_s:
        args.append(f"--node-monitor-period={node_monitor_period_s}s")
    if node_monitor_grace_period_s:
        args.append(f"--node-monitor-grace-period={node_monitor_grace_period_s}s")
    return Component(
        name="kube-controller-manager",
        version=version,
        links=["kube-apiserver"],
        binary=binary,
        command=["kube-controller-manager"],
        workDir=workdir,
        args=args,
    )


def build_kube_scheduler(
    binary: str,
    workdir: str,
    kubeconfig_path: str,
    port: int,
    version: str = "",
    address: str = LOCAL_ADDRESS,
    secure_port: bool = False,
    feature_gates: str = "",
) -> Component:
    args = []
    if feature_gates:
        args.append(f"--feature-gates={feature_gates}")
    args.append(f"--kubeconfig={kubeconfig_path}")
    if secure_port:
        args += [
            "--authorization-always-allow-paths=/healthz,/readyz,/livez,/metrics",
            f"--bind-address={address}",
            f"--secure-port={port}",
        ]
    else:
        args += [
            f"--address={address}",
            f"--port={port}",
        ]
    return Component(
        name="kube-scheduler",
        version=version,
        links=["kube-apiserver"],
        binary=binary,
        command=["kube-scheduler"],
        workDir=workdir,
        args=args,
    )


def build_kwok_controller(
    binary: str,
    workdir: str,
    kubeconfig_path: str,
    config_path: str,
    port: int,
    version: str = "",
    address: str = LOCAL_ADDRESS,
) -> Component:
    """The simulation engine — THIS package's `kwok` CLI, launched via the
    shim written by the binary runtime (kwok_controller.go:61-83 arg
    surface)."""
    return Component(
        name="kwok-controller",
        version=version,
        links=["kube-apiserver"],
        binary=binary,
        command=["kwok"],
        workDir=workdir,
        args=[
            "--manage-all-nodes=true",
            f"--kubeconfig={kubeconfig_path}",
            f"--config={config_path}",
            f"--server-address={address}:{port}",
        ],
    )


def build_prometheus(
    binary: str,
    workdir: str,
    config_path: str,
    port: int,
    version: str = "",
    address: str = LOCAL_ADDRESS,
    links: list[str] | None = None,
) -> Component:
    # default links assume the full control plane; callers with disabled
    # components must pass the names actually present, or group_by_links
    # could never place prometheus
    return Component(
        name="prometheus",
        version=version,
        links=list(links)
        if links is not None
        else [
            "etcd",
            "kube-apiserver",
            "kube-controller-manager",
            "kube-scheduler",
            "kwok-controller",
        ],
        binary=binary,
        command=["prometheus"],
        workDir=workdir,
        args=[
            f"--config.file={config_path}",
            f"--web.listen-address={address}:{port}",
        ],
    )


def build_prometheus_config(
    project_name: str,
    etcd_port: int,
    kube_apiserver_port: int,
    kube_controller_manager_port: int,
    kube_scheduler_port: int,
    kwok_controller_port: int,
    secure_port: bool = False,
    admin_crt_path: str = "",
    admin_key_path: str = "",
) -> str:
    """Scrape config over every control-plane component
    (runtime/binary/prometheus.yaml.tpl semantics)."""
    scheme = "https" if secure_port else "http"
    tls = ""
    if secure_port:
        tls = (
            "    tls_config:\n"
            "      insecure_skip_verify: true\n"
            f"      cert_file: {admin_crt_path}\n"
            f"      key_file: {admin_key_path}\n"
        )

    def job(name: str, port: int, metrics_path: str = "/metrics", secure: bool = True) -> str:
        sch = scheme if secure else "http"
        out = (
            f"  - job_name: {name}\n"
            f"    scheme: {sch}\n"
            f"    metrics_path: {metrics_path}\n"
        )
        if secure and tls:
            out += tls
        out += (
            "    static_configs:\n"
            f"      - targets: ['127.0.0.1:{port}']\n"
        )
        return out

    cfg = (
        "global:\n"
        "  scrape_interval: 15s\n"
        f"  external_labels:\n    cluster: {project_name}\n"
        "scrape_configs:\n"
    )
    cfg += job("etcd", etcd_port, secure=False)
    cfg += job("kube-apiserver", kube_apiserver_port)
    if kube_controller_manager_port:
        cfg += job("kube-controller-manager", kube_controller_manager_port)
    if kube_scheduler_port:
        cfg += job("kube-scheduler", kube_scheduler_port)
    cfg += job("kwok-controller", kwok_controller_port, secure=False)
    return cfg
