"""Declarative component builders + start-order grouping.

Behavioral port of pkg/kwokctl/components: each build_* function is a pure
function from a config to a Component spec (binary path + argv + links);
group_by_links is the reference's topological batching (utils.go:33-65) that
yields waves of components safe to start concurrently.

Arg matrices follow the reference builders (etcd.go:36-92,
kube_apiserver.go:45-195, kube_controller_manager.go:40-160,
kube_scheduler.go:39-140, kwok_controller.go:37-99, prometheus.go:38-133),
host-process ("binary") flavor only — the container branches belong to the
compose runtime.
"""

from __future__ import annotations

from kwok_tpu.config.ctl import Component, Port, Volume

LOCAL_ADDRESS = "127.0.0.1"
PUBLIC_ADDRESS = "0.0.0.0"

# In-container well-known paths (components/*.go image branches)
IN_CONTAINER_PKI = "/etc/kubernetes/pki"
IN_CONTAINER_KUBECONFIG = "/root/.kube/config"
IN_CONTAINER_KWOK_CONFIG = "/root/.kwok/kwok.yaml"
IN_CONTAINER_ETCD_DATA = "/etcd-data"
IN_CONTAINER_AUDIT_POLICY = "/etc/kubernetes/audit-policy.yaml"
IN_CONTAINER_AUDIT_LOG = "/var/log/kubernetes/audit/audit.log"
IN_CONTAINER_PROMETHEUS_CONFIG = "/etc/prometheus/prometheus.yaml"


def _release_at_least(version: str, minor: int) -> bool:
    """True when a k8s version string is >= 1.<minor>; unknown/empty
    versions count as current (the reference always has a parsed Version,
    defaulting to the newest supported release)."""
    from kwok_tpu.kwokctl.k8s import parse_release

    release = parse_release(version or "")
    return release < 0 or release >= minor


class BrokenLinksError(ValueError):
    pass


def group_by_links(components: list[Component]) -> list[list[Component]]:
    """Batch components into start waves: a component joins the earliest wave
    after all of its links (utils.go GroupByLinks)."""
    placed: set[str] = set()
    remaining = list(components)
    groups: list[list[Component]] = []
    while remaining:
        wave = [c for c in remaining if all(l in placed for l in c.links)]
        if not wave:
            raise BrokenLinksError(
                f"broken links dependency detected: {[c.name for c in remaining]}"
            )
        remaining = [c for c in remaining if c not in wave]
        placed.update(c.name for c in wave)
        groups.append(wave)
    return groups


def build_etcd(
    binary: str = "",
    data_path: str = "",
    workdir: str = "",
    image: str = "",
    version: str = "",
    address: str = LOCAL_ADDRESS,
    port: int = 2379,
    peer_port: int = 2380,
) -> Component:
    args = [
        "--name=node0",
        f"--initial-advertise-peer-urls=http://{address}:{peer_port}",
        f"--listen-peer-urls=http://{address}:{peer_port}",
        f"--advertise-client-urls=http://{address}:{port}",
        f"--listen-client-urls=http://{address}:{port}",
        f"--initial-cluster=node0=http://{address}:{peer_port}",
        "--auto-compaction-retention=1",
        "--quota-backend-bytes=8589934592",
    ]
    # image mode stores data inside the container (etcd.go:61-77)
    args.append(f"--data-dir={IN_CONTAINER_ETCD_DATA if image else data_path}")
    return Component(
        name="etcd",
        version=version,
        binary=binary,
        image=image,
        command=["etcd"],
        workDir=workdir,
        args=args,
    )


def build_kube_apiserver(
    binary: str = "",
    workdir: str = "",
    port: int = 0,
    image: str = "",
    version: str = "",
    address: str = LOCAL_ADDRESS,
    etcd_address: str = LOCAL_ADDRESS,
    etcd_port: int = 2379,
    runtime_config: str = "",
    feature_gates: str = "",
    secure_port: bool = False,
    authorization: bool = False,
    audit_policy_path: str = "",
    audit_log_path: str = "",
    ca_cert_path: str = "",
    admin_cert_path: str = "",
    admin_key_path: str = "",
) -> Component:
    """Image mode (kube_apiserver.go:75-183): fixed in-container ports
    (6443 secure / 8080 insecure) published to the host port, certs and
    audit files bind-mounted at /etc/kubernetes paths."""
    in_container = bool(image)
    ports: list[Port] = []
    volumes: list[Volume] = []
    args = [
        "--admission-control=",
        f"--etcd-servers=http://{etcd_address}:{etcd_port}",
        "--etcd-prefix=/registry",
        "--allow-privileged=true",
    ]
    if runtime_config:
        args.append(f"--runtime-config={runtime_config}")
    if feature_gates:
        args.append(f"--feature-gates={feature_gates}")
    if secure_port:
        if authorization:
            args.append("--authorization-mode=Node,RBAC")
        if in_container:
            ports = [Port(hostPort=port, port=6443)]
            volumes += [
                Volume(hostPath=ca_cert_path, mountPath=f"{IN_CONTAINER_PKI}/ca.crt", readOnly=True),
                Volume(hostPath=admin_cert_path, mountPath=f"{IN_CONTAINER_PKI}/admin.crt", readOnly=True),
                Volume(hostPath=admin_key_path, mountPath=f"{IN_CONTAINER_PKI}/admin.key", readOnly=True),
            ]
            crt = f"{IN_CONTAINER_PKI}/admin.crt"
            key = f"{IN_CONTAINER_PKI}/admin.key"
            ca = f"{IN_CONTAINER_PKI}/ca.crt"
            bind, sport = PUBLIC_ADDRESS, 6443
        else:
            crt, key, ca = admin_cert_path, admin_key_path, ca_cert_path
            bind, sport = address, port
        args += [
            f"--bind-address={bind}",
            f"--secure-port={sport}",
            f"--tls-cert-file={crt}",
            f"--tls-private-key-file={key}",
            f"--client-ca-file={ca}",
            f"--service-account-key-file={key}",
            f"--service-account-signing-key-file={key}",
            "--service-account-issuer=https://kubernetes.default.svc.cluster.local",
        ]
    else:
        if in_container:
            ports = [Port(hostPort=port, port=8080)]
            args += [
                f"--insecure-bind-address={PUBLIC_ADDRESS}",
                "--insecure-port=8080",
            ]
        else:
            args += [
                f"--insecure-bind-address={address}",
                f"--insecure-port={port}",
            ]
    if audit_policy_path:
        if in_container:
            volumes += [
                Volume(hostPath=audit_policy_path, mountPath=IN_CONTAINER_AUDIT_POLICY, readOnly=True),
                Volume(hostPath=audit_log_path, mountPath=IN_CONTAINER_AUDIT_LOG, readOnly=False),
            ]
            args += [
                f"--audit-policy-file={IN_CONTAINER_AUDIT_POLICY}",
                f"--audit-log-path={IN_CONTAINER_AUDIT_LOG}",
            ]
        else:
            args += [
                f"--audit-policy-file={audit_policy_path}",
                f"--audit-log-path={audit_log_path}",
            ]
    return Component(
        name="kube-apiserver",
        version=version,
        links=["etcd"],
        binary=binary,
        image=image,
        command=["kube-apiserver"],
        workDir=workdir,
        ports=ports,
        volumes=volumes,
        args=args,
    )


def build_kube_controller_manager(
    binary: str = "",
    workdir: str = "",
    kubeconfig_path: str = "",
    port: int = 0,
    image: str = "",
    version: str = "",
    address: str = LOCAL_ADDRESS,
    secure_port: bool = False,
    authorization: bool = False,
    feature_gates: str = "",
    ca_cert_path: str = "",
    admin_cert_path: str = "",
    admin_key_path: str = "",
    node_monitor_period_s: float = 0.0,
    node_monitor_grace_period_s: float = 0.0,
) -> Component:
    """Image mode (kube_controller_manager.go:54-147): kubeconfig + certs
    bind-mounted, fixed in-container ports 10257/10252."""
    in_container = bool(image)
    volumes: list[Volume] = []
    if in_container:
        volumes += [
            Volume(hostPath=kubeconfig_path, mountPath=IN_CONTAINER_KUBECONFIG, readOnly=True),
            Volume(hostPath=admin_cert_path, mountPath=f"{IN_CONTAINER_PKI}/admin.crt", readOnly=True),
            Volume(hostPath=admin_key_path, mountPath=f"{IN_CONTAINER_PKI}/admin.key", readOnly=True),
        ]
    args = []
    if feature_gates:
        args.append(f"--feature-gates={feature_gates}")
    args.append(
        f"--kubeconfig={IN_CONTAINER_KUBECONFIG if in_container else kubeconfig_path}"
    )
    if secure_port:
        if _release_at_least(version, 12):
            # --authorization-always-allow-paths exists since 1.12
            # (kube_controller_manager.go:84-89 Version.GE(1,12,0) gate)
            args.append(
                "--authorization-always-allow-paths="
                "/healthz,/readyz,/livez,/metrics"
            )
        if in_container:
            args += [f"--bind-address={PUBLIC_ADDRESS}", "--secure-port=10257"]
        else:
            args += [f"--bind-address={address}", f"--secure-port={port}"]
    else:
        if in_container:
            args += [f"--address={PUBLIC_ADDRESS}", "--port=10252"]
        else:
            args += [f"--address={address}", f"--port={port}"]
        args.append("--secure-port=0")
    if authorization:
        if in_container:
            volumes.append(
                Volume(hostPath=ca_cert_path, mountPath=f"{IN_CONTAINER_PKI}/ca.crt", readOnly=True)
            )
            args += [
                f"--root-ca-file={IN_CONTAINER_PKI}/ca.crt",
                f"--service-account-private-key-file={IN_CONTAINER_PKI}/admin.key",
            ]
        else:
            args += [
                f"--root-ca-file={ca_cert_path}",
                f"--service-account-private-key-file={admin_key_path}",
            ]
    # accelerated node-failure detection for simulation scenarios
    # (kube_controller_manager.go NodeMonitor options)
    if node_monitor_period_s:
        args.append(f"--node-monitor-period={node_monitor_period_s}s")
    if node_monitor_grace_period_s:
        args.append(f"--node-monitor-grace-period={node_monitor_grace_period_s}s")
    return Component(
        name="kube-controller-manager",
        version=version,
        links=["kube-apiserver"],
        binary=binary,
        image=image,
        command=["kube-controller-manager"],
        workDir=workdir,
        volumes=volumes,
        args=args,
    )


def build_kube_scheduler(
    binary: str = "",
    workdir: str = "",
    kubeconfig_path: str = "",
    port: int = 0,
    image: str = "",
    version: str = "",
    address: str = LOCAL_ADDRESS,
    secure_port: bool = False,
    feature_gates: str = "",
    admin_cert_path: str = "",
    admin_key_path: str = "",
) -> Component:
    """Image mode (kube_scheduler.go:53-122): kubeconfig + certs
    bind-mounted, fixed in-container ports 10259/10251."""
    in_container = bool(image)
    volumes: list[Volume] = []
    if in_container:
        volumes += [
            Volume(hostPath=kubeconfig_path, mountPath=IN_CONTAINER_KUBECONFIG, readOnly=True),
            Volume(hostPath=admin_cert_path, mountPath=f"{IN_CONTAINER_PKI}/admin.crt", readOnly=True),
            Volume(hostPath=admin_key_path, mountPath=f"{IN_CONTAINER_PKI}/admin.key", readOnly=True),
        ]
    args = []
    if feature_gates:
        args.append(f"--feature-gates={feature_gates}")
    args.append(
        f"--kubeconfig={IN_CONTAINER_KUBECONFIG if in_container else kubeconfig_path}"
    )
    if secure_port:
        if _release_at_least(version, 12):
            # same 1.12 gate as the controller-manager
            # (kube_scheduler.go:84-88)
            args.append(
                "--authorization-always-allow-paths="
                "/healthz,/readyz,/livez,/metrics"
            )
        if in_container:
            args += [f"--bind-address={PUBLIC_ADDRESS}", "--secure-port=10259"]
        else:
            args += [f"--bind-address={address}", f"--secure-port={port}"]
    else:
        if in_container:
            args += [f"--address={PUBLIC_ADDRESS}", "--port=10251"]
        else:
            args += [f"--address={address}", f"--port={port}"]
    return Component(
        name="kube-scheduler",
        version=version,
        links=["kube-apiserver"],
        binary=binary,
        image=image,
        command=["kube-scheduler"],
        workDir=workdir,
        volumes=volumes,
        args=args,
    )


def build_kwok_controller(
    binary: str = "",
    workdir: str = "",
    kubeconfig_path: str = "",
    config_path: str = "",
    port: int = 0,
    image: str = "",
    version: str = "",
    address: str = LOCAL_ADDRESS,
    admin_cert_path: str = "",
    admin_key_path: str = "",
) -> Component:
    """The simulation engine — THIS package's `kwok` CLI, launched via the
    shim written by the binary runtime (kwok_controller.go:61-83 arg
    surface). Image mode (:47-78) bind-mounts kubeconfig, certs and config
    and serves on 0.0.0.0:8080 in-container."""
    in_container = bool(image)
    volumes: list[Volume] = []
    ports: list[Port] = []
    if in_container:
        volumes += [
            Volume(hostPath=kubeconfig_path, mountPath=IN_CONTAINER_KUBECONFIG, readOnly=True),
            Volume(hostPath=admin_cert_path, mountPath=f"{IN_CONTAINER_PKI}/admin.crt", readOnly=True),
            Volume(hostPath=admin_key_path, mountPath=f"{IN_CONTAINER_PKI}/admin.key", readOnly=True),
            Volume(hostPath=config_path, mountPath=IN_CONTAINER_KWOK_CONFIG, readOnly=True),
        ]
        if port:
            # publish the engine's healthz/metrics server to the host
            ports = [Port(hostPort=port, port=8080)]
        args = [
            "--manage-all-nodes=true",
            f"--kubeconfig={IN_CONTAINER_KUBECONFIG}",
            f"--config={IN_CONTAINER_KWOK_CONFIG}",
            f"--server-address={PUBLIC_ADDRESS}:8080",
        ]
    else:
        args = [
            "--manage-all-nodes=true",
            f"--kubeconfig={kubeconfig_path}",
            f"--config={config_path}",
            f"--server-address={address}:{port}",
        ]
    return Component(
        name="kwok-controller",
        version=version,
        links=["kube-apiserver"],
        binary=binary,
        image=image,
        command=["kwok"],
        workDir=workdir,
        ports=ports,
        volumes=volumes,
        args=args,
    )


def build_prometheus(
    binary: str = "",
    workdir: str = "",
    config_path: str = "",
    port: int = 0,
    image: str = "",
    version: str = "",
    address: str = LOCAL_ADDRESS,
    links: list[str] | None = None,
    admin_cert_path: str = "",
    admin_key_path: str = "",
) -> Component:
    # default links assume the full control plane; callers with disabled
    # components must pass the names actually present, or group_by_links
    # could never place prometheus
    in_container = bool(image)
    ports: list[Port] = []
    volumes: list[Volume] = []
    if in_container:
        # prometheus.go:47-75: config + certs mounted, 9090 published
        volumes += [
            Volume(hostPath=config_path, mountPath=IN_CONTAINER_PROMETHEUS_CONFIG, readOnly=True),
            Volume(hostPath=admin_cert_path, mountPath=f"{IN_CONTAINER_PKI}/admin.crt", readOnly=True),
            Volume(hostPath=admin_key_path, mountPath=f"{IN_CONTAINER_PKI}/admin.key", readOnly=True),
        ]
        ports = [Port(hostPort=port, port=9090)]
        args = [
            f"--config.file={IN_CONTAINER_PROMETHEUS_CONFIG}",
            f"--web.listen-address={PUBLIC_ADDRESS}:9090",
        ]
    else:
        args = [
            f"--config.file={config_path}",
            f"--web.listen-address={address}:{port}",
        ]
    return Component(
        name="prometheus",
        version=version,
        links=list(links)
        if links is not None
        else [
            "etcd",
            "kube-apiserver",
            "kube-controller-manager",
            "kube-scheduler",
            "kwok-controller",
        ],
        binary=binary,
        image=image,
        command=["prometheus"],
        workDir=workdir,
        ports=ports,
        volumes=volumes,
        args=args,
    )


def build_prometheus_config(
    project_name: str,
    etcd_port: int,
    kube_apiserver_port: int,
    kube_controller_manager_port: int,
    kube_scheduler_port: int,
    kwok_controller_port: int,
    secure_port: bool = False,
    admin_crt_path: str = "",
    admin_key_path: str = "",
) -> str:
    """Scrape config over every control-plane component
    (runtime/binary/prometheus.yaml.tpl semantics)."""
    scheme = "https" if secure_port else "http"
    tls = ""
    if secure_port:
        tls = (
            "    tls_config:\n"
            "      insecure_skip_verify: true\n"
            f"      cert_file: {admin_crt_path}\n"
            f"      key_file: {admin_key_path}\n"
        )

    def job(name: str, port: int, metrics_path: str = "/metrics", secure: bool = True) -> str:
        sch = scheme if secure else "http"
        out = (
            f"  - job_name: {name}\n"
            f"    scheme: {sch}\n"
            f"    metrics_path: {metrics_path}\n"
        )
        if secure and tls:
            out += tls
        out += (
            "    static_configs:\n"
            f"      - targets: ['127.0.0.1:{port}']\n"
        )
        return out

    cfg = (
        "global:\n"
        "  scrape_interval: 15s\n"
        f"  external_labels:\n    cluster: {project_name}\n"
        "scrape_configs:\n"
    )
    cfg += job("etcd", etcd_port, secure=False)
    cfg += job("kube-apiserver", kube_apiserver_port)
    if kube_controller_manager_port:
        cfg += job("kube-controller-manager", kube_controller_manager_port)
    if kube_scheduler_port:
        cfg += job("kube-scheduler", kube_scheduler_port)
    cfg += job("kwok-controller", kwok_controller_port, secure=False)
    return cfg


def build_prometheus_config_compose(
    project_name: str,
    secure_port: bool = False,
    admin_crt_path: str = f"{IN_CONTAINER_PKI}/admin.crt",
    admin_key_path: str = f"{IN_CONTAINER_PKI}/admin.key",
    kube_controller_manager: bool = True,
    kube_scheduler: bool = True,
) -> str:
    """Scrape config for the compose runtime: targets are container DNS
    names `<project>-<component>:<in-container port>`
    (runtime/compose/prometheus.yaml.tpl semantics)."""
    scheme = "https" if secure_port else "http"
    tls = ""
    if secure_port:
        tls = (
            "    tls_config:\n"
            "      insecure_skip_verify: true\n"
            f"      cert_file: {admin_crt_path}\n"
            f"      key_file: {admin_key_path}\n"
        )

    def job(name: str, target: str, secure: bool = True) -> str:
        sch = scheme if secure else "http"
        out = f"  - job_name: {name}\n    scheme: {sch}\n    metrics_path: /metrics\n"
        if secure and tls:
            out += tls
        out += f"    static_configs:\n      - targets: ['{target}']\n"
        return out

    cfg = (
        "global:\n"
        "  scrape_interval: 15s\n"
        f"  external_labels:\n    cluster: {project_name}\n"
        "scrape_configs:\n"
    )
    cfg += job("prometheus", "localhost:9090", secure=False)
    cfg += job("etcd", f"{project_name}-etcd:2379", secure=False)
    cfg += job(
        "kube-apiserver",
        f"{project_name}-kube-apiserver:{6443 if secure_port else 8080}",
    )
    if kube_controller_manager:
        cfg += job(
            "kube-controller-manager",
            f"{project_name}-kube-controller-manager:{10257 if secure_port else 10252}",
        )
    if kube_scheduler:
        cfg += job(
            "kube-scheduler",
            f"{project_name}-kube-scheduler:{10259 if secure_port else 10251}",
        )
    cfg += job("kwok-controller", f"{project_name}-kwok-controller:8080", secure=False)
    return cfg
