"""Deterministic fault injection at the engine's real I/O boundaries.

The chaos substrate for ROADMAP items 4-5: the 410/compaction/restart
dialects the mock apiservers already speak (edge/mockserver.py,
native/apiserver.cc) and the pump's connection-failure contract
(native/pump.cc) are only worth anything if the threaded engine is
routinely *driven through them*. This module wraps the three boundaries
faults actually enter through:

- the KubeClient transport (``wrap_client``): watch handshake 410 storms,
  mid-stream connection cuts, list failures, and apiserver-restart
  blackout windows;
- the native pump (``wrap_pump``): dropped connections, short writes
  (a batch suffix dies mid-frame with status 0 — exactly pump.cc's
  failure contract), and send delays;
- worker threads (``kill_worker`` / the ``worker.kill`` spec): a
  :class:`WorkerKilled` poison pill async-raised into a named
  ``spawn_worker`` thread, which the watchdog must absorb and restart.

Determinism: every boundary draws from its own ``random.Random`` stream
seeded from ``(seed, site)``, so one site's decision sequence never
depends on how other sites' calls interleave across threads. Same spec +
same per-site call sequence -> same faults.

Zero cost when disabled: with no spec there is no plane, no wrapper
objects exist, and the engine's hot paths carry no fault checks — the
only trace is an ``is None`` test at construction time.

Spec grammar (``EngineConfig.faults`` / ``KWOK_TPU_FAULTS``)::

    seed=42;pump.drop=0.02;pump.partial=0.02;pump.delay=0.01:0.05;
    watch.expire=0.2;watch.cut=0.001;list.fail=0.1;
    api.blackout=0.01:0.5;worker.kill=kwok-lane*:2.0

Entries are ``;``-separated ``key=value`` pairs. Probability-valued keys
take ``p`` or ``p:arg`` (``pump.delay``'s arg is seconds of sleep,
``api.blackout``'s the blackout window length). ``worker.kill`` and
``lane.sigstop`` take ``<name-glob>:<period-seconds>``: every period,
one live matching worker/process is killed (or SIGSTOPped), rotating
through matches. Under ``--lane-procs`` the parent derives each child's
plane via :func:`child_spec_text` — the CHILD_KINDS subset re-seeded as
``(seed, lane_index, kind)`` — and the shm/IPC tier (``shm.torn``,
``shm.desc_drop``, ``shm.desc_garble``, ``shm.stall``) exercises the
ring/descriptor/seqlock surfaces. See docs/resilience.md.
"""

from __future__ import annotations

import ctypes
import fnmatch
import json
import logging
import random
import threading
import time

from kwok_tpu.edge.kubeclient import WatchExpired
from kwok_tpu.telemetry.errors import PROCESS_REGISTRY

logger = logging.getLogger("kwok_tpu.resilience")

_injected = PROCESS_REGISTRY.counter(
    "kwok_faults_injected_total",
    "Faults injected by the resilience fault plane, by kind "
    "(pump.drop, watch.expire, worker.kill, ...); only moves when "
    "KWOK_TPU_FAULTS / EngineConfig.faults is set",
    ("kind",),
)

# every fault kind the spec accepts; parse rejects anything else so a
# typo'd key fails fast instead of silently injecting nothing
KINDS = (
    "pump.drop",      # whole pump batch loses its connection (status 0)
    "pump.partial",   # short write: a batch SUFFIX dies mid-frame
    "pump.delay",     # sleep arg seconds before the send
    "watch.expire",   # watch handshake answers 410 (WatchExpired)
    "watch.cut",      # per-event/line: stream cut (connection drop)
    "list.fail",      # LIST raises a connection error
    "api.blackout",   # all transport fails for arg seconds (restart)
    "worker.kill",    # kill matching workers every arg seconds
    # hostile-wire tier (ISSUE 10): bytes are WRONG, not just absent
    "wire.garble",    # flip/insert bytes in a watch line / LIST body
    "wire.truncate",  # cut a line mid-JSON, then die without a clean close
    "wire.dup",       # replay the immediately-prior event/line
    "wire.stale",     # re-deliver an OLD event (regressed resourceVersion)
    "clock.jump",     # skew the engine's `now` by uniform(-arg, +arg)
    # shm/IPC tier (ISSUE 17): faults on the --lane-procs surfaces
    "shm.torn",       # writer dies mid-slab (odd seq / half-armed slot)
    "shm.desc_drop",  # a ring descriptor is lost before the pipe send
    "shm.desc_garble",  # descriptor corrupted in flight (bounds-reject)
    "shm.stall",      # child pauses ring consumption for arg seconds
)

# the subset of kinds a lane CHILD's plane may carry: faults on the
# child's own boundaries (its HttpKubeClient, its pumps, its clock, its
# shm consumer/publisher side). Ingest faults (watch.*, list.fail,
# api.blackout on the watch plane), router-side shm faults and real
# signal delivery (worker.kill / lane.sigstop) stay on the parent, which
# owns those surfaces.
CHILD_KINDS = (
    "pump.drop", "pump.partial", "pump.delay",
    "wire.garble", "wire.truncate", "wire.dup", "wire.stale",
    "clock.jump",
    "shm.torn", "shm.stall",
)


class FaultInjected(ConnectionError):
    """An injected transport failure. Subclasses ConnectionError so every
    existing reconnect/retry path treats it exactly like the real thing."""


class WorkerKilled(BaseException):
    """Poison pill async-raised into a worker thread. BaseException so the
    per-item ``except Exception`` guards inside worker loops cannot absorb
    it — the thread's supervision (resilience/watchdog.py) must."""


def _async_raise(thread: threading.Thread, exc=WorkerKilled) -> bool:
    """Raise ``exc`` inside ``thread`` at its next bytecode boundary.
    Returns False when the thread is gone (or the raise could not be
    armed). A thread parked in a C-level wait dies only once it wakes —
    acceptable for chaos workers, which wake constantly under load."""
    tid = thread.ident
    if tid is None or not thread.is_alive():
        return False
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid), ctypes.py_object(exc)
    )
    if res > 1:  # should not happen; undo rather than corrupt the thread
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(tid), None
        )
        return False
    return res == 1


class _Rate:
    __slots__ = ("p", "arg")

    def __init__(self, p: float, arg: float = 0.0):
        self.p = float(p)
        self.arg = float(arg)


class FaultSpec:
    """Parsed fault spec: per-kind rates + the deterministic seed."""

    def __init__(self, seed: int = 0, rates: "dict[str, _Rate] | None" = None):
        self.seed = int(seed)
        self.rates: dict[str, _Rate] = rates or {}
        self.kill_glob = ""
        self.kill_period = 0.0
        self.sigstop_glob = ""
        self.sigstop_period = 0.0
        # lane index of the child plane this spec was derived for; -1 on
        # a parent/threaded plane. Folded into every stream seed so the
        # same parent spec gives each lane a DIFFERENT but reproducible
        # decision sequence.
        self.lane = -1

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        spec = cls()
        for entry in text.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if "=" not in entry:
                raise ValueError(f"fault spec entry {entry!r}: missing '='")
            key, _, value = entry.partition("=")
            key = key.strip()
            value = value.strip()
            if key == "seed":
                spec.seed = int(value)
                continue
            if key == "lane":
                spec.lane = int(value)
                continue
            if key in ("worker.kill", "lane.sigstop"):
                glob, _, period = value.rpartition(":")
                if not glob:
                    raise ValueError(
                        f"{key} takes <name-glob>:<period-seconds>"
                    )
                if float(period) <= 0:
                    raise ValueError(f"{key} period must be > 0")
                if key == "worker.kill":
                    spec.kill_glob, spec.kill_period = glob, float(period)
                else:
                    spec.sigstop_glob, spec.sigstop_period = (
                        glob, float(period)
                    )
                continue
            if key not in KINDS:
                raise ValueError(
                    f"unknown fault kind {key!r} (known: {', '.join(KINDS)})"
                )
            p, _, arg = value.partition(":")
            spec.rates[key] = _Rate(p, float(arg) if arg else 0.0)
        return spec

    def rate(self, kind: str) -> "_Rate | None":
        return self.rates.get(kind)

    def render(self) -> str:
        """Serialize back to the spec grammar (parse(render()) is
        equivalent). The propagation surface: the parent renders each
        lane's derived child spec into the spawn payload."""
        parts = [f"seed={self.seed}"]
        if self.lane >= 0:
            parts.append(f"lane={self.lane}")
        for kind in KINDS:  # KINDS order: deterministic text
            rate = self.rates.get(kind)
            if rate is None:
                continue
            if rate.arg:
                parts.append(f"{kind}={rate.p}:{rate.arg}")
            else:
                parts.append(f"{kind}={rate.p}")
        if self.kill_glob:
            parts.append(f"worker.kill={self.kill_glob}:{self.kill_period}")
        if self.sigstop_glob:
            parts.append(
                f"lane.sigstop={self.sigstop_glob}:{self.sigstop_period}"
            )
        return ";".join(parts)


def child_spec_text(spec: "FaultSpec | None", lane_index: int) -> str:
    """Derive the fault spec a lane child should run: the parent's rates
    restricted to CHILD_KINDS (the boundaries the child actually owns),
    re-keyed with ``lane=<i>`` so every stream re-seeds as
    (seed, lane_index, kind). Signal delivery and ingest faults never
    propagate. Returns the literal ``"off"`` when nothing survives the
    filter — the child then builds NO plane (zero-cost contract), even
    when KWOK_TPU_FAULTS is set in the inherited environment."""
    if spec is None:
        return "off"
    child = FaultSpec(seed=spec.seed)
    child.lane = int(lane_index)
    child.rates = {
        k: v for k, v in spec.rates.items() if k in CHILD_KINDS
    }
    if not child.rates:
        return "off"
    return child.render()


class FaultPlane:
    """One seeded instance of the fault plane: decision streams, the
    blackout window, counters, and the optional worker-killer thread."""

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        # per-site decision streams: one Random per kind, seeded from
        # (seed, kind) — (seed, lane, kind) on a lane child's plane —
        # each behind its own lock so a site's sequence is a pure
        # function of its own call count (thread interleaving across
        # sites cannot perturb it)
        _lane = f"L{spec.lane}:" if spec.lane >= 0 else ""
        self._streams = {
            kind: (
                random.Random(f"{spec.seed}:{_lane}{kind}"),
                threading.Lock(),
            )
            for kind in KINDS
        }
        # blackout state: monotonic deadline; reads are lock-free (float
        # store is GIL-atomic), arming happens under the fault lock
        self._blackout_until = 0.0
        # clock.jump skew: the offset added to engine `now`; re-drawn (not
        # accumulated — convergence must stay bounded) on each firing draw
        self._skew = 0.0
        self._fault_lock = threading.Lock()
        self._events: dict[str, int] = {}
        self._started = 0
        self._killer: "threading.Thread | None" = None
        self._stop = threading.Event()
        self._kill_results: list[dict] = []
        # process-lane kill targets (engine/proclanes.py): name -> a
        # callable delivering a REAL SIGKILL to the lane process. The
        # worker.kill spec matches these exactly like supervised thread
        # names, so `worker.kill=kwok-lane*` kills processes under
        # --lane-procs and threads otherwise.
        self._proc_targets: dict = {}
        # lane.sigstop targets: name -> callable delivering SIGSTOP (the
        # wedged-but-alive shape; the supervisor's stall-kill recovers)
        self._stop_targets: dict = {}
        self._stopper: "threading.Thread | None" = None

    # ------------------------------------------------------------ decisions

    def decide(self, kind: str) -> "_Rate | None":
        """One draw from ``kind``'s stream: its rate when the fault fires,
        else None. Sites with no configured rate never draw (their stream
        stays untouched, preserving determinism for enabled sites)."""
        rate = self.spec.rate(kind)
        if rate is None or rate.p <= 0.0:
            return None
        rng, lock = self._streams[kind]
        with lock:
            fired = rng.random() < rate.p
        return rate if fired else None

    def record(self, kind: str) -> None:
        """Account one injected fault (counter + the artifact tally)."""
        with self._fault_lock:
            self._events[kind] = self._events.get(kind, 0) + 1
        # registry child locks are leaves; never take them under ours
        _injected.labels(kind=kind).inc()

    def counts(self) -> dict:
        """Injected-fault tally by kind (chaos artifact surface)."""
        with self._fault_lock:
            return dict(self._events)

    def kill_log(self) -> list[dict]:
        with self._fault_lock:
            return list(self._kill_results)

    # ---------------------------------------------------------- hostile wire

    def clock_skew(self) -> float:
        """The current clock.jump skew in seconds, re-drawn from the
        kind's stream with its configured probability per read. The skew
        JUMPS to a fresh uniform(-arg, +arg) value instead of
        accumulating, so hostile clocks stay bounded (arg must be well
        under the heartbeat interval). Only the engine's ``_now`` calls
        this, and only when the spec configures clock.jump."""
        rate = self.decide("clock.jump")
        if rate is not None:
            rng, lock = self._streams["clock.jump"]
            with lock:
                self._skew = rng.uniform(-rate.arg, rate.arg)
            self.record("clock.jump")
        return self._skew

    def garble_bytes(self, data: bytes) -> bytes:
        """One seeded byte-level corruption: flip a byte to a different
        value, or insert a junk byte — the two shapes a hostile wire
        produces without changing framing. Callers already drew the
        wire.garble decision; this only draws the corruption shape."""
        if not data:
            return b"\xff"
        rng, lock = self._streams["wire.garble"]
        with lock:
            i = rng.randrange(len(data))
            delta = rng.randrange(1, 256)
            insert = rng.random() < 0.5
        if insert:
            return data[:i] + bytes((delta,)) + data[i:]
        return data[:i] + bytes(((data[i] ^ delta),)) + data[i + 1:]

    def truncate_bytes(self, data: bytes) -> bytes:
        """A seeded mid-JSON cut: a strict, non-empty prefix."""
        if len(data) < 2:
            return data[:1]
        rng, lock = self._streams["wire.truncate"]
        with lock:
            k = rng.randrange(1, len(data))
        return data[:k]

    # ------------------------------------------------------------- blackout

    def transport_fault(self, op: str) -> None:
        """Shared unary-transport gate: raises FaultInjected while a
        blackout window is open, and may open one (api.restart
        semantics: every caller fails until the window closes)."""
        now = time.monotonic()
        if now < self._blackout_until:
            self.record("api.blackout")
            raise FaultInjected(f"injected apiserver blackout ({op})")
        rate = self.decide("api.blackout")
        if rate is not None:
            with self._fault_lock:
                self._blackout_until = now + max(rate.arg, 0.05)
            self.record("api.blackout")
            raise FaultInjected(f"injected apiserver restart ({op})")

    # ------------------------------------------------------------- wrappers

    def wrap_client(self, client):
        """Fault-injecting view over a KubeClient. Idempotent: an already
        wrapped client is returned unchanged (lane engines share their
        parent's client)."""
        if isinstance(client, FaultyClient):
            return client
        return FaultyClient(self, client)

    def wrap_pump(self, pump):
        return FaultyPump(self, pump)

    # --------------------------------------------------------- worker kills

    def start(self) -> None:
        """Arm the worker-killer / lane-stopper threads (when the spec
        asks for them). Refcounted: engines sharing the plane start/stop
        them together."""
        with self._fault_lock:
            self._started += 1
            if self._started > 1:
                return
            self._stop.clear()
            from kwok_tpu.workers import spawn_worker

            if self._killer is None and self.spec.kill_glob:
                self._killer = spawn_worker(
                    self._kill_loop, name="kwok-chaos-killer"
                )
            if self._stopper is None and self.spec.sigstop_glob:
                self._stopper = spawn_worker(
                    self._sigstop_loop, name="kwok-chaos-stopper"
                )

    def stop(self) -> None:
        with self._fault_lock:
            self._started = max(0, self._started - 1)
            if self._started:
                return
            killer, self._killer = self._killer, None
            stopper, self._stopper = self._stopper, None
        if killer is not None or stopper is not None:
            self._stop.set()
        if killer is not None:
            killer.join(timeout=5)
        if stopper is not None:
            stopper.join(timeout=5)

    # Threads the spec-driven killer may target: ONLY the watchdog-
    # supervised workers — lane workers (LaneSet.start_workers) and,
    # since ISSUE 7, the watch ingest loops (ClusterEngine._spawn_watch
    # spawns them under the watchdog; a restarted watch loop re-lists by
    # construction, so the restart IS the recovery). Killing an
    # unsupervised singleton (kwok-tick, kwok-http, the profiling
    # sampler) would end it for good with /readyz still 200 — a
    # silently-dead engine, not a self-healing exercise. Tests that
    # want to assassinate arbitrary threads call kill_worker directly.
    _SUPERVISED_PREFIXES = (
        "kwok-lane", "kwok-emit", "kwok-route", "kwok-watch",
    )

    def register_proc_target(self, name: str, kill_fn, stop_fn=None) -> None:
        """Expose a supervised lane PROCESS to the worker.kill rotation;
        ``kill_fn()`` must deliver SIGKILL and return whether it did.
        ``stop_fn()`` (optional) delivers SIGSTOP for the lane.sigstop
        rotation — the wedged-but-alive shape whose recovery is the
        supervisor's KWOK_TPU_LANE_STALL_S stall-kill."""
        with self._fault_lock:
            self._proc_targets[name] = kill_fn
            if stop_fn is not None:
                self._stop_targets[name] = stop_fn

    def unregister_proc_target(self, name: str) -> None:
        with self._fault_lock:
            self._proc_targets.pop(name, None)
            self._stop_targets.pop(name, None)

    def _kill_loop(self) -> None:
        from kwok_tpu.workers import live_workers

        nth = 0
        while not self._stop.wait(self.spec.kill_period):
            with self._fault_lock:
                procs = dict(self._proc_targets)
            names = sorted(
                {
                    n for n in live_workers()
                    if fnmatch.fnmatch(n, self.spec.kill_glob)
                    and n.startswith(self._SUPERVISED_PREFIXES)
                }
                | {
                    n for n in procs
                    if fnmatch.fnmatch(n, self.spec.kill_glob)
                }
            )
            if not names:
                continue
            # rotate deterministically through the sorted matches
            name = names[nth % len(names)]
            nth += 1
            if name in procs:
                self.kill_process(name, procs[name])
            else:
                self.kill_worker(name)

    def kill_process(self, name: str, kill_fn) -> bool:
        """SIGKILL a registered lane process (the process-lane twin of
        kill_worker: same counter, same kill log)."""
        try:
            ok = bool(kill_fn())
        except Exception:
            logger.exception("chaos: SIGKILL of %s failed", name)
            return False
        if ok:
            self.record("worker.kill")
            with self._fault_lock:
                self._kill_results.append(
                    {"thread": name, "proc": True, "t": time.monotonic()}
                )
            logger.warning("chaos: SIGKILLed lane process %s", name)
        return ok

    def _sigstop_loop(self) -> None:
        """Rotate SIGSTOP through registered lane processes matching the
        lane.sigstop glob. The stopped child keeps its shm maps and pipe
        but its StatusBank beat freezes — the parent's supervisor must
        stall-kill (SIGKILL works on a stopped process) and respawn."""
        nth = 0
        while not self._stop.wait(self.spec.sigstop_period):
            with self._fault_lock:
                stops = dict(self._stop_targets)
            names = sorted(
                n for n in stops
                if fnmatch.fnmatch(n, self.spec.sigstop_glob)
            )
            if not names:
                continue
            name = names[nth % len(names)]
            nth += 1
            self.stop_process(name, stops[name])

    def stop_process(self, name: str, stop_fn) -> bool:
        """SIGSTOP a registered lane process (wedged-but-alive: counted
        like a kill, recovered by the supervisor's stall-kill)."""
        try:
            ok = bool(stop_fn())
        except Exception:
            logger.exception("chaos: SIGSTOP of %s failed", name)
            return False
        if ok:
            self.record("lane.sigstop")
            with self._fault_lock:
                self._kill_results.append(
                    {"thread": name, "proc": True, "stop": True,
                     "t": time.monotonic()}
                )
            logger.warning("chaos: SIGSTOPped lane process %s", name)
        return ok

    def kill_worker(self, name: str) -> bool:
        """Async-raise WorkerKilled into the named spawn_worker thread.
        Returns whether the pill was armed."""
        from kwok_tpu.workers import live_workers

        t = live_workers().get(name)
        if t is None:
            return False
        ok = _async_raise(t)
        if ok:
            self.record("worker.kill")
            with self._fault_lock:
                self._kill_results.append(
                    {"thread": name, "t": time.monotonic()}
                )
            logger.warning("chaos: killed worker %s", name)
        return ok


class FaultyClient:
    """KubeClient wrapper injecting transport faults. Unknown attributes
    delegate, so FakeKube test hooks and HttpKubeClient extras survive."""

    def __init__(self, plane: FaultPlane, inner):
        self._plane = plane
        self._inner = inner

    def list(self, kind, **kw):
        self._plane.transport_fault("list")
        if self._plane.decide("list.fail") is not None:
            self._plane.record("list.fail")
            raise FaultInjected(f"injected list failure ({kind})")
        out = self._inner.list(kind, **kw)
        if self._plane.decide("wire.truncate") is not None:
            # a LIST body cut mid-JSON: the whole-document parse fails —
            # the same error shape json.loads raises in the real client
            self._plane.record("wire.truncate")
            raise FaultInjected(f"injected truncated LIST body ({kind})")
        if self._plane.decide("wire.garble") is not None:
            self._plane.record("wire.garble")
            return self._garble_list(kind, out)
        return out

    def _garble_list(self, kind, items):
        """Byte-corrupt the LIST body: serialize, garble, re-parse.
        A parse failure is what a real garbled body does to the client
        (raised, caller re-lists); a still-parseable result carries the
        corrupted values into ingest — the anti-entropy auditor's case."""
        blob = json.dumps({"items": items}, separators=(",", ":")).encode()
        try:
            doc = json.loads(self._plane.garble_bytes(blob))
            got = doc.get("items")
            if not isinstance(got, list):
                raise ValueError("garbled items")
        except ValueError:
            raise FaultInjected(
                f"injected garbled LIST body ({kind})"
            ) from None
        return [o for o in got if isinstance(o, dict)]

    def watch(self, kind, **kw):
        self._plane.transport_fault("watch")
        if kw.get("resource_version") and (
            self._plane.decide("watch.expire") is not None
        ):
            # a compaction storm: every rv-resume is below the floor
            self._plane.record("watch.expire")
            raise WatchExpired(f"injected compaction ({kind})")
        return FaultyWatch(self._plane, self._inner.watch(kind, **kw))

    def get(self, kind, namespace, name):
        self._plane.transport_fault("get")
        return self._inner.get(kind, namespace, name)

    def create(self, kind, obj, *a, **kw):
        self._plane.transport_fault("create")
        return self._inner.create(kind, obj, *a, **kw)

    def patch_status(self, kind, namespace, name, patch):
        self._plane.transport_fault("patch_status")
        return self._inner.patch_status(kind, namespace, name, patch)

    def patch_meta(self, kind, namespace, name, patch):
        self._plane.transport_fault("patch_meta")
        return self._inner.patch_meta(kind, namespace, name, patch)

    def delete(self, kind, namespace, name, **kw):
        self._plane.transport_fault("delete")
        return self._inner.delete(kind, namespace, name, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyWatch:
    """Watch-handle wrapper: cuts the stream (connection drop) with
    ``watch.cut`` probability per event/line, and speaks the hostile-wire
    tier — ``wire.dup`` (replay the prior event), ``wire.stale``
    (re-deliver an old event whose resourceVersion has regressed),
    ``wire.garble`` (byte corruption) and ``wire.truncate`` (a mid-JSON
    cut followed by an abrupt stream death). The native reader is
    disabled — it reads the socket from C, where per-line injection
    cannot reach — so a faulted engine always takes a Python-visible
    ingest path (raw_lines when the inner handle has it)."""

    native_reader = None  # force the per-line path under faults

    #: replay window for wire.dup / wire.stale (per stream)
    _RECENT = 64

    def __init__(self, plane: FaultPlane, inner):
        self._plane = plane
        self._inner = inner
        if hasattr(inner, "raw_lines"):
            # instance attribute: engines probe with getattr, and a
            # wrapper around a handle WITHOUT raw_lines must not grow one
            self.raw_lines = self._raw_lines

    def _cut(self) -> bool:
        if self._plane.decide("watch.cut") is not None:
            self._plane.record("watch.cut")
            self._stop_inner()
            return True
        return False

    def _stop_inner(self) -> None:
        try:
            self._inner.stop()
        except Exception:
            logger.debug("inner watch stop failed mid-cut", exc_info=True)

    def __iter__(self):
        """Parsed-event path (clients without raw_lines): the wire tier is
        emulated at the event level. Garble serializes the event document,
        corrupts bytes, and re-parses — a still-parseable result delivers
        the corrupted values (the auditor's case); an unparseable one ends
        the stream the way the hardened client does on a bad line
        (integrity doubt -> reconnect resumes and the server replays)."""
        import collections
        import json as _json

        from kwok_tpu.edge.kubeclient import WatchEvent

        plane = self._plane
        recent: "collections.deque" = collections.deque(maxlen=self._RECENT)
        for ev in self._inner:
            if self._cut():
                return
            if recent and plane.decide("wire.dup") is not None:
                plane.record("wire.dup")
                yield recent[-1]
            if recent and plane.decide("wire.stale") is not None:
                plane.record("wire.stale")
                yield recent[0]
            if plane.decide("wire.truncate") is not None:
                plane.record("wire.truncate")
                self._stop_inner()
                return  # the half-delivered event dies with the stream
            if plane.decide("wire.garble") is not None:
                plane.record("wire.garble")
                blob = plane.garble_bytes(_json.dumps(
                    {"type": ev.type, "object": ev.object},
                    separators=(",", ":"), default=str,
                ).encode())
                try:
                    doc = _json.loads(blob)
                    type_ = doc.get("type")
                    obj = doc.get("object")
                    if type_ not in ("ADDED", "MODIFIED", "DELETED",
                                     "BOOKMARK") or not isinstance(obj, dict):
                        raise ValueError("garbled event")
                except ValueError:
                    # unparseable on the wire: the hardened client treats
                    # it as integrity doubt and ends the stream
                    self._stop_inner()
                    return
                recent.append(ev)
                yield WatchEvent(type_, obj)
                continue
            recent.append(ev)
            yield ev

    def _raw_lines(self):
        """Raw byte-line path (the engine's native-parse ingest edge):
        the wire tier operates on the real bytes."""
        import collections

        plane = self._plane
        recent: "collections.deque" = collections.deque(maxlen=self._RECENT)
        for line in self._inner.raw_lines():
            if self._cut():
                return
            if recent and plane.decide("wire.dup") is not None:
                plane.record("wire.dup")
                yield recent[-1]
            if recent and plane.decide("wire.stale") is not None:
                plane.record("wire.stale")
                yield recent[0]
            if plane.decide("wire.truncate") is not None:
                plane.record("wire.truncate")
                yield plane.truncate_bytes(line)
                self._stop_inner()
                return  # mid-JSON cut, no clean close
            if plane.decide("wire.garble") is not None:
                plane.record("wire.garble")
                recent.append(line)
                yield plane.garble_bytes(line)
                continue
            recent.append(line)
            yield line

    def stop(self) -> None:
        self._inner.stop()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyPump:
    """Native-pump wrapper reproducing pump.cc's failure contract on
    demand: a dropped connection fails the whole batch with status 0; a
    short write delivers a PREFIX and fails the suffix mid-frame (the
    exact shape the partial-write fix in the engine's ``_pump_send``
    retry must recover from); a delay stalls the send."""

    def __init__(self, plane: FaultPlane, inner):
        self._plane = plane
        self._inner = inner

    def send(self, requests):
        import numpy as np

        plane = self._plane
        rate = plane.decide("pump.delay")
        if rate is not None:
            plane.record("pump.delay")
            time.sleep(rate.arg or 0.01)
        if plane.decide("pump.drop") is not None:
            plane.record("pump.drop")
            return np.zeros(len(requests), np.int32)
        if len(requests) > 1 and plane.decide("pump.partial") is not None:
            plane.record("pump.partial")
            rng, lock = plane._streams[("pump.partial")]
            with lock:
                k = rng.randrange(1, len(requests))
            head = self._inner.send(requests[:k])
            return np.concatenate(
                [head, np.zeros(len(requests) - k, np.int32)]
            )
        return self._inner.send(requests)

    def close(self) -> None:
        self._inner.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)


def from_config(spec_text: str = "") -> "FaultPlane | None":
    """The engine's entry point: a FaultPlane when a spec is configured
    (EngineConfig.faults, falling back to KWOK_TPU_FAULTS), else None —
    the disabled case allocates nothing and wraps nothing. The literal
    ``"off"`` disables the plane even when the env var is set (a lane
    child whose parent has no plane — or no child-side kinds — receives
    it via :func:`child_spec_text`, so an inherited KWOK_TPU_FAULTS can
    never resurrect a plane the parent decided against)."""
    import os

    text = (spec_text or os.environ.get("KWOK_TPU_FAULTS", "")).strip()
    if not text or text == "off":
        return None
    return FaultPlane(FaultSpec.parse(text))
