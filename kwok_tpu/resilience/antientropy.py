"""Anti-entropy auditor: a continuous convergence oracle for engine rows.

The transport tiers (PR 6-8) harden against *clean* faults — connections
die, servers say 429, bytes are never wrong. The hostile-wire tier
(faults.py ``wire.*``) and plain operational entropy (a store restored
behind the engine's back, an operator's stray ``kubectl edit``, a
corrupted-but-parseable LIST body) can make engine device state and
apiserver truth *silently* diverge, and nothing on the event path can
notice: no event fires for a mutation the watch never delivered.

This module closes that hole the way Dynamo/Cassandra anti-entropy does —
a paced background pass that re-reads a budgeted window of ground truth
and diffs it against local state:

- **window**: one page-budgeted LIST per kind per pass, through the SAME
  selectors the engine's watch streams use (``HttpKubeClient.list_page``
  when the client has it; the scan cursor survives across passes, so big
  clusters are audited in slices and the auditor can never self-inflict
  the apiserver's 429 admission storm);
- **diff**: each listed object vs its engine row by ``(uid, rv, phase)``,
  plus — once a scan cycle has covered the whole keyspace — engine rows
  the server no longer has;
- **classify**: ``missed-event`` (object with no row), ``ghost-row``
  (row whose object is gone or was deleted+recreated under a new uid),
  ``double-apply`` (the engine ingested revisions the server does not
  have — the old-world signature after a store rewind), ``stale-row``
  (same object, same uid, but the server's status/phase disagrees with
  the engine-owned truth);
- **suspicion**: a divergence only counts once it survives a settle
  re-check inside the same pass (fresh per-object GET + fresh row read),
  so in-flight transitions and not-yet-landed patches never count;
- **repair**: per row, by re-ingest through the engine's own queue — a
  fresh ``ADDED`` re-runs the upsert + repair-render tier (which
  re-patches the engine-owned status back onto the server), a synthetic
  ``DELETED`` releases a ghost row. Never wholesale.

Exports ``kwok_drift_detected_total{kind=,reason=}``,
``kwok_drift_repaired_total`` and ``kwok_audit_pass_seconds`` on the
engine's registry, and degrades ``/readyz`` (``kwok_degraded{reason=
"drift"}``) only when the SAME divergence survives repair for several
consecutive passes — detection alone is the auditor doing its job.

Off by default (``--audit-interval`` / ``auditInterval`` /
``KWOK_TPU_AUDIT_INTERVAL``); disabled means disabled: no thread, no
LISTs, no per-tick cost anywhere in the engine.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from kwok_tpu.edge.kubeclient import (
    ADDED,
    DELETED,
    ContinueExpired,
    TooManyRequests,
)
from kwok_tpu.engine.rowpool import shard_of
from kwok_tpu.models.lifecycle import NODE_PHASES
from kwok_tpu.resilience.checkpoint import row_uid

logger = logging.getLogger("kwok_tpu.resilience")

#: divergence classes (the kwok_drift_detected_total reason label)
REASONS = ("missed-event", "double-apply", "stale-row", "ghost-row")

# Per-pass budgets. Pages/pass bounds the read load (the 429-storm
# guard); suspects/pass bounds the settle re-check GET fan-out. Both are
# deliberately small — anti-entropy converges over passes, not within
# one — and env-tunable for rigs.
_PAGE_SIZE = int(os.environ.get("KWOK_TPU_AUDIT_PAGE_SIZE", "256"))
_MAX_PAGES = int(os.environ.get("KWOK_TPU_AUDIT_MAX_PAGES", "4"))
_MAX_SUSPECTS = 64

#: consecutive passes one divergence must survive REPAIR before the
#: engine degrades (reason "drift"): 1-2 passes are normal repair
#: latency, 3+ means re-ingest is not converging
_DEGRADE_STREAK = 3

_HELP_DETECTED = (
    "Silent state divergences the anti-entropy auditor confirmed "
    "(survived the settle re-check) between apiserver truth and engine "
    "rows, by kind and class: missed-event (object with no row), "
    "double-apply (engine rv ahead of the server's — old-world state), "
    "stale-row (same uid, server status disagrees with engine-owned "
    "truth), ghost-row (row whose object is gone or was recreated under "
    "a new uid)"
)
_HELP_REPAIRED = (
    "Divergent rows the auditor repaired via re-ingest (a fresh ADDED "
    "re-runs upsert + the repair-render re-patch; a synthetic DELETED "
    "releases a ghost row)"
)
_HELP_PASS = (
    "Wall seconds per anti-entropy audit pass (budgeted LIST window + "
    "settle re-check + repair enqueue; only moves with --audit-interval "
    "set)"
)


class AntiEntropyAuditor:
    """One engine's background drift detector/repairer.

    Single audit thread by contract (``run`` is the worker target); the
    ``_ae_lock`` (kwoklint lock table, level-84 leaf) guards the scan
    cursor / cycle / streak state against snapshot reads from other
    threads (gates and tests read ``snapshot()`` while a pass runs).
    """

    def __init__(self, engine, interval: float,
                 page_size: int = 0, max_pages: int = 0,
                 settle_s: float = 0.0):
        self.engine = engine
        self.interval = max(0.05, float(interval))
        self.page_size = int(page_size) or _PAGE_SIZE
        self.max_pages = int(max_pages) or _MAX_PAGES
        # settle window: long enough for an in-flight patch to land
        # (executor RTT), short enough to stay inside one pass
        self.settle_s = float(settle_s) or max(
            0.2, 3.0 * float(engine.config.tick_interval)
        )
        # hash-shard scope (ISSUE 17): a --lane-procs CHILD audits only
        # the keys its lane owns — LIST windows are filtered by
        # rowpool.shard_of, so two lanes never double-repair one object
        # and repairs re-ingest through the OWNING lane's queue (per-key
        # order preserved by construction). (1, 0) everywhere else:
        # parent/threaded engines audit the whole keyspace.
        self.shard_i = int(getattr(engine, "_lane_index", 0))
        self.shard_n = int(getattr(engine, "_lane_n", 1))
        self._ae_lock = threading.Lock()
        self._cursor: dict[str, str] = {"nodes": "", "pods": ""}
        self._cycle_seen: dict[str, set] = {"nodes": set(), "pods": set()}
        # completed scan cycles per kind: the streak bookkeeping's clock.
        # Streaks must be judged per CYCLE, not per pass — on a cluster
        # larger than one window a divergent object is only re-scanned
        # once per cycle, and pass-keyed streaks would reset (and the
        # degraded flag clear) on every intervening healthy window
        self._cycles: dict[str, int] = {"nodes": 0, "pods": 0}
        # (kind, key, reason) -> [confirm_count, cycle_no at last confirm]
        self._streaks: dict[tuple, list] = {}
        self._passes = 0
        r = engine.telemetry.registry
        self._detected = r.counter(
            "kwok_drift_detected_total", _HELP_DETECTED, ("kind", "reason")
        )
        self._repaired = r.counter(
            "kwok_drift_repaired_total", _HELP_REPAIRED
        )
        self._pass_hist = r.histogram(
            "kwok_audit_pass_seconds", _HELP_PASS
        )

    # ------------------------------------------------------------- reads

    def detected_total(self, kind: str | None = None,
                       reason: str | None = None) -> int:
        total = 0
        for values, c in self._detected.children():
            if kind is not None and values[0] != kind:
                continue
            if reason is not None and values[1] != reason:
                continue
            total += c.value
        return total

    @property
    def repaired_total(self) -> int:
        return self._repaired.child.value

    def snapshot(self) -> dict:
        """Gate/diagnostic view of the auditor's state."""
        with self._ae_lock:
            return {
                "passes": self._passes,
                "cursor": dict(self._cursor),
                "streaks": {
                    "/".join(map(str, k)): v
                    for k, v in self._streaks.items()
                },
                "detected_total": self.detected_total(),
                "repaired_total": self.repaired_total,
            }

    # ----------------------------------------------------------- the loop

    def run(self) -> None:
        """Worker target (thread ``kwok-audit``, watchdog-supervised)."""
        eng = self.engine
        next_at = time.monotonic() + self.interval
        while eng._running:
            now = time.monotonic()
            if now < next_at:
                time.sleep(min(0.2, next_at - now))
                continue
            next_at = now + self.interval
            if not eng.ready:
                # the startup catch-up gate owns convergence until the
                # first full re-list lands; auditing half-built rows
                # would flood the suspect list with false positives
                continue
            try:
                self.pass_once()
            except TooManyRequests as e:
                # the admission tier said stop: honor the hint on top of
                # the normal cadence — the auditor must never contribute
                # to a 429 storm
                next_at = time.monotonic() + max(
                    self.interval, e.retry_after
                )
                eng.telemetry.add_throttle(e.retry_after)
                logger.warning(
                    "audit pass throttled by apiserver (429); next pass "
                    "in %.1fs", next_at - time.monotonic(),
                )
            except Exception:
                # transport faults (incl. injected ones) and transient
                # store errors: skip the pass, keep the cadence — the
                # next window re-reads everything this one missed
                logger.warning("audit pass failed", exc_info=True)

    def pass_once(self) -> None:
        """One audit pass over both kinds: window -> diff -> settle
        re-check -> repair -> degradation bookkeeping."""
        t0 = time.perf_counter()
        confirmed: list[tuple] = []  # (kind, key, reason)
        suspects: list[tuple] = []   # (kind, key, reason, ns, name)
        for kind in ("pods", "nodes"):
            # per-KIND cap (inside _scan_kind): a pod-drift storm must
            # not starve node suspects out of the shared re-check budget
            suspects.extend(self._scan_kind(kind))
        if suspects:
            self._settle_sleep()
            for kind, key, reason, ns, name in suspects:
                if self._recheck_and_repair(kind, key, reason, ns, name):
                    confirmed.append((kind, key, reason))
        self._account(confirmed)
        self._pass_hist.observe(time.perf_counter() - t0)

    # ------------------------------------------------------------ windows

    def _scan_kind(self, kind: str) -> list[tuple]:
        """List one budgeted window of ``kind`` and return divergence
        suspects ``(kind, key, reason, ns, name)``."""
        items, cycle_done = self._list_window(kind)
        out: list[tuple] = []
        capped = False
        seen = self._cycle_seen[kind]
        for obj in items:
            meta = obj.get("metadata") or {}
            name = meta.get("name")
            if not name:
                continue
            ns = meta.get("namespace") or "default"
            key = (ns, name) if kind == "pods" else name
            if self.shard_n > 1 and (
                shard_of(key, self.shard_n) != self.shard_i
            ):
                # another lane's shard: its own auditor covers it (a
                # node outside the shard is the topology TAP's — no row
                # here, and classifying it would flag a false
                # missed-event every cycle)
                continue
            with self._ae_lock:
                seen.add(key)
            reason = self._classify(kind, key, obj)
            if reason is not None:
                if len(out) >= _MAX_SUSPECTS:
                    capped = True
                    break
                out.append((kind, key, reason, ns, name))
        if cycle_done:
            # the scan covered the whole keyspace: rows the server never
            # returned are ghost suspects (verified per row by the
            # settle re-check's GET — a row acquired mid-cycle may
            # simply postdate its window)
            with self._ae_lock:
                cycle = set(seen)
                seen.clear()
                self._cycles[kind] += 1  # the streak bookkeeping's clock
            for key in self._engine_keys(kind):
                if key in cycle:
                    continue
                if len(out) >= _MAX_SUSPECTS:
                    capped = True
                    break
                if kind == "pods":
                    ns, name = key
                else:
                    ns, name = None, key
                out.append((kind, key, "ghost-row", ns, name))
        if capped:
            # never a silent cap: the remainder waits for later passes
            logger.warning(
                "audit pass capped %s suspects at %d; the rest re-check "
                "on later passes", kind, _MAX_SUSPECTS,
            )
        return out

    def _list_window(self, kind: str):
        """One page-budgeted LIST slice through the engine's own watch
        selectors. Returns ``(items, cycle_done)`` where ``cycle_done``
        means the scan cursor wrapped — the union of windows since the
        last wrap covered the whole keyspace."""
        eng = self.engine
        opts = eng._watch_opts.get(kind, {})
        page = getattr(eng.client, "list_page", None)
        if page is None:
            # clients without paging (the in-memory FakeKube): one full
            # list IS the whole cycle
            return eng.client.list(kind, **opts), True
        with self._ae_lock:
            cont = self._cursor[kind]
        items: list[dict] = []
        restarted = False
        for _ in range(self.max_pages):
            try:
                objs, cont = page(
                    kind, limit=self.page_size, cont=cont, **opts
                )
            except ContinueExpired:
                # the cursor was compacted away mid-scan: the scan
                # RESTARTS — typed, so a legitimately-empty final page
                # (no items, no token) still counts as a completed
                # cycle, while an expiry never does (every unscanned
                # engine row would otherwise become a false ghost
                # suspect swept against a just-compacted apiserver)
                restarted = True
                cont = ""
                break
            items.extend(objs)
            if not cont:
                break
        with self._ae_lock:
            self._cursor[kind] = cont
            if restarted:
                self._cycle_seen[kind].clear()
        return items, (not cont and not restarted)

    def _engine_keys(self, kind: str) -> list:
        eng = self.engine
        lanes = eng._lanes
        if lanes is None:
            # lock-free read racing the tick thread: a mid-copy resize
            # raises; yield and retry the C-level copy
            k = eng.pods if kind == "pods" else eng.nodes
            while True:
                try:
                    return list(k.pool.keys())
                except RuntimeError:
                    time.sleep(0)
        keys: list = []
        for lane in lanes.lanes:
            e = lane.engine
            k = e.pods if kind == "pods" else e.nodes
            with lane.stage_lock:
                # the lane's stage_lock serializes every pool mutation,
                # so one plain copy suffices (no retry, no sleep held)
                keys.extend(k.pool.keys())
        return keys

    # ----------------------------------------------------------- classify

    def _row_view(self, kind: str, key):
        """(uid, rv, phase_name) of the engine's row, or None. Reads are
        GIL-atomic dict/array ops; a torn read only creates a suspect the
        settle re-check throws out."""
        eng = self.engine
        lanes = eng._lanes
        if lanes is not None:
            from kwok_tpu.engine.rowpool import shard_of

            e = lanes.lanes[shard_of(key, lanes.n)].engine
        else:
            e = eng
        k = e.pods if kind == "pods" else e.nodes
        idx = k.pool.lookup(key)
        if idx is None:
            return None
        m = k.pool.meta[idx]
        if not m:
            return None
        try:
            rv = int(m.get("rv") or 0)
        except (TypeError, ValueError):
            rv = 0
        if kind == "pods":
            phase = e._pod_phases[int(k.phase_h[idx])]
        else:
            phase = NODE_PHASES.phases[int(k.phase_h[idx])]
        return row_uid(m), rv, phase

    def _classify(self, kind: str, key, obj: dict) -> "str | None":
        """One listed object vs its row; None = converged."""
        eng = self.engine
        view = self._row_view(kind, key)
        meta = obj.get("metadata") or {}
        if view is None:
            if kind == "pods":
                if not (obj.get("spec") or {}).get("nodeName"):
                    return None  # unscheduled: outside the watch filter
            elif not (
                eng._node_need_heartbeat(obj) or key in eng.node_has
            ):
                return None  # a node this engine does not manage
            return "missed-event"
        uid, rv, phase = view
        srv_uid = meta.get("uid") or ""
        try:
            srv_rv = int(meta.get("resourceVersion") or 0)
        except (TypeError, ValueError):
            srv_rv = 0
        if uid and srv_uid and uid != srv_uid:
            # deleted + recreated while the engine looked away: the row
            # describes an object that no longer exists
            return "ghost-row"
        if rv and srv_rv and srv_rv < rv:
            # the engine ingested revisions the server does not have —
            # a double-applied old-world state (store rewind signature)
            return "double-apply"
        if kind == "pods" and phase not in ("", "Gone"):
            srv_phase = (obj.get("status") or {}).get("phase") or ""
            if srv_phase and srv_phase != phase:
                # same object, same uid, but the server's status
                # disagrees with the engine-owned truth
                return "stale-row"
        return None

    # ---------------------------------------------------- confirm + repair

    def _settle_sleep(self) -> None:
        deadline = time.monotonic() + self.settle_s
        while self.engine._running and time.monotonic() < deadline:
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))

    def _recheck_and_repair(self, kind, key, reason, ns, name) -> bool:
        """The suspicion gate: re-GET the object and re-read the row
        after the settle window; only a divergence that is STILL there —
        same class — counts and repairs. Returns confirmed?"""
        eng = self.engine
        fresh = eng.client.get(kind, ns, name)
        if fresh is None:
            # object truly gone: divergence iff the row still exists
            if self._row_view(kind, key) is None:
                return False
            confirmed_reason = "ghost-row"
        else:
            confirmed_reason = self._classify(kind, key, fresh)
            if confirmed_reason is None:
                return False
            if confirmed_reason != reason:
                # the divergence changed shape mid-settle: still moving,
                # let the next pass judge it. (A cycle-scan ghost suspect
                # whose object reappeared under a NEW uid re-classifies
                # as ghost-row — equal reasons — and is confirmed here;
                # any other re-classification is an in-flight transient.)
                return False
        self._detected.labels(kind=kind, reason=confirmed_reason).inc()
        logger.warning(
            "drift detected (%s %s): %s; repairing via re-ingest",
            kind, key, confirmed_reason,
        )
        t = time.monotonic()
        if fresh is None:
            md = {"name": name}
            if ns is not None:
                md["namespace"] = ns
            eng._q.put((kind, DELETED, {"metadata": md}, t))
        else:
            # ADDED (not MODIFIED): the stale-rv ingest tier must never
            # drop a repair that legitimately carries a regressed
            # revision (the double-apply/rewind case)
            eng._q.put((kind, ADDED, fresh, t))
        self._repaired.inc()
        return True

    def _account(self, confirmed: list) -> None:
        """Streak bookkeeping, keyed per scan CYCLE (not per pass): on a
        cluster larger than one window a divergent object is re-scanned
        only once per cycle, so pass-keyed streaks would reset — and the
        degraded flag clear — on every intervening healthy window. A
        streak entry survives until its kind completes a full cycle
        after the last confirmation without re-confirming it (its window
        was re-scanned and found clean, or the object is gone)."""
        eng = self.engine
        with self._ae_lock:
            self._passes += 1
            for ent in confirmed:
                kind = ent[0]
                rec = self._streaks.get(ent)
                if rec is None:
                    self._streaks[ent] = [1, self._cycles[kind]]
                else:
                    rec[0] += 1
                    rec[1] = self._cycles[kind]
            # prune entries whose kind's scan wrapped a full cycle past
            # their last confirmation: that cycle re-covered the
            # object's window and did not re-confirm
            self._streaks = {
                ent: rec for ent, rec in self._streaks.items()
                if self._cycles[ent[0]] < rec[1] + 2
            }
            worst = max((r[0] for r in self._streaks.values()), default=0)
            stuck = sum(
                1 for r in self._streaks.values()
                if r[0] >= _DEGRADE_STREAK
            )
            empty = not self._streaks
        if worst >= _DEGRADE_STREAK:
            if eng._degradation.set("drift"):
                logger.error(
                    "engine degraded: %d divergence(s) surviving repair "
                    "for %d+ audit cycles (reason drift)",
                    stuck, _DEGRADE_STREAK,
                )
        elif empty:
            if eng._degradation.clear("drift"):
                logger.info("drift cleared: audit found no divergence")
