"""Crash-durable checkpoints: the per-row scalars a restart cannot relist.

The reference kwok is stateless by design — a controller restart re-lists
and re-adopts the cluster from apiserver state. This engine holds volatile
state the apiserver does NOT carry: the device-resident ``fire_at`` stage
deadline of every armed row (how much of a Stage delay has already
elapsed), the heartbeat wheel's per-row phase (``hb_due``), and the
per-row transition generation (``gen``). A ``kill -9`` + restart without
this module silently resets every in-flight delay to zero.

Three pieces:

- :class:`Checkpointer`: a periodic, atomic-rename JSON checkpoint of the
  irreplaceable scalars. The GATHER (device arrays -> host, pool/meta
  walk) always happens on the thread that owns device state — the tick
  thread / lane coordinator / federated loop — at the configured cadence;
  serialization and file I/O happen on this module's writer thread so the
  tick lane never blocks on disk. Writes go to ``<name>.ckpt.json.tmp``
  then ``os.replace`` — a crash mid-write can never leave a torn file.
- :func:`gather_rows` / :func:`load`: the snapshot row format. Each
  active, device-flushed row records ``(uid, rv, fire-residue,
  hb-residue, gen, phase)``; residues are *remaining* seconds (deadline
  minus engine-now), so the restore semantics are freeze-during-downtime.
- :class:`RestoreSession`: the cold-start (and federation member refill)
  reconcile. The engine re-lists as it always did and lets Stage
  selectors place each row; the session then refines ``fire_at``/
  ``hb_due``/``gen`` for rows whose ``(uid, rv)`` still match their
  checkpoint entry, and drops stale rows PER ROW (an object that changed
  while the engine was down simply re-arms fresh) — never wholesale.

Zero cost when disabled: no ``--checkpoint-dir`` means no Checkpointer
object, no writer thread, no gathers, and a single ``is None`` test on
the tick loop's service gate.
"""

from __future__ import annotations

import json
import logging
import math
import os
import queue
import threading
import time

import numpy as np

logger = logging.getLogger("kwok_tpu.resilience")

VERSION = 1

# Per-kind key <-> JSON string key. Pods join (namespace, name) with "/":
# a k8s namespace can never contain a slash (RFC 1123 label), so the join
# is unambiguous.
_POD_SEP = "/"


def key_str(kind: str, key) -> str:
    if kind == "pods":
        return f"{key[0]}{_POD_SEP}{key[1]}"
    return str(key)


def str_key(kind: str, ks: str):
    if kind == "pods":
        ns, _, name = ks.partition(_POD_SEP)
        return (ns, name)
    return ks


def checkpoint_path(directory: str, name: str) -> str:
    return os.path.join(directory, f"{name}.ckpt.json")


def row_uid(m: dict) -> str:
    """The row's object uid, extracted lazily and cached in the meta dict.

    Dict-path rows carry a parsed object; native-record rows only carry
    the raw watch line — a C-level byte search finds the first
    ``"uid":"`` there without a JSON parse. ownerReferences can in
    principle shadow metadata.uid depending on serialization order, so a
    mis-extracted uid only ever makes the restore MORE conservative (the
    (uid, rv) match fails and the row re-arms fresh)."""
    uid = m.get("uid")
    if uid is None:
        obj = m.get("obj")
        if obj is not None:
            uid = ((obj.get("metadata") or {}).get("uid")) or ""
        else:
            raw = m.get("raw") or b""
            i = raw.find(b'"uid":"')
            if i >= 0:
                j = raw.find(b'"', i + 7)
                uid = raw[i + 7 : j].decode("utf-8", "replace") if j > 0 else ""
            else:
                uid = ""
        m["uid"] = uid
    return uid


def _residue(deadline: float, now: float):
    """Remaining seconds until an engine-time deadline; None for the
    +inf sentinel (no timer armed — JSON has no Infinity)."""
    if not math.isfinite(deadline):
        return None
    return round(max(0.0, deadline - now), 6)


def gather_rows(
    kind: str,
    pool,
    phase_h,
    fire: np.ndarray,
    hb: np.ndarray,
    gen: np.ndarray,
    staged,
    now: float,
    offset: int = 0,
) -> dict:
    """One kind's checkpoint rows: ``{key: [uid, rv, fire_res, hb_res,
    gen, phase]}`` over every pooled row whose device state is current.

    ``staged`` is the set of row indices with a staged-but-unflushed init
    (UpdateBuffer.staged_rows): their device slots still describe a
    previous occupant, so they are skipped — they'll be in the next
    checkpoint, one cadence later. Rows without a recorded ``rv`` carry
    no identity the restore could match and are skipped too. ``offset``
    shifts pool-local indices into a stacked state (lane/member slices).
    """
    ents: dict[str, list] = {}
    for key, idx in list(pool.items()):
        if idx in staged:
            continue
        m = pool.meta[idx]
        if not m:
            continue
        rv = int(m.get("rv") or 0)
        if not rv:
            continue
        di = idx + offset
        ents[key_str(kind, key)] = [
            row_uid(m),
            rv,
            _residue(float(fire[di]), now),
            _residue(float(hb[di]), now),
            int(gen[di]),
            int(phase_h[idx]),
        ]
    return ents


def load(directory: str, name: str) -> "dict | None":
    """Read a checkpoint written by :class:`Checkpointer`. Returns the
    parsed document or None (absent file = cold start; a malformed file —
    impossible from the atomic writer, possible from a hand edit — is a
    logged warning, never a startup crash)."""
    path = checkpoint_path(directory, name)
    try:
        with open(path, "rb") as f:
            doc = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        logger.warning("unreadable checkpoint %s; cold start", path,
                       exc_info=True)
        return None
    if not isinstance(doc, dict) or doc.get("v") != VERSION:
        logger.warning(
            "checkpoint %s has unknown version %r; cold start",
            path, doc.get("v") if isinstance(doc, dict) else None,
        )
        return None
    kinds = doc.get("kinds")
    if not isinstance(kinds, dict):
        logger.warning("checkpoint %s missing kinds; cold start", path)
        return None
    return doc


class Checkpointer:
    """Cadenced checkpoint writer for one engine (or federation member).

    The device-owning loop polls :meth:`due` once per iteration (one
    monotonic compare), gathers a snapshot when due, and :meth:`submit`\\ s
    it; this class serializes + atomically renames on its own writer
    thread. The FINAL checkpoint at shutdown (:meth:`final`) rides the
    same queue so it can never be overwritten by an older periodic
    snapshot still in flight."""

    def __init__(
        self,
        directory: str,
        name: str,
        interval: float,
        telemetry=None,
        degradation=None,
    ) -> None:
        self.directory = directory
        self.name = name
        self.interval = max(0.05, float(interval))
        self.path = checkpoint_path(directory, name)
        self._tmp = self.path + ".tmp"
        self._telemetry = telemetry
        # the engine's Degradation ledger: a writer that cannot reach
        # disk (ENOSPC, read-only remount) flips kwok_degraded{reason=
        # "checkpoint"} while it retries, cleared on the next good write
        self._degradation = degradation
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread: "threading.Thread | None" = None
        self._next = time.monotonic() + self.interval
        self.writes = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        from kwok_tpu.workers import spawn_worker

        os.makedirs(self.directory, exist_ok=True)
        self._thread = spawn_worker(
            self._write_loop, name=f"kwok-ckpt-{self.name}"
        )

    def stop(self) -> None:
        """Drain the queue (any final snapshot included) and join."""
        self._q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -------------------------------------------------------------- cadence

    def due(self) -> bool:
        return time.monotonic() >= self._next

    def submit(self, snapshot: dict) -> None:
        """Queue one gathered snapshot for writing; resets the cadence."""
        self._next = time.monotonic() + self.interval
        self._q.put(snapshot)

    def final(self, snapshot: dict) -> None:
        """Queue the shutdown checkpoint (ordered behind any periodic
        snapshot already queued, so the last write is always the newest
        gather). Falls back to a synchronous write when the writer thread
        is gone (a crash-during-shutdown path)."""
        if self._thread is not None and self._thread.is_alive():
            self._q.put(snapshot)
        else:
            self._write(snapshot)

    # --------------------------------------------------------------- writer

    def _write_loop(self) -> None:
        from kwok_tpu.resilience.policy import CKPT_RETRY

        backoff = None
        snap = None
        while True:
            if snap is None:
                snap = self._q.get()
            if snap is None:
                return
            try:
                self._write(snap)
            except OSError:
                # disk trouble (ENOSPC, EIO, read-only remount): the tmp
                # write failed BEFORE os.replace, so the last good
                # checkpoint on disk is intact by construction. Degrade
                # (kwok_degraded{reason="checkpoint"}; /readyz 503 —
                # this engine's crash durability is gone until the disk
                # heals) and retry under the shared policy — always with
                # the NEWEST snapshot available, because writing a stale
                # one after a fresher gather queued would move the
                # restore target BACKWARD.
                logger.exception("checkpoint write failed (%s)", self.path)
                if self._degradation is not None and self._degradation.set(
                    "checkpoint"
                ):
                    logger.error(
                        "engine degraded: checkpoint writer cannot reach "
                        "disk (%s); retrying under policy", self.path,
                    )
                if backoff is None:
                    backoff = CKPT_RETRY.session()
                snap = self._retry_wait(snap, backoff.next_delay() or 1.0)
                if snap is None:
                    return  # stop sentinel drained mid-retry
                continue
            except Exception:
                # a serialization bug is not a disk outage: one failed
                # write must not end checkpointing; the next cadence
                # retries with fresher data
                logger.exception("checkpoint write failed (%s)", self.path)
                snap = None
                continue
            if backoff is not None:
                backoff = None
                if self._degradation is not None and self._degradation.clear(
                    "checkpoint"
                ):
                    logger.info(
                        "checkpoint writer recovered (%s)", self.path
                    )
            snap = None

    def _retry_wait(self, snap: dict, delay: float) -> "dict | None":
        """Sleep out one write-retry backoff window on the writer thread,
        absorbing anything newer that queues meanwhile: the freshest
        snapshot supersedes the failed one. Returns the snapshot to retry
        (never older than ``snap``) or None when the stop sentinel
        arrived — after one last best-effort write of the freshest
        gather, so a shutdown during a disk outage still tries to leave
        the newest state behind."""
        deadline = time.monotonic() + delay
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return snap
            try:
                nxt = self._q.get(timeout=min(remaining, 0.2))
            except queue.Empty:
                continue
            if nxt is None:
                try:
                    self._write(snap)
                except OSError:
                    logger.error(
                        "final checkpoint write failed during disk "
                        "outage; last good checkpoint (%s) left intact",
                        self.path,
                    )
                return None
            snap = nxt

    def _write(self, snapshot: dict) -> None:
        t0 = time.perf_counter()
        doc = {
            "v": VERSION,
            "name": self.name,
            "wall": time.time(),
            "kinds": snapshot.get("kinds") or {},
        }
        blob = json.dumps(doc, separators=(",", ":")).encode()
        with open(self._tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(self._tmp, self.path)
        self.writes += 1
        dt = time.perf_counter() - t0
        tel = self._telemetry
        if tel is not None:
            armed = idle = 0
            for ents in doc["kinds"].values():
                for e in ents.values():
                    if e[2] is not None:
                        armed += 1
                    else:
                        idle += 1
            tel.ckpt_write_hist.observe(dt)
            tel.ckpt_rows["armed"].set(armed)
            tel.ckpt_rows["idle"].set(idle)


class RestoreSession:
    """Match checkpoint entries against freshly re-listed rows and hand
    back refine batches; consumed per row, dropped per row.

    Single consumer by contract: only the device-owning loop calls
    :meth:`match_kind`. ``gate_ready`` sessions belong to the startup
    reconcile (the engine's /readyz gate finishes them); refill sessions
    (federation member restarts, watch-worker restarts) instead carry a
    TTL — they end when the re-list has had ample time to re-deliver."""

    def __init__(self, kinds: dict, gate_ready: bool, ttl: float = 0.0):
        # parse into {kind: {key_str: entry-list}} defensively: a stale
        # or hand-edited file must degrade to "nothing matches"
        self.kinds: dict[str, dict] = {}
        for kind in ("nodes", "pods"):
            ents = kinds.get(kind)
            self.kinds[kind] = dict(ents) if isinstance(ents, dict) else {}
        self.gate_ready = gate_ready
        self.deadline = (time.monotonic() + ttl) if ttl > 0 else 0.0
        self.matched = 0
        self.stale = 0

    @property
    def remaining(self) -> int:
        return sum(len(v) for v in self.kinds.values())

    def expired(self) -> bool:
        return bool(self.deadline) and time.monotonic() > self.deadline

    def match_kind(
        self, kind: str, pool, staged, now: float, phase_h=None,
        fire=None, offset: int = 0,
    ):
        """Pop every entry whose row is present, device-flushed, ARMED,
        and still the same object ``(uid, rv, phase)``; return its
        refine arrays (idx, fire_at, hb_due, gen) in ENGINE time.
        Entries whose row exists but whose identity moved on are dropped
        as stale; entries whose key is absent — or whose row the kernel
        has not armed yet — stay (the re-list / a managed-ness XUPD may
        not have reached them; :meth:`finish` drops the leftovers).

        ``fire`` is the CURRENT device fire_at array (host copy): an
        entry carrying a delay residue is only consumed once the row's
        own deadline is finite, i.e. the kernel has matched and armed
        its rule. Refining before that point would be undone by the very
        re-arm that follows — the restart_soak gate caught exactly this
        on pods whose managed bit arrives via a later XUPD."""
        ents = self.kinds.get(kind)
        if not ents:
            return (np.empty(0, np.int32),) * 4
        idx_l: list[int] = []
        fire_l: list[float] = []
        hb_l: list[float] = []
        gen_l: list[int] = []
        inf = float("inf")
        for ks, ent in list(ents.items()):
            try:
                uid, rv, fire_res, hb_res, gen, phase = ent
            except (TypeError, ValueError):
                ents.pop(ks)
                self.stale += 1
                continue
            idx = pool.lookup(str_key(kind, ks))
            if idx is None:
                continue  # not re-listed yet; the final pass drops it
            if idx in staged:
                continue  # staged init not flushed/armed yet; next pass
            m = pool.meta[idx] or {}
            if int(m.get("rv") or 0) != int(rv):
                ents.pop(ks)
                self.stale += 1
                continue
            cur_uid = row_uid(m)
            if uid and cur_uid and uid != cur_uid:
                ents.pop(ks)
                self.stale += 1
                continue
            if phase_h is not None and int(phase_h[idx]) != int(phase):
                # same rv but a different lifecycle phase can only mean
                # the row transitioned since the checkpoint (the echo
                # has not landed yet): resuming the OLD delay would
                # re-fire it — drop, let the fresh arm win
                ents.pop(ks)
                self.stale += 1
                continue
            if fire_res is not None and fire is not None and not (
                math.isfinite(float(fire[idx + offset]))
            ):
                continue  # not armed yet (e.g. XUPD pending); next pass
            ents.pop(ks)
            self.matched += 1
            idx_l.append(idx)
            fire_l.append(now + fire_res if fire_res is not None else inf)
            hb_l.append(now + hb_res if hb_res is not None else inf)
            gen_l.append(int(gen))
        if not idx_l:
            return (np.empty(0, np.int32),) * 4
        return (
            np.fromiter(idx_l, np.int32, len(idx_l)),
            np.fromiter(fire_l, np.float32, len(fire_l)),
            np.fromiter(hb_l, np.float32, len(hb_l)),
            np.fromiter(gen_l, np.int32, len(gen_l)),
        )

    def finish(self) -> dict:
        """Close the session: leftovers are objects the re-list did not
        return (deleted while down) — stale by definition, dropped per
        row. Returns the summary for the recovery log line."""
        leftover = self.remaining
        self.stale += leftover
        for ents in self.kinds.values():
            ents.clear()
        return {
            "refined": self.matched,
            "stale": self.stale,
            "unlisted": leftover,
        }
