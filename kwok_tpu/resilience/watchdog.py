"""Supervised worker threads: crash -> account -> restart within budget.

Before this module, a crashed lane drain/emit/router worker was counted
(``kwok_worker_crashes_total``) and then simply *gone* — a dead drain
worker left its lane queue backing up forever while the rest of the
engine looked healthy. The watchdog closes that hole with in-thread
supervision: ``Watchdog.spawn`` runs the worker target inside a
supervision loop on ONE ``spawn_worker`` thread, so a crash (any
``Exception``, or the chaos plane's ``WorkerKilled`` pill) is caught,
accounted (crash counter + ``kwok_worker_restarts_total{thread=}``),
paced by the shared ``RetryPolicy``, and the target simply runs again on
the same thread against the same queues — no thread-handle churn, no
re-registration, the engine's ``stop()`` join logic unchanged.

The restart budget bounds crash loops: more than ``budget`` restarts of
one worker inside ``window`` seconds stops supervision for that worker,
marks the engine degraded (``on_exhausted`` -> ``kwok_degraded{reason=
"worker_restart_budget"}``; ``/readyz`` answers 503), and re-raises the
final exception into ``threading.excepthook`` so test fixtures and crash
accounting still see a genuinely wedged worker.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from kwok_tpu.resilience.faults import WorkerKilled
from kwok_tpu.resilience.policy import RetryPolicy
from kwok_tpu.telemetry.errors import (
    swallowed,
    worker_crashed,
    worker_restarted,
)
from kwok_tpu.workers import spawn_worker

logger = logging.getLogger("kwok_tpu.resilience")

# Restart pacing: near-immediate first restart (the queue is backing up),
# backing off if the worker keeps dying.
RESTART_PACING = RetryPolicy(base=0.02, cap=1.0)


class Watchdog:
    """Supervision for a set of named worker threads."""

    def __init__(
        self,
        budget: int = 5,
        window: float = 30.0,
        on_exhausted=None,
        on_restart=None,
    ):
        self.budget = int(budget)
        self.window = float(window)
        self.on_exhausted = on_exhausted
        # fired (from the restarted worker's thread) after each restart:
        # the engine resyncs its watch streams here, because a crash can
        # eat an in-flight item (the pill lands mid-apply or mid-get) and
        # only a full list+RESYNC provably reconciles what was lost
        self.on_restart = on_restart
        self._wd_lock = threading.Lock()
        # thread name -> monotonic restart stamps inside the window
        self._restarts: dict[str, deque] = {}
        self._log: list[dict] = []  # chaos-artifact surface
        self._closed = False

    # -------------------------------------------------------------- spawn

    def spawn(self, target, *, name: str, args: tuple = ()) -> threading.Thread:
        """Spawn ``target`` under supervision (via workers.spawn_worker,
        so naming/registry/crash accounting are the standard ones)."""
        return spawn_worker(
            self._supervise, name=name, args=(target, name, args)
        )

    def close(self) -> None:
        """Stop restarting: a crash during shutdown ends its worker."""
        self._closed = True

    def charge(self, name: str) -> bool:
        """Account one external restart of ``name`` against the SAME
        budget window in-thread supervision uses; returns whether the
        restart is allowed. The process-lane supervisor
        (engine/proclanes.py) charges lane-process respawns here — a
        crash-looping process degrades exactly like a crash-looping
        thread, and the respawn joins the restart ledger (marked
        ``proc``) so the chaos artifacts see one unified surface for
        thread restarts, SIGKILL respawns, and stall-kill respawns."""
        if self._closed:
            return False
        allowed = self._allow(name, time.monotonic())
        if allowed:
            with self._wd_lock:
                self._log.append({"thread": name, "proc": True})
        return allowed

    # -------------------------------------------------------- supervision

    def _supervise(self, target, name: str, args: tuple) -> None:
        pacing = RESTART_PACING.session()
        t0 = time.monotonic()
        while True:
            try:
                t0 = time.monotonic()
                target(*args)
                return  # clean exit (sentinel consumed / engine stopping)
            except (Exception, WorkerKilled):
                # WorkerKilled named explicitly: the chaos pill is a
                # BaseException precisely so worker loops' per-item
                # ``except Exception`` guards cannot absorb it — only
                # supervision may
                crashed_at = time.monotonic()
                if crashed_at - t0 > self.window:
                    pacing.reset()  # a long healthy run resets the pacing
                if self._closed or not self._allow(name, crashed_at):
                    logger.error(
                        "worker %s exceeded its restart budget "
                        "(%d/%.0fs); giving up",
                        name, self.budget, self.window,
                    )
                    if self.on_exhausted is not None and not self._closed:
                        self.on_exhausted(name)
                    # the final crash is accounted by spawn_worker's own
                    # wrapper (counter + excepthook) as it re-raises
                    raise
                # recovery absorbs its OWN faults: a second chaos pill
                # async-raised while we sleep/log here must not escape
                # supervision — it is the same crash for budget purposes
                # (already charged by _allow above), so just restart
                try:
                    worker_crashed(name)
                    delay = pacing.next_delay() or 0.0
                    logger.warning(
                        "worker %s crashed; restarting in %.3fs",
                        name, delay, exc_info=True,
                    )
                    worker_restarted(name)
                    if delay:
                        time.sleep(delay)
                except (Exception, WorkerKilled):
                    logger.warning(
                        "worker %s: fault landed mid-recovery; "
                        "restarting anyway", name, exc_info=True,
                    )
                # on_restart is the DATA-healing half of the restart (the
                # engine resyncs streams here): a pill absorbed above must
                # not skip it — the first crash's eaten item would stay
                # lost forever — so it gets its own bounded retry that
                # absorbs further pills and tries again
                for _ in range(3):
                    try:
                        if self.on_restart is not None:
                            self.on_restart(name)
                        break
                    except (Exception, WorkerKilled):
                        logger.warning(
                            "worker %s: fault landed in on_restart; "
                            "retrying the resync", name, exc_info=True,
                        )
                else:
                    logger.error(
                        "worker %s: on_restart failed 3 times; worker "
                        "restarts without a stream resync", name,
                    )
                try:
                    with self._wd_lock:
                        self._log.append({
                            "thread": name,
                            "restart_latency_s": round(
                                time.monotonic() - crashed_at, 6
                            ),
                        })
                except (Exception, WorkerKilled):
                    # accounting only; the restart must proceed
                    swallowed("watchdog_restart_log")

    def _allow(self, name: str, now: float) -> bool:
        with self._wd_lock:
            stamps = self._restarts.setdefault(name, deque())
            while stamps and now - stamps[0] > self.window:
                stamps.popleft()
            if len(stamps) >= self.budget:
                return False
            stamps.append(now)
            return True

    # ------------------------------------------------------------- reads

    def restart_log(self) -> list[dict]:
        """Per-restart records (thread + crash->restart latency) for the
        chaos artifact."""
        with self._wd_lock:
            return list(self._log)

    def restarts_total(self) -> int:
        with self._wd_lock:
            return len(self._log)
