"""kwok_tpu.resilience: chaos-hardening substrate for the control loop.

Three pieces (ISSUE 6 tentpole; docs/resilience.md is the operator's
guide):

- ``faults``: a seedable, deterministic fault-injection plane
  (``KWOK_TPU_FAULTS`` / ``EngineConfig.faults``) wrapping the pump,
  the KubeClient transport, and worker threads — zero overhead when
  disabled.
- ``policy``: the shared ``RetryPolicy`` (exponential backoff + full
  jitter + deadline cap) every reconnect loop uses, plus the
  ``Degradation`` ledger behind ``kwok_degraded{reason=}`` and the
  ``/readyz`` 503.
- ``watchdog``: in-thread supervision restarting crashed lane
  router/drain/emit workers within a budgeted window
  (``kwok_worker_restarts_total{thread=}``), degrading the engine when
  the budget runs out.
- ``checkpoint`` (ISSUE 7): crash-durable restarts — the periodic
  atomic-rename checkpoint of device-resident timer state
  (``--checkpoint-dir``), and the cold-start/refill reconcile that
  resumes matching rows' Stage delays after a ``kill -9``.
- ``antientropy`` (ISSUE 10): the continuous convergence oracle — a
  paced background pass diffing budgeted windows of apiserver truth
  against engine rows by ``(uid, rv, phase)``, classifying silent
  divergence and repairing per row via re-ingest
  (``--audit-interval``).
- ``ha`` (ISSUE 12): warm-standby high availability — a lease-based
  leadership plane (``--ha-role``) whose elector renews/acquires the
  apiservers' coordination.k8s.io Lease, fences every outward write on
  still-holding-it (locally and server-side), runs the standby
  observe-only over warm state, and turns the PR 7 checkpoint stream
  into zero-double-fire takeover.
"""

from kwok_tpu.resilience.antientropy import AntiEntropyAuditor
from kwok_tpu.resilience.checkpoint import (
    Checkpointer,
    RestoreSession,
)
from kwok_tpu.resilience.faults import (
    FaultInjected,
    FaultPlane,
    FaultSpec,
    WorkerKilled,
    from_config,
)
from kwok_tpu.resilience.ha import HAPlane
from kwok_tpu.resilience.policy import (
    PATCH_RETRY,
    PUMP_RESEND,
    WATCH_RECONNECT,
    Backoff,
    Degradation,
    RetryPolicy,
)
from kwok_tpu.resilience.watchdog import Watchdog

__all__ = [
    "AntiEntropyAuditor",
    "Backoff",
    "Checkpointer",
    "Degradation",
    "FaultInjected",
    "FaultPlane",
    "FaultSpec",
    "HAPlane",
    "PATCH_RETRY",
    "PUMP_RESEND",
    "RestoreSession",
    "RetryPolicy",
    "WATCH_RECONNECT",
    "Watchdog",
    "WorkerKilled",
    "from_config",
]
