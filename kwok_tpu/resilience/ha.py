"""Warm-standby high availability: lease-fenced failover (ISSUE 12).

PR 7 made one engine crash-*durable* — a SIGKILL'd process cold-restarts
from its checkpoint with zero double-fires. This module turns that into
*availability*: an active/warm-standby engine pair coordinated through a
minimal ``coordination.k8s.io/v1`` Lease both mock apiservers serve
(create / GET / PATCH-renew; the server's clock arbitrates expiry), the
client-go leader-election shape with the optimistic-concurrency Update
replaced by a server-arbitrated PATCH:

- the **primary** renews the lease every ``renew_interval`` and holds a
  local *fence*: a monotonic deadline stamped BEFORE each renew was sent,
  plus the lease duration. The server stamps ``renewTime`` when it
  processes the PATCH — always at-or-after the send stamp — so the fence
  always lapses at-or-before the earliest instant the server could hand
  the lease to someone else. Every outward write is gated on the fence:
  the patch executor through :class:`FencedClient`, the native pump
  through :class:`FencedPump` (and, authoritatively, server-side: both
  writers ride the :data:`FENCE_HEADER` fencing claim, which the
  apiservers validate under the same store lock a takeover PATCH
  serializes through — a paused-and-revived zombie's in-flight bytes die
  there even when they slipped past the local check before the pause).
- the **standby** runs the engine in observe-only mode — watches both
  kinds, ingests, flushes device mirrors, but the transition kernel never
  runs: nothing arms, nothing fires, nothing emits (``engine._ha_hold``).
  It tails the primary's ``<identity>.ckpt.json`` checkpoint stream
  (atomic-rename files are safe to read concurrently) and keeps PATCHing
  the lease with its own identity: 409 Conflict while the primary lives,
  acquisition the moment the lease expires. Takeover = arm a PR 7
  :class:`~kwok_tpu.resilience.checkpoint.RestoreSession` from the dead
  primary's freshest checkpoint, open the gate, flip /readyz — the
  re-list is already done, so failover beats a cold restart.
- a **deposed leader** (renew answered 409: the lease was stolen while it
  was paused/partitioned) closes its fence permanently, re-enters hold
  mode and parks degraded (``kwok_degraded{reason="ha_lost_lease"}``);
  rejoining the pair takes a process restart, never a split brain.

Zero cost when disabled: ``from_config`` returns None for an empty role —
no elector thread, no client/pump wrapping, no fence check anywhere on
the hot path (the single ``_ha_hold`` attribute test per tick dispatch is
the same class of cost as the checkpoint service gate).

Lock: ``_ha_lock`` guards the role state machine and the tailed peer
checkpoint; it is a leaf (kwoklint level 84, docs/static-analysis.md) —
nothing is ever acquired under it.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time

import numpy as np

logger = logging.getLogger("kwok_tpu.resilience")

#: mutating requests carry this header naming the lease the writer
#: believes it holds ("<namespace>/<name>/<holderIdentity>"); both mock
#: apiservers reject the write 409 when that lease is not currently held
#: by that identity (mockserver.FENCING_HEADER / apiserver.cc mirror).
FENCE_HEADER = "X-Kwok-Lease-Holder"

_ROLES = ("leader", "standby", "lost")

_HELP_ROLE = (
    "Current HA role of this engine (1 on exactly one of "
    "role=leader|standby|lost; absent families mean HA is disabled)"
)
_HELP_TRANSITIONS = (
    "Lease acquisitions performed by THIS engine (its standby->leader "
    "edges; the lease object's own leaseTransitions counts cluster-wide "
    "handovers)"
)
_HELP_TAKEOVER = (
    "Seconds from the last moment the previous holder was observed "
    "alive (the final 409-denied acquire attempt) to this engine "
    "serving after takeover (gate open, /readyz 200); 0 for an "
    "uncontested first acquisition"
)
_HELP_FENCED = (
    "Outward writes dropped by the lease fence (patch-executor jobs and "
    "native pump requests attempted while not holding the lease: the "
    "observe-only standby's repair renders, a deposed or expired "
    "leader's in-flight emits)"
)


def default_identity() -> str:
    """client-go's id shape: hostname + a per-process discriminator."""
    return f"{socket.gethostname()}-{os.getpid()}"


class _Fence:
    """The local fencing token: a monotonic deadline below which this
    process may still consider itself the lease holder. Reads and writes
    are single float-attribute operations (GIL-atomic) — the fence check
    on the emit path is one clock read and one compare."""

    def __init__(self) -> None:
        self._deadline = 0.0

    def open_until(self, deadline: float) -> None:
        self._deadline = deadline

    def close(self) -> None:
        self._deadline = 0.0

    def holding(self) -> bool:
        return time.monotonic() < self._deadline


class FencedClient:
    """KubeClient wrapper gating the OUTWARD WRITE verbs on the fence.

    A fenced write is dropped (counted, warn-once) and reports the same
    shape a deleted-object no-op would: ``None`` from the patch verbs,
    silent return from delete — the executor's ``_safe`` treats both as
    settled, so a fenced engine never burns retries on writes that must
    not land. Reads (list/watch/get) and the lease verbs themselves pass
    through untouched."""

    def __init__(self, plane: "HAPlane", inner):
        self.plane = plane
        self.inner = inner

    def patch_status(self, kind, namespace, name, patch):
        if self.plane.fence.holding():
            return self.inner.patch_status(kind, namespace, name, patch)
        self.plane.note_fenced()
        return None

    def patch_meta(self, kind, namespace, name, patch):
        if self.plane.fence.holding():
            return self.inner.patch_meta(kind, namespace, name, patch)
        self.plane.note_fenced()
        return None

    def delete(self, kind, namespace, name, **kw):
        if self.plane.fence.holding():
            return self.inner.delete(kind, namespace, name, **kw)
        self.plane.note_fenced()
        return None

    def __getattr__(self, name):
        return getattr(self.inner, name)


class FencedPump:
    """Native pump wrapper: a batch sent while not holding the lease is
    answered with all-404 statuses — the engine's ack loop treats 404 as
    "object deleted server-side, no-op" (no per-object fallback, no
    resend, no pump degradation), which is exactly a dropped write."""

    def __init__(self, plane: "HAPlane", inner):
        self.plane = plane
        self.inner = inner

    def send(self, requests):
        if self.plane.fence.holding():
            return self.inner.send(requests)
        n = len(requests)
        self.plane.note_fenced(n)
        return np.full(n, 404, dtype=np.int32)

    def close(self) -> None:
        self.inner.close()

    def __getattr__(self, name):
        return getattr(self.inner, name)


class HAPlane:
    """The leadership plane of one engine: elector thread + fence +
    peer-checkpoint tail. Built by ``ClusterEngine.__init__`` (via
    :func:`from_config`), bound to the engine in ``start()``, run as the
    watchdog-supervised ``kwok-ha`` worker."""

    def __init__(
        self,
        role: str,
        identity: str = "",
        lease_name: str = "kwok-tpu-engine",
        lease_namespace: str = "kube-system",
        duration: float = 2.0,
        renew_interval: float = 0.0,
    ) -> None:
        if role not in ("primary", "standby"):
            raise ValueError(f"ha_role must be primary|standby, got {role!r}")
        self.role = role
        self.identity = identity or default_identity()
        self.lease_name = lease_name
        self.lease_namespace = lease_namespace
        # the wire carries whole seconds (k8s leaseDurationSeconds), and
        # the LOCAL fence must never outlive the server's grant — so the
        # working duration is quantized to the exact integer the wire
        # carries (a fractional configured value anchoring the fence
        # while the server granted the rounded one would let a
        # partitioned leader keep writing after a takeover window opens)
        self.duration = float(max(1, round(float(duration))))
        self.renew_interval = (
            float(renew_interval) if renew_interval and renew_interval > 0
            else self.duration / 3.0
        )
        # the standby's acquire-poll cadence bounds takeover detection
        # latency on top of the lease duration; keep it well under the
        # RTO gate's one-tick-quantum allowance
        self.acquire_interval = max(
            0.05, min(self.renew_interval, self.duration / 20.0)
        )
        self.fence = _Fence()
        # role state machine + tailed peer checkpoint; leaf lock,
        # kwoklint level 84 (docs/static-analysis.md)
        self._ha_lock = threading.Lock()
        self.leading = False
        self.lost = False
        self.engine = None
        self._stop = False
        self._next_renew = 0.0
        self._last_denied = 0.0   # monotonic of the last 409-denied grab
        self._lease_seen = False  # a GET has observed the lease existing
        self._lease_get_at = 0.0  # monotonic of the last discovery GET
        self._peer_holder = ""
        self._peer_doc = None     # freshest parsed peer checkpoint
        self._peer_read_at = 0.0
        self.fenced_writes = 0
        self._fenced_logged = False
        self._role_fam = None
        self._transitions_c = None
        self._takeover_g = None
        self._fenced_c = None

    # ------------------------------------------------------------- wrapping

    def wrap_client(self, client):
        return FencedClient(self, client)

    def wrap_pump(self, pump):
        return FencedPump(self, pump)

    def fence_header_line(self) -> str:
        """The fencing claim as a raw HTTP header line (native pump
        ``header_extra``)."""
        return f"{FENCE_HEADER}: {self.fence_header_value()}\r\n"

    def fence_header_value(self) -> str:
        return f"{self.lease_namespace}/{self.lease_name}/{self.identity}"

    def note_fenced(self, n: int = 1) -> None:
        # executor threads and several lane pump workers can hit the
        # fence concurrently: the tally moves under _ha_lock (a legal
        # 80 -> 84 descent from a pump group lock; the registry child
        # below is touched after release, per the leaf-lock contract)
        with self._ha_lock:
            self.fenced_writes += n
            first = not self._fenced_logged
            self._fenced_logged = True
        c = self._fenced_c
        if c is not None:
            c.inc(n)
        if first:
            logger.warning(
                "HA fence dropped an outward write (not holding lease "
                "%s/%s as %s); further drops are counted silently "
                "(kwok_ha_fenced_writes_total)",
                self.lease_namespace, self.lease_name, self.identity,
            )

    # ---------------------------------------------------------------- wiring

    def bind(self, engine) -> None:
        """Attach to the engine: register the kwok_ha_* families on its
        registry, hold the serve gate (degradation reason ``ha_standby``
        keeps /readyz 503 until leadership), and plant the fencing claim
        on the underlying HTTP client's extra headers so every unary
        write is server-side fenced too."""
        self.engine = engine
        reg = engine.telemetry.registry
        self._role_fam = reg.gauge("kwok_ha_role", _HELP_ROLE, ("role",))
        self._transitions_c = reg.counter(
            "kwok_lease_transitions_total", _HELP_TRANSITIONS
        ).labels()
        self._takeover_g = reg.gauge(
            "kwok_ha_takeover_seconds", _HELP_TAKEOVER
        ).labels()
        self._fenced_c = reg.counter(
            "kwok_ha_fenced_writes_total", _HELP_FENCED
        ).labels()
        self._set_role_gauge("standby")
        engine._degradation.set("ha_standby")
        inner = engine.client
        for _ in range(8):
            if inner is None or hasattr(inner, "extra_headers"):
                break
            inner = getattr(inner, "inner", None)
        if inner is not None and hasattr(inner, "extra_headers"):
            inner.extra_headers[FENCE_HEADER] = self.fence_header_value()

    def _set_role_gauge(self, role: str) -> None:
        fam = self._role_fam
        if fam is None:
            return
        for r in _ROLES:
            fam.labels(role=r).set(1 if r == role else 0)

    def stop(self) -> None:
        self._stop = True

    # ------------------------------------------------------------ lease wire

    def _spec(self) -> dict:
        return {
            "holderIdentity": self.identity,
            # exact: self.duration is quantized to this integer at
            # construction, so fence arithmetic and wire agree
            "leaseDurationSeconds": int(self.duration),
        }

    def _lease(self, verb: str):
        """One lease operation -> (status_code, parsed doc | None).
        Transport failures raise (callers back off). Works against both
        the HTTP client (dict answers) and the in-process FakeKube
        (bytes answers)."""
        c = self.engine.client
        ns, name = self.lease_namespace, self.lease_name
        if verb == "GET":
            code, doc = c.lease_get(ns, name)
        elif verb == "POST":
            code, doc = c.lease_create(ns, name, self._spec())
        else:
            code, doc = c.lease_renew(ns, name, self._spec())
        if isinstance(doc, (bytes, bytearray, memoryview)):
            import json

            try:
                doc = json.loads(bytes(doc) or b"null")
            except ValueError:
                doc = None
        return code, doc

    # --------------------------------------------------------------- elector

    def run(self) -> None:
        """The elector loop (worker ``kwok-ha``, watchdog-supervised; a
        crash restarts it in place — the fence deadline survives on this
        object, so a mid-crash window can only be MORE conservative).

        Deliberately keyed on ``self._stop`` alone, NOT the engine's
        ``_running``: a gracefully-stopping leader keeps RENEWING while
        the engine drains its in-flight emits — otherwise the fence
        lapses mid-drain (lease TTL << drain deadline) and the tail
        writes are silently dropped, unrecoverable for a solo primary
        (a paired standby would re-fire them, a solo engine has nobody
        to). ``ClusterEngine.stop`` stops this plane only after the
        executor drained; the lease then expires naturally and a
        standby takes over."""
        while not self._stop:
            if self.lost:
                # deposed: permanently fenced and parked; rejoining the
                # pair takes a process restart (never a split brain)
                time.sleep(0.2)
                continue
            try:
                if self.leading:
                    self._renew_cycle()
                else:
                    self._attempt_cycle()
            except Exception:
                # transport trouble reaching the lease: the fence lapses
                # by itself at its deadline (writes stop — the safe
                # direction); keep trying on a short cadence, a renew
                # that lands before anyone stole the lease re-opens it
                logger.warning(
                    "lease %s transport failure; retrying",
                    "renew" if self.leading else "acquire", exc_info=True,
                )
                self._sleep(0.1)

    def _sleep(self, seconds: float) -> None:
        deadline = time.monotonic() + seconds
        while not self._stop:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            time.sleep(min(remaining, 0.05))

    def _renew_cycle(self) -> None:
        while not self._stop and time.monotonic() < self._next_renew:
            time.sleep(
                min(0.05, max(0.0, self._next_renew - time.monotonic()))
            )
        if self._stop:
            return
        t0 = time.monotonic()
        code, doc = self._lease("PATCH")
        if code == 200:
            # fence anchored at the SEND stamp: the server's renewTime is
            # at-or-after it, so local expiry precedes server expiry
            self.fence.open_until(t0 + self.duration)
            self._next_renew = t0 + self.renew_interval
            return
        if code == 409:
            self._lose("lease stolen while renewing")
            return
        if code == 404:
            # the dialect has no lease delete, so this is a fresh store
            # (e.g. the apiserver restarted empty): re-create
            code2, _doc2 = self._lease("POST")
            if code2 == 201:
                self.fence.open_until(t0 + self.duration)
                self._next_renew = t0 + self.renew_interval
                return
            self._lose(f"lease vanished and re-create answered {code2}")
            return
        logger.warning("lease renew answered %s; retrying", code)
        self._sleep(0.1)

    def _attempt_cycle(self) -> None:
        # the discovery GET feeds holder identification + the checkpoint
        # tail, both of which only need the renew cadence — pacing it
        # keeps the standby's steady-state load at one acquire PATCH per
        # poll instead of doubling it. While the lease has never been
        # seen (startup, or a fresh store) the GET stays on the fast
        # poll: that path decides whether a primary may CREATE.
        if (
            not self._lease_seen
            or time.monotonic() - self._lease_get_at >= self.renew_interval
        ):
            code, doc = self._lease("GET")
            self._lease_get_at = time.monotonic()
            if code == 404:
                self._lease_seen = False
                if self.role == "primary":
                    # first acquisition: create IS the claim
                    t0 = time.monotonic()
                    code2, _doc2 = self._lease("POST")
                    if code2 == 201:
                        self._become_leader(t0, prev_holder="")
                        return
                # a standby never self-elects onto a lease that has
                # never existed: it only takes over from a once-alive
                # primary
                self._sleep(self.acquire_interval)
                return
            self._lease_seen = True
            holder = ""
            if isinstance(doc, dict):
                holder = (
                    (doc.get("spec") or {}).get("holderIdentity") or ""
                )
            if holder and holder != self.identity:
                self._tail_peer(holder)
        t0 = time.monotonic()
        code2, _doc2 = self._lease("PATCH")
        if code2 == 200:
            # the previous holder is the last one discovery observed; a
            # holder that changed hands inside one renew window tails a
            # slightly older checkpoint, which the (uid, rv, phase)
            # match degrades to fresh arms — conservative, never wrong
            ph = self._peer_holder
            self._become_leader(
                t0, prev_holder=ph if ph != self.identity else ""
            )
            return
        if code2 == 409:
            self._last_denied = time.monotonic()
        elif code2 == 404:
            self._lease_seen = False  # store reset between polls
        self._sleep(self.acquire_interval)

    # ------------------------------------------------------------- takeover

    def _tail_peer(self, holder: str) -> None:
        """Keep the freshest parse of the current holder's checkpoint
        (atomic-rename files are safe to read concurrently); paced to the
        renew cadence so a fast acquire poll doesn't hammer the disk."""
        e = self.engine
        if not e._ckpt_dir:
            return
        now = time.monotonic()
        if (
            holder == self._peer_holder
            and now - self._peer_read_at < self.renew_interval
        ):
            return
        from kwok_tpu.resilience import checkpoint as ckpt_mod

        doc = ckpt_mod.load(e._ckpt_dir, holder)
        with self._ha_lock:
            self._peer_holder = holder
            self._peer_read_at = now
            if doc is not None:
                self._peer_doc = doc

    def _become_leader(self, t0: float, prev_holder: str) -> None:
        with self._ha_lock:
            self.leading = True
        self.fence.open_until(t0 + self.duration)
        self._next_renew = t0 + self.renew_interval
        if self._transitions_c is not None:
            self._transitions_c.inc()
        takeover = (
            time.monotonic() - self._last_denied if self._last_denied
            else 0.0
        )
        self._open_gate(prev_holder)
        if self._takeover_g is not None:
            self._takeover_g.set(takeover)
        self._set_role_gauge("leader")
        logger.warning(
            "HA: %s acquired lease %s/%s%s; serving (takeover %.3fs)",
            self.identity, self.lease_namespace, self.lease_name,
            f" from {prev_holder}" if prev_holder else "", takeover,
        )

    def _open_gate(self, prev_holder: str) -> None:
        """Standby -> leader: arm the PR 7 reconcile from the dead
        primary's freshest checkpoint (rows whose (uid, rv, phase) still
        match resume their delay residues; everything else fresh-arms
        from the already-warm re-list) and open the tick gate."""
        e = self.engine
        if prev_holder and e._ckpt is not None:
            from kwok_tpu.resilience import checkpoint as ckpt_mod

            doc = ckpt_mod.load(e._ckpt_dir, prev_holder)
            if doc is None:
                with self._ha_lock:
                    doc = (
                        self._peer_doc
                        if self._peer_holder == prev_holder else None
                    )
            if doc is not None:
                session = ckpt_mod.RestoreSession(
                    doc.get("kinds") or {}, gate_ready=False, ttl=30.0
                )
                with e._ckpt_lock:
                    e._restore = session
                logger.info(
                    "HA takeover: %d checkpointed rows from %s to "
                    "reconcile against warm state",
                    session.remaining, prev_holder,
                )
        e._ha_hold = False
        e._idle_wake = 0.0  # wake the (possibly idle) device loop now
        # a QUIET cluster's tick loop may be deep in its idle sleep with
        # the old wake: the sentinel ends the drain window promptly (the
        # single-lane loop clamps its deadline on it; the lane
        # coordinator re-reads _idle_wake every poll slice)
        e._q.put(None)
        e._degradation.clear("ha_standby")
        # flight-recorder dump on the role edge (the set() edge hook only
        # fires on degradations; a takeover is the OTHER edge worth a
        # post-mortem of the requests that led into it)
        try:
            e._flight_dump_on_degrade("ha_takeover")
        except Exception:
            from kwok_tpu.telemetry.errors import swallowed

            swallowed("ha.takeover_flight_dump")

    def _lose(self, reason: str) -> None:
        with self._ha_lock:
            self.leading = False
            self.lost = True
        self.fence.close()
        e = self.engine
        e._ha_hold = True  # stop arming/firing; observe-only again
        self._set_role_gauge("lost")
        if e._degradation.set("ha_lost_lease"):
            logger.error(
                "HA: %s lost lease %s/%s (%s); engine fenced and parked "
                "— restart the process to rejoin the pair",
                self.identity, self.lease_namespace, self.lease_name,
                reason,
            )


def from_config(config) -> "HAPlane | None":
    """Build the HA plane from an EngineConfig, or None when HA is off
    (``ha_role`` empty — the zero-cost default). ``KWOK_HA_ROLE`` etc.
    reach the CLI through the generic env-override pass over
    KwokConfigurationOptions, not through this module."""
    role = (getattr(config, "ha_role", "") or "").strip()
    if not role or role == "off":
        return None
    return HAPlane(
        role,
        identity=(getattr(config, "ha_identity", "") or "").strip(),
        lease_name=getattr(config, "lease_name", "") or "kwok-tpu-engine",
        lease_namespace=(
            getattr(config, "lease_namespace", "") or "kube-system"
        ),
        duration=getattr(config, "lease_duration", 2.0) or 2.0,
        renew_interval=getattr(config, "lease_renew_interval", 0.0) or 0.0,
    )
