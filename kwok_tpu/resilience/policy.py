"""Shared retry/backoff policy + the engine's degraded-mode surface.

One backoff implementation for every reconnect loop in the tree — the
watch re-watch loop (engine.py), the pump whole-frame resend
(``_pump_send``), patch-job transport retries, and the watchdog's
restart pacing — replacing the ad-hoc ``time.sleep(5)`` constants that
used to live at each site. The shape is client-go's wait.Backoff with
full jitter (AWS-style): attempt ``n`` sleeps ``uniform(0, min(cap,
base * factor**n))``, optionally bounded by a wall-clock deadline.

Degradation is the graceful-degradation ledger: named reasons
(``lane2_queue``, ``worker_restart_budget``, ``pump``) raise the
``kwok_degraded{reason=}`` gauge on the engine's registry and flip the
engine's ``degraded`` property, which ``/readyz`` reflects with a 503 —
load balancers and rigs stop sending work to an engine that is shedding
instead of keeping up. Reasons clear when the condition heals.
"""

from __future__ import annotations

import random
import threading
import time


class RetryPolicy:
    """Immutable backoff shape; ``session()`` mints independent attempt
    state, so one policy object can serve many concurrent loops."""

    def __init__(
        self,
        base: float = 0.5,
        cap: float = 5.0,
        factor: float = 2.0,
        deadline: "float | None" = None,
        jitter: bool = True,
        rng: "random.Random | None" = None,
    ):
        if base <= 0 or cap < base or factor < 1.0:
            raise ValueError("invalid retry policy shape")
        self.base = float(base)
        self.cap = float(cap)
        self.factor = float(factor)
        self.deadline = deadline
        self.jitter = bool(jitter)
        self._rng = rng or random

    def session(self) -> "Backoff":
        return Backoff(self)


class Backoff:
    """Mutable attempt state for one retry loop. Single-threaded by
    contract (each loop owns its session), so no lock."""

    def __init__(self, policy: RetryPolicy):
        self.policy = policy
        self.attempt = 0
        self._started = time.monotonic()

    def reset(self) -> None:
        """A success: the next failure backs off from scratch."""
        self.attempt = 0
        self._started = time.monotonic()

    def next_delay(self) -> "float | None":
        """The next sleep, or None when the policy deadline has passed
        (callers give up, shed, or escalate)."""
        p = self.policy
        if p.deadline is not None and (
            time.monotonic() - self._started >= p.deadline
        ):
            return None
        ceiling = min(p.cap, p.base * (p.factor ** self.attempt))
        self.attempt += 1
        if p.jitter:
            return p._rng.uniform(0, ceiling)
        return ceiling

    def sleep(self, delay: float, should_stop=None) -> None:
        """Sleep ``delay`` seconds in short slices so a stopping engine
        is never blocked behind a full backoff window."""
        deadline = time.monotonic() + delay
        while True:
            if should_stop is not None and should_stop():
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            time.sleep(min(remaining, 0.1))


# The watch re-watch loop's shape: first retry well under a second (a
# one-off stream hiccup must not idle the ingest edge for 5s the way the
# old constant did), converging to the reference's 5s ceiling under a
# persistent outage (node_controller.go:241-254 semantics).
WATCH_RECONNECT = RetryPolicy(base=0.2, cap=5.0)

# Pump whole-frame resend: the C++ layer re-dials on the next send, so
# retries are cheap; bound hard so a downed apiserver degrades to
# shedding instead of wedging executor workers.
PUMP_RESEND = RetryPolicy(base=0.05, cap=0.5, deadline=5.0)

# Patch-job transport retries on the executor (connection-ish errors
# only): enough attempts to ride out an apiserver restart window.
PATCH_RETRY = RetryPolicy(base=0.1, cap=1.0, deadline=8.0)

# Checkpoint writer disk retries (ENOSPC / read-only remounts): no
# deadline — a degraded-but-retrying writer beats silently losing crash
# durability, and every retry uses the newest queued snapshot.
CKPT_RETRY = RetryPolicy(base=0.2, cap=5.0)

_DEGRADED_HELP = (
    "Degraded-mode reasons currently active (1 = degraded): queue "
    "shedding, exhausted worker restart budgets, a downed pump; "
    "/readyz answers 503 while any reason is set"
)


class Degradation:
    """Per-engine degraded-mode ledger over the engine's own registry
    (a process-global ledger would cross-contaminate the multi-engine
    test and federation topologies)."""

    def __init__(self, registry, on_set=None):
        self._fam = registry.gauge(
            "kwok_degraded", _DEGRADED_HELP, ("reason",)
        )
        self._deg_lock = threading.Lock()
        self._reasons: set[str] = set()
        # edge hook: called with the reason on every FRESH set, outside
        # the ledger lock (the engine hangs its flight-recorder
        # post-mortem grab here — best-effort, never raising back into
        # the degrading code path)
        self._on_set = on_set

    def set(self, reason: str) -> bool:
        """Mark a reason active; returns True when newly set (callers
        log/trace on the edge, not on every recurrence)."""
        with self._deg_lock:
            fresh = reason not in self._reasons
            self._reasons.add(reason)
        # registry child access is a leaf; never under our lock
        self._fam.labels(reason=reason).set(1)
        if fresh and self._on_set is not None:
            try:
                self._on_set(reason)
            except Exception:
                from kwok_tpu.telemetry.errors import swallowed

                # a failing post-mortem hook must never break the
                # degradation transition it is documenting
                swallowed("policy.degradation_on_set")
        return fresh

    def clear(self, reason: str) -> bool:
        with self._deg_lock:
            was = reason in self._reasons
            self._reasons.discard(reason)
        if was:
            self._fam.labels(reason=reason).set(0)
        return was

    @property
    def active(self) -> bool:
        return bool(self._reasons)

    @property
    def reasons(self) -> tuple:
        with self._deg_lock:
            return tuple(sorted(self._reasons))
