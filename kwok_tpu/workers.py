"""Central daemon-thread spawning for the engine's worker topology.

Every ``threading.Thread(daemon=True)`` in ``engine/``, ``kwok/server.py``
and the profiling sampler goes through :func:`spawn_worker`: one place
that names threads (the trace viewer and the sampling profiler key
per-thread attribution on these names), keeps a live registry, and
accounts crashes — an uncaught exception is logged with the thread's name
and bumped into ``kwok_worker_crashes_total{thread=...}`` *before being
re-raised into* ``threading.excepthook``. Wrapping the target (instead of
replacing the process hook) composes with test fixtures that install
their own ``threading.excepthook`` to fail tests on escaped exceptions:
they still see every crash, in addition to the log line and the counter.
"""

from __future__ import annotations

import logging
import threading
import weakref

from kwok_tpu.telemetry.errors import worker_crashed

logger = logging.getLogger("kwok_tpu.workers")

# name -> Thread, entries vanish when the thread object is collected
_live: "weakref.WeakValueDictionary[str, threading.Thread]" = (
    weakref.WeakValueDictionary()
)


def spawn_worker(
    target,
    *,
    name: str,
    args: tuple = (),
    kwargs: "dict | None" = None,
    daemon: bool = True,
    start: bool = True,
) -> threading.Thread:
    """Create (and by default start) a named daemon worker thread with
    crash accounting. Returns the Thread."""

    def run() -> None:
        try:
            target(*args, **(kwargs or {}))
        except BaseException:
            worker_crashed(name)
            logger.error("worker thread %s crashed", name, exc_info=True)
            raise  # still reaches threading.excepthook (tests fail on it)

    t = threading.Thread(target=run, name=name, daemon=daemon)
    _live[name] = t
    if start:
        t.start()
    return t


def live_workers() -> dict[str, threading.Thread]:
    """Snapshot of spawned workers still referenced, by name (diagnostic
    surface for the trace viewer and tests)."""
    return {n: t for n, t in _live.items() if t.is_alive()}
