"""Hash-partitioned host lanes: the sharded drain+emit pipeline.

The round-5 cost model attributed 31.9 µs/pod to ``engine_serial_drain_emit``
— ONE tick thread doing all of drain, apply, wire-consume, and emit — and
predicted a hard ceiling of ~31k pods/s at any core count. This module
removes that wall the way the reference KWOK scales (goroutine fan-out per
controller), but key-partitioned so per-object ordering survives:

  watch threads ──> ingest queue ──> router (parse + hash by key)
                                       │
                       ┌───────────────┼──────────────┐
                       ▼               ▼              ▼
                    lane 0          lane 1   ...   lane N-1
                 drain worker    drain worker     drain worker
                 staged buffer   staged buffer    staged buffer
                       └───────────────┼──────────────┘
                                       ▼
                tick thread: flush per-lane buffers into ONE stacked
                device state, dispatch the fused kernel, slice the wire
                per lane (ops/tick.lane_views) and hand each slice to
                       ┌───────────────┼──────────────┐
                       ▼               ▼              ▼
                  emit worker     emit worker     emit worker
                 (own pump conn   (own pump conn  (own pump conn
                  group)           group)          group)

Ordering: a key always maps to the same lane (``rowpool.shard_of``), lane
queues are FIFO, and the tick thread hands wire slices to lanes in consume
order — so per-object patch order is exactly the single-lane engine's (the
oracle in tests/test_lanes.py proves it). Cross-shard state is shared with
striped/narrow locking: the IP pool and release logs ride the engine's
``_alloc_lock``-adjacent discipline (release bookkeeping is mutated under
the lane's ``stage_lock``), ``node_has``/``pods_by_node`` are shared
structures whose single-op mutations are GIL-atomic, and a node's
managed-ness flip reaches OTHER lanes' pods as routed ``XUPD`` items
through their own queues (no cross-lane lock acquisition, no deadlock).

Each lane is implemented as a full ``ClusterEngine`` minus its threads —
exactly how ``FederatedEngine`` hosts members — so the per-event ingest
and emit code paths run unchanged; only the plumbing around them is new.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import queue
import threading
import time
from collections import deque

import numpy as np

from kwok_tpu import profiling
from kwok_tpu.edge.render import now_rfc3339
from kwok_tpu.telemetry.errors import swallowed
from kwok_tpu.workers import spawn_worker
from kwok_tpu.engine.engine import ClusterEngine
from kwok_tpu.engine.rowpool import shard_of
from kwok_tpu.ops.state import RowState, new_row_state
from kwok_tpu.ops.updates import UpdateBuffer
from kwok_tpu.ops.tick import (
    REBASE_AFTER,
    lane_views,
    prefetch,
    rebase_times,
    to_host,
    unpack_wire,
)

logger = logging.getLogger("kwok_tpu.lanes")

_KINDS = ("nodes", "pods")

# Per-lane row-budget floor: tiny lanes regrow (host copy + re-jit at the
# new stacked shape) constantly under any real load. Tests shrink this to
# exercise the mid-run regrow path without six-digit event streams.
_MIN_LANE_ROWS = 1024

# Minimum seconds between shed-clear stream resyncs (drain_loop): bounds
# the full-LIST rate when a resync's own re-list burst re-trips shedding.
_SHED_RESYNC_MIN_S = 5.0


@dataclasses.dataclass
class _LanePending:
    """A dispatched-but-unconsumed stacked tick."""

    wire: object  # device array; self-contained (pack_rows wire)
    r: int  # rows per lane AT DISPATCH (regrow may change it)
    cap: int  # stacked capacity at dispatch
    seqs: list  # per-lane release seq at dispatch (stale-mask filter)
    now: float  # engine time of the dispatch
    mono: float  # monotonic clock at dispatch (idle-wake anchor)
    host_s: float  # host seconds spent in the dispatch half


class _LaneEngine(ClusterEngine):
    """A ClusterEngine serving as ONE lane: no threads of its own, shared
    cross-lane topology, and node managed-ness flips routed to sibling
    lanes instead of applied against the (lane-local) pod pool."""

    _lane_set: "LaneSet | None" = None
    _lane_index = 0

    def _update_pods_on_node(self, node_name: str) -> None:
        ls = self._lane_set
        if ls is None:  # construction-time call paths
            super()._update_pods_on_node(node_name)
            return
        # pods on this node live in OTHER lanes' pools: route one XUPD
        # batch per owning lane through its own queue (FIFO per key keeps
        # the update ordered against the pod's own events)
        ls.route_pod_updates(node_name)

    def _mark_resync(self, kind: str, lane: int = 0) -> None:
        # the startup catch-up gate lives on the PARENT: RESYNC markers
        # broadcast to every lane, and the kind only counts once all
        # lanes processed theirs (ClusterEngine._mark_resync)
        ls = self._lane_set
        if ls is None:
            super()._mark_resync(kind, lane)
            return
        ls.parent._mark_resync(kind, self._lane_index)

    def _integrity_resync(self, kind: str) -> None:
        # corrupt input detected while THIS lane applied a routed record:
        # the watch handles (and the resync bookkeeping the reconnect
        # reads) live on the parent, so the quarantine's re-list request
        # must land there
        ls = self._lane_set
        if ls is None:
            super()._integrity_resync(kind)
            return
        ls.parent._integrity_resync(kind)


class ShardLane:
    """One hash-partition of the host pipeline: ingest queue + drain
    worker + staged-row buffers + emit worker + pump connection group."""

    def __init__(self, lane_set: "LaneSet", index: int, capacity: int):
        parent = lane_set.parent
        self.lane_set = lane_set
        self.index = index
        cfg = dataclasses.replace(
            parent.config,
            drain_shards=1,  # lanes never recurse
            use_mesh=False,  # the coordinator owns device placement
            initial_capacity=capacity,
            profile_dir="",
            trace_dump="",  # one dump, owned by the parent
            faults="off",  # ONE fault plane, the parent's (shared below)
            checkpoint_dir="off",  # ONE checkpoint, the parent's stacked
            audit_interval=-1.0,  # ONE auditor, the parent's (env-proof)
            ha_role="",  # ONE lease plane + fence, the parent's (below)
        )
        e = _LaneEngine(parent.client, cfg, telemetry=parent.telemetry)
        e._lane_set = lane_set
        e._lane_index = index
        # the parent's fault plane and degraded-mode ledger are THE
        # engine-wide instances: lane pumps draw from the same seeded
        # decision streams, and a lane marking "pump" down flips the
        # parent's /readyz — not a private ledger nobody reads
        e._faults = parent._faults
        e._degradation = parent._degradation
        # the parent's HA plane fences THIS lane's pump group too (the
        # client is the parent's, already fence-wrapped); lane engines
        # never dispatch, so their own _ha_hold stays False and inert
        e._ha = parent._ha
        # ONE compiled emit-template table per engine: the lanes' rule
        # set is the parent's, so their phase->template mapping is too —
        # sharing keeps a single ctypes-pinned copy for every emit
        # worker (read-only after construction)
        e._emit_tpl = parent._emit_tpl
        e._emit_cols = parent._emit_cols
        # shared cross-lane state: one IP pool / allocation lock (striped
        # enough — held only for bookkeeping, never across provider
        # calls), one topology view, one clock
        e.ippool = parent.ippool
        e._alloc_lock = parent._alloc_lock
        e.node_has = parent.node_has
        e.pods_by_node = parent.pods_by_node
        e._epoch = parent._epoch
        e.start_time = parent.start_time
        e._owns_tick = False  # the coordinator owns device state
        # each lane's emit path builds its own (smaller) pump connection
        # group — the satellite fix writ structural: emit workers never
        # share a pump lock
        e._pump_groups = 2
        self.engine = e
        self.q: "queue.SimpleQueue" = queue.SimpleQueue()
        # queue.Queue (not SimpleQueue): the emit worker's crash-replay
        # claim (emit_loop) peeks under the queue's own condition before
        # popping, which needs the Python implementation's not_empty /
        # queue attributes. Emit traffic is per-TICK per lane (not
        # per-event), so the condition-variable cost is irrelevant here —
        # the ingest queues stay SimpleQueue.
        self.emit_q: "queue.Queue" = queue.Queue()
        # guards this lane's staged buffers + pool growth + release log:
        # held by the drain worker while applying, by the tick thread
        # while swapping buffers / growing, by the emit worker only for
        # the stale-release snapshot. RLock: apply paths may nest.
        self.stage_lock = threading.RLock()
        self.telemetry = parent.telemetry.lane(str(index))
        # graceful degradation: router sheds into kwok_dropped_jobs_total
        # when this queue is deeper than the configured threshold (0 =
        # never; see EngineConfig.shed_queue_depth); the drain worker
        # clears the flag once the backlog halves
        self._shed_depth = int(parent.config.shed_queue_depth)
        self.shedding = False
        # emit crash-replay slot (see emit_loop): the item being
        # processed, held so a worker crash cannot lose a wire slice
        self._emit_inflight = None

    # --------------------------------------------------------------- drain

    # max items applied per stage_lock hold: bounds how long a flood can
    # keep the tick thread from swapping this lane's buffers
    _BURST = 4096

    def _apply_item(self, item) -> int:
        """Apply one routed queue item; returns the EVENT count it carried
        (burst accounting: a packed sub-batch weighs its record count, so
        the stage_lock hold stays bounded like the per-event path's)."""
        e = self.engine
        if item[1] == "XUPD":
            # managed-ness re-evaluation for pods this lane owns, routed
            # from a sibling lane's node event (see _LaneEngine)
            k = e.pods
            for key in item[2]:
                idx = k.pool.lookup(key)
                if idx is None:
                    continue
                m = k.pool.meta[idx]
                k.buffer.stage_update(
                    idx, e._pod_bits(m), m.get("has_del", False)
                )
            return len(item[2])
        if item[1] == "RECB":
            # a native pre-partitioned sub-batch: this lane's contiguous
            # index run over the shared ParsedBatch (zero-copy handoff)
            batch, idx, lo, hi = item[2]
            return e._ingest_record_batch(item[0], batch, idx, lo, hi)
        e._drain_apply(item, {})  # routed items are parsed; no RAW buffer
        return 1

    def _apply_locked(self, item) -> int:
        """Apply one routed item under the stage_lock. A RECB sub-batch is
        indivisible to the burst accounting, and a reconnect flood can
        partition a whole parse window into one lane — so oversized runs
        are applied in _BURST slices, each under its OWN hold, keeping the
        tick thread's buffer-swap wait bounded exactly like the per-event
        path bounded it. Slice boundaries are legal swap points: the tick
        thread could always interleave between any two routed items of the
        same window, and per-key order is the slice order (same thread)."""
        if item[1] == "RECB":
            batch, idx, lo, hi = item[2]
            e = self.engine
            kind = item[0]
            n = 0
            while lo < hi:
                end = min(lo + self._BURST, hi)
                with self.stage_lock:
                    n += e._ingest_record_batch(kind, batch, idx, lo, end)
                lo = end
            return n
        with self.stage_lock:
            return self._apply_item(item)

    _EMPTY = object()  # drain_loop window sentinel: queue momentarily dry

    def drain_loop(self) -> None:
        q = self.q
        tel = self.telemetry
        empty = self._EMPTY

        def next_item():
            try:
                return q.get_nowait()
            except queue.Empty:
                return empty

        while True:
            item = q.get()
            if item is None:
                return
            stop = False
            t0 = time.perf_counter()
            n = 0
            while item is not empty and not stop:
                if item[1] == "RECB":
                    # sub-batches take their own (sliced) holds
                    n += self._apply_locked(item)
                    if n >= self._BURST:
                        item = empty
                    else:
                        item = next_item()
                        if item is None:
                            stop = True
                else:
                    # consecutive per-event items share ONE stage_lock
                    # hold (bounded by _BURST); a RECB ends the hold so
                    # its slice-holds never nest inside this one
                    with self.stage_lock:
                        while True:
                            n += self._apply_item(item)
                            if n >= self._BURST:
                                item = empty
                                break
                            item = next_item()
                            if item is None:
                                stop = True
                                break
                            if item is empty or item[1] == "RECB":
                                break
            tel.observe_stage("drain", time.perf_counter() - t0)
            depth = q.qsize()
            tel.set_queue_depth(depth)
            if self._shed_depth and self.shedding and (
                depth * 2 <= self._shed_depth
            ):
                # backlog halved: stop shedding, clear the degraded
                # reason, and resync the watch streams — shed events are
                # GONE from the queue, so only a full list+RESYNC
                # actually re-delivers them (this is what makes _shed's
                # "trades freshness, not permanent state" contract true).
                # The clear is RATE-LIMITED by the last resync: a re-list
                # burst bigger than the shed threshold would otherwise
                # re-trip shedding instantly and the clear->resync cycle
                # would hammer the apiserver with back-to-back full
                # LISTs. Deferring the clear keeps the lane shedding
                # (still degraded, still counted) until the interval
                # passes, bounding the LIST rate while each cycle applies
                # up to a queue-full of objects — monotonic progress.
                parent = self.lane_set.parent
                now = time.monotonic()
                if now - parent._shed_resync_at >= _SHED_RESYNC_MIN_S:
                    parent._shed_resync_at = now
                    self.shedding = False
                    if self.engine._degradation.clear(
                        f"lane{self.index}_queue"
                    ):
                        logger.info(
                            "lane %d drained below shed threshold; "
                            "degraded reason cleared; resyncing streams "
                            "to re-deliver shed events", self.index,
                        )
                        parent.resync_streams()
            if stop:
                return

    # ---------------------------------------------------------------- emit

    def emit_loop(self) -> None:
        eq = self.emit_q
        while True:
            if self._emit_inflight is None:
                # the crash-replay slot: unlike drain items (whose loss a
                # stream resync re-delivers), an emit item is an
                # IRREPLACEABLE wire slice — its device transitions fired
                # exactly once — so the claim is NON-destructive: peek
                # under the queue's own condition, publish the reference
                # to the slot, THEN pop. A crash (chaos pill, any
                # BaseException) at ANY point — including the get() wake,
                # where an async exception by construction lands — leaves
                # the item in the queue, in the slot, or both; the
                # watchdog-restarted loop replays it in order. At-least-
                # once is safe: a replayed slice only duplicates patches
                # the echo drop / repair no-op absorbs, the stale filter
                # is idempotent, and _prune_now is monotonic.
                with eq.not_empty:
                    while not eq._qsize():
                        eq.not_empty.wait()
                    self._emit_inflight = eq.queue[0]
                got = eq.get_nowait()
                if got is not self._emit_inflight:
                    # replay raced a crash between store and pop: the
                    # slot's item was already popped+replayed — process
                    # the freshly popped one instead
                    self._emit_inflight = got
            item = self._emit_inflight
            if item is None:
                return
            try:
                if item[0] == "__prune__":
                    self._prune_now(item[1])
                else:
                    self._process_emit(item)
            except Exception:
                logger.exception("lane %d emit failed", self.index)
            self._emit_inflight = None

    def _prune_now(self, min_seq: int) -> None:
        """Drop release-log entries no queued-or-future emit item can
        still consult. Runs BEHIND the emit queue (FIFO): every emit item
        enqueued before this marker has already done its stale filter, so
        entries at or below the oldest in-flight dispatch's seq are dead."""
        with self.stage_lock:
            self.engine._prune_released(min_seq)

    def _process_emit(self, item) -> None:
        """Consume one tick's wire slice for this lane: filter stale mask
        bits, refresh fired rows' phase/cond mirrors, emit patches.

        The whole body holds the lane's stage_lock: the single-lane engine
        ran emit and ingest on one thread, so _emit's pool/meta reads
        (key_of, meta[idx]) could never see a row released-and-reacquired
        mid-iteration. Holding the lock restores that invariant per lane —
        this lane's drain stalls during its own emit, but every OTHER
        lane's drain+emit (and the tick thread) keep running, which is
        where the parallelism was always meant to come from."""
        kind, dirty, deleted, hb, ph, cb, seq, now_str = item
        e = self.engine
        k = e.nodes if kind == "nodes" else e.pods
        t0 = time.perf_counter()
        cap = dirty.shape[0]
        with self.stage_lock:
            # rows released since this tick's dispatch: their mask bits
            # describe the OLD occupant (see ClusterEngine._tick_consume)
            stale = [
                idx for idx, s in k.released_at.items()
                if s > seq and idx < cap
            ]
            if stale:
                dirty[stale] = False
                deleted[stale] = False
                hb[stale] = False
            idxs = np.nonzero(dirty | deleted)[0]
            if idxs.size and ph is not None:
                # fired rows only: rows acquired after the dispatch keep
                # their ingest-time mirror values
                k.phase_h[idxs] = ph[idxs]
                k.cond_h[idxs] = cb[idxs]
            if idxs.size:
                e.telemetry.inc_kind(
                    "transitions_total", kind, int(idxs.size)
                )
            if idxs.size or hb.any():
                e._emit(kind, k, dirty, deleted, hb, now_str)
        t1 = time.perf_counter()
        self.telemetry.observe_stage("emit", t1 - t0)
        e.telemetry.span(
            "tick.emit", t0, t1, "emit",
            {"kind": kind, "shard": self.index},
        )


def iter_recb_items(kind: str, batch, t: float):
    """Yield ``(lane_index, n_events, item)`` per non-empty lane of a
    pre-partitioned ParsedBatch — THE routed-item wire shape
    ``(kind, "RECB", (batch, lane_idx, lo, hi), t)`` that ShardLane's
    queue consumer unpacks. The single producer-side definition: the
    router (LaneSet.route_batch) and the microbenches
    (benchmarks/cost_model.py, benchmarks/route_micro.py) all build the
    handoff here, so the benches can never measure a stale shape."""
    lane_off = batch.lane_off
    lane_idx = batch.lane_idx
    for li in range(len(lane_off) - 1):
        lo = lane_off[li]
        hi = lane_off[li + 1]
        if hi > lo:
            yield li, hi - lo, (kind, "RECB", (batch, lane_idx, lo, hi), t)


class LaneSet:
    """The coordinator: owns the stacked device state, the router, and the
    (now thin) tick loop — kernel dispatch plus per-shard wire handoff."""

    def __init__(self, parent: ClusterEngine, n: int):
        self.parent = parent
        self.n = int(n)
        # per-lane row budget: an even split PLUS 25% slack — crc32
        # partitioning is only statistically even, and one lane crossing
        # cap/n would otherwise force a whole-stack regrow (host copy +
        # re-jit at the new shape) right at the configured capacity
        r = max(
            _MIN_LANE_ROWS,
            -(-int(parent.config.initial_capacity) * 5 // (4 * self.n)),
        )
        if parent._mesh is not None:
            from kwok_tpu.parallel.mesh import pad_to_multiple

            r = pad_to_multiple(r, parent._mesh)
        self.r = r
        self.lanes = [ShardLane(self, i, r) for i in range(self.n)]
        self.stacked: dict[str, RowState] = {}
        # bumped by the router per routed event; the tick loop's
        # got-an-event gate (plain int: GIL-atomic, one writer)
        self.events_routed = 0

    # ------------------------------------------------------------ lifecycle

    def prepare(self, executor) -> None:
        """Wire the shared executor into every lane, place the stacked
        state on device, and pre-compile scatters + the fused tick (the
        single-lane warm-up, against the stacked shapes)."""
        for lane in self.lanes:
            e = lane.engine
            e._executor = executor
            e._running = True
            e._record_needs_full_path = self.parent._record_needs_full_path
            # Prime the native pump NOW, outside every lock: the emit
            # worker runs _process_emit under the lane's stage_lock, and
            # lazy construction there opened this lane's whole TCP
            # connection group while the drain worker queued on the lock
            # (kwoklint blocking-under-lock caught it; regression:
            # tests/test_lanes.py::test_pump_primed_before_workers).
            e._get_pump()
        self._ensure_stacked()
        self._warm_scatters()
        self._warm_tick()

    def _ensure_stacked(self) -> None:
        if self.stacked:
            return
        fused = self.parent._get_fused()
        cap = self.r * self.n
        self.stacked = {
            "nodes": fused.place(new_row_state(cap)),
            "pods": fused.place(new_row_state(cap)),
        }

    def _warm_scatters(self) -> None:
        from kwok_tpu.ops.updates import (
            BATCH,
            BATCH_LARGE,
            InitBatch,
            UpdateBatch,
            init_rows,
            update_rows,
        )

        for kind in _KINDS:
            state = self.stacked[kind]
            cap = state.capacity
            for width in (BATCH, BATCH_LARGE):
                idx = np.full(width, cap, np.int32)  # every lane padded
                state = init_rows(state, InitBatch(
                    idx=idx,
                    active=np.zeros(width, bool),
                    phase=np.zeros(width, np.int32),
                    cond_bits=np.zeros(width, np.uint32),
                    sel_bits=np.zeros(width, np.uint32),
                    has_deletion=np.zeros(width, bool),
                ))
                state = update_rows(state, UpdateBatch(
                    idx=idx,
                    sel_bits=np.zeros(width, np.uint32),
                    has_deletion=np.zeros(width, bool),
                ))
            self.stacked[kind] = state

    def _warm_tick(self) -> None:
        fused = self.parent._get_fused()
        (nout, pout), wire = fused(
            (self.stacked["nodes"], self.stacked["pods"]), 0.0
        )
        self.stacked["nodes"] = nout.state
        self.stacked["pods"] = pout.state
        np.asarray(wire)  # complete (and warm) the wire's D2H path

    def start_workers(self, threads: list) -> None:
        """Spawn the router + per-lane drain/emit workers (the tick loop
        itself is started by ClusterEngine.start as 'kwok-tick'),
        supervised by the engine's watchdog: a crashed worker used to
        leave its queue backing up forever behind a healthy-looking
        engine — now it restarts in place (same thread, same queues)
        within the restart budget."""
        wd = self.parent._watchdog

        def spawn(target, name):
            if wd is not None:
                return wd.spawn(target, name=name)
            return spawn_worker(target, name=name)

        threads.append(spawn(self.route_loop, "kwok-route"))
        for lane in self.lanes:
            for target, name in (
                (lane.drain_loop, f"kwok-lane{lane.index}"),
                (lane.emit_loop, f"kwok-emit{lane.index}"),
            ):
                threads.append(spawn(target, name))

    def close(self) -> None:
        """Release lane-owned pump connection groups (the shared client
        and executor belong to the parent)."""
        for lane in self.lanes:
            e = lane.engine
            e._running = False
            if e._pump is not None:
                e._pump.close()
                e._pump = None

    # --------------------------------------------------------------- router

    def route_loop(self) -> None:
        """Drain the parent's ingest queue, batch-parse raw watch lines
        (the cheap C++ call — ~1.3 µs/line), and hand parsed events to
        their key's lane. The rv/generation bookkeeping stays here, on the
        parent, exactly as the single-lane tick thread kept it."""
        parent = self.parent
        q = parent._q
        tel = parent.telemetry
        # parse-batch window: after the first queued item, keep absorbing
        # for up to half a tick before flushing — the single-lane loop
        # amortized ONE batched C++ parse per drain window, and flushing
        # per tiny burst would re-pay the per-call setup thousands of
        # times at high event rates (measured 5x parse inflation)
        window = max(0.002, parent.config.tick_interval / 2)
        raw_buf: dict = {}
        try:
            while True:
                try:
                    item = q.get(timeout=0.1)
                except queue.Empty:
                    if not parent._running:
                        return
                    continue
                if item is None:
                    if not parent._running:
                        return
                    continue
                lag = time.monotonic() - item[3]
                parent._drain_apply(item, raw_buf, self.route, self.n)
                window_end = time.monotonic() + window
                while True:
                    timeout = window_end - time.monotonic()
                    if timeout <= 0:
                        break
                    try:
                        item = q.get(timeout=timeout)
                    except queue.Empty:
                        break
                    if item is None:
                        if not parent._running:
                            break
                        continue
                    lag = max(lag, time.monotonic() - item[3])
                    parent._drain_apply(item, raw_buf, self.route, self.n)
                if raw_buf:
                    parent._drain_flush(raw_buf, self.route, self.n)
                tel.observe_watch_lag(lag)
                tel.set_gauge("ingest_queue_depth", q.qsize())
                if not parent._running:
                    return
        finally:
            # flush straggler lines, then let every lane drain worker exit
            try:
                if raw_buf:
                    parent._drain_flush(raw_buf, self.route, self.n)
            finally:
                for lane in self.lanes:
                    lane.q.put(None)

    def route(self, kind: str, type_: str, obj) -> None:
        """Partition one parsed event to its key's lane. RESYNC snapshots
        broadcast (each lane prunes only keys it owns)."""
        t = time.monotonic()
        if type_ == "RESYNC":
            for lane in self.lanes:
                lane.q.put((kind, type_, obj, t))
            self.events_routed += 1
            return
        key = self._key_of(kind, type_, obj)
        if key is None:
            return
        lane = self.lanes[shard_of(key, self.n)]
        if lane._shed_depth and lane.q.qsize() > lane._shed_depth:
            self._shed(lane, 1)
            return
        self.events_routed += 1
        lane.q.put((kind, type_, obj, t))

    def route_batch(self, kind: str, batch) -> None:
        """Hand a native pre-partitioned ParsedBatch to the lanes: one
        zero-copy (batch, index-run) item per lane with routed work. The
        per-event Python hash+dispatch of `route` collapses to n_lanes
        queue puts per window — the router's cost stops scaling with the
        event rate (the serial-Amdahl fix; benchmarks/route_micro.py
        measures the per-event delta). Key->lane mapping is the C side of
        rowpool.shard_of, proven identical by the test_lanes parity
        oracle."""
        t0 = time.perf_counter()
        t = time.monotonic()
        routed = 0
        for li, count, item in iter_recb_items(kind, batch, t):
            lane = self.lanes[li]
            if lane._shed_depth and lane.q.qsize() > lane._shed_depth:
                self._shed(lane, count)
                continue
            lane.q.put(item)
            lane.telemetry.inc_routed(count)
            routed += count
        self.events_routed += routed
        self.parent.telemetry.observe_route_batch(
            time.perf_counter() - t0
        )

    def _shed(self, lane: ShardLane, n: int) -> None:
        """Graceful degradation: a lane whose drain is down (or drowning)
        past the configured queue depth sheds routed events — counted in
        kwok_dropped_jobs_total, surfaced via kwok_degraded{reason=} and
        a 503 /readyz — instead of growing the queue without bound. The
        drain worker requests a stream resync the moment it catches up
        (drain_loop's shed-clear path), so every shed object is
        re-delivered by the full re-list: shedding trades freshness,
        not permanent state."""
        parent = self.parent
        parent.telemetry.inc("dropped_jobs_total", n)
        lane.shedding = True
        if parent._degradation.set(f"lane{lane.index}_queue"):
            logger.warning(
                "lane %d queue past %d: shedding routed events "
                "(engine degraded)", lane.index, lane._shed_depth,
            )

    def _key_of(self, kind: str, type_: str, obj):
        """The routing key — identical to the lane pool's key, so a key's
        row can only ever live in the lane its events are routed to."""
        if type_ == "REC":
            name = obj.name
            ns = obj.namespace or "default"
            if not name:
                # unparseable record fields: fall back to the raw line
                try:
                    meta = (
                        (json.loads(obj.raw).get("object") or {})
                        .get("metadata") or {}
                    )
                except Exception:
                    # unrouteable event dropped — same information loss as
                    # the single-lane parse fallback, but COUNTED so a
                    # flood of these shows up on /metrics
                    swallowed("lanes.unrouteable_event")
                    return None
                name = meta.get("name") or ""
                ns = meta.get("namespace") or "default"
        elif isinstance(obj, dict):
            meta = obj.get("metadata") or {}
            name = meta.get("name") or ""
            ns = meta.get("namespace") or "default"
        else:
            return None
        if not name:
            return None
        return (ns, name) if kind == "pods" else name

    def route_pod_updates(self, node_name: str) -> None:
        """Fan a node's managed-ness change out to the lanes owning its
        pods — one XUPD batch per lane, through the lane's own queue."""
        keys = self.parent.pods_by_node.get(node_name)
        if not keys:
            return
        # snapshot: the set is shared and other lanes' drain workers
        # add/discard concurrently (single-op mutations, GIL-atomic); a
        # mid-copy resize just means retrying the C-level copy — the
        # resize window is nanoseconds, so this converges immediately,
        # and losing the fan-out (stale SEL_MANAGED bits until the pod's
        # next event) is worse than another attempt
        while True:
            try:
                snapshot = list(keys)
                break
            except RuntimeError:
                time.sleep(0)  # yield to the mutating drain worker
        by_lane: dict[int, list] = {}
        for key in snapshot:
            by_lane.setdefault(shard_of(key, self.n), []).append(key)
        t = time.monotonic()
        for li, lane_keys in by_lane.items():
            self.lanes[li].q.put(("pods", "XUPD", lane_keys, t))

    # ------------------------------------------------------------ tick loop

    def tick_loop(self) -> None:
        """The coordinator tick thread: pure kernel dispatch + per-shard
        wire handoff (drain and emit live on the lane workers). Pipelined
        like the single-lane loop: up to pipeline_depth wires in flight,
        FIFO consume."""
        parent = self.parent
        interval = parent.config.tick_interval
        depth = max(1, int(parent.config.pipeline_depth))
        pending: "deque[_LanePending]" = deque()
        profiling.maybe_start()
        seen_events = 0
        tel = parent.telemetry
        try:
            while parent._running:
                deadline = time.monotonic() + interval
                got_event = self.events_routed != seen_events
                if (
                    not pending
                    and not got_event
                    and not self._staged()
                ):
                    wake = parent._idle_wake
                    if wake is None:
                        deadline = time.monotonic() + parent._IDLE_MAX
                    elif wake > deadline:
                        deadline = min(
                            wake, time.monotonic() + parent._IDLE_MAX
                        )
                while parent._running:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    if pending and parent._wire_ready(pending[0]):
                        try:
                            self._consume(pending.popleft(), pending)
                        except Exception:
                            logger.exception("sharded consume failed")
                        continue
                    if not got_event and (
                        self.events_routed != seen_events or self._staged()
                    ):
                        # an event arriving during an idle sleep must be
                        # ticked within one normal interval
                        got_event = True
                        deadline = min(
                            deadline, time.monotonic() + interval
                        )
                    if parent._idle_wake == 0.0:
                        # the HA plane zeroes the wake when it opens the
                        # takeover gate on a quiet cluster: honor the
                        # explicit wake within one poll slice. (Normal
                        # wakes are future monotonic stamps and keep the
                        # interval pacing; only the literal 0.0 sentinel
                        # breaks early.)
                        break
                    time.sleep(
                        min(remaining, 0.002 if pending else 0.02)
                    )
                got_event = got_event or self.events_routed != seen_events
                seen_events = self.events_routed
                tel.set_gauge("tick_inflight", len(pending))
                did_dispatch = False
                try:
                    while pending and (
                        len(pending) >= depth
                        or parent._wire_ready(pending[0])
                    ):
                        self._consume(pending.popleft(), pending)
                    wake = parent._idle_wake
                    if (
                        got_event
                        or self._staged()
                        or (wake is not None
                            and time.monotonic() >= wake)
                    ):
                        did_dispatch = True
                        p = self.dispatch()
                        if p is not None:
                            pending.append(p)
                except Exception:
                    logger.exception("sharded tick failed")
                    parent._idle_wake = time.monotonic() + interval
                if (
                    parent._startup_pending is not None
                    or parent._ckpt is not None
                ):
                    # crash-durable restarts: the coordinator owns the
                    # stacked device state, so reconcile + checkpoint
                    # gathers run here (zero-cost when disabled: one
                    # attribute test per iteration)
                    try:
                        self._ckpt_service(did_dispatch)
                    except Exception:
                        logger.exception("checkpoint service failed")
        finally:
            # stopping: flush in-flight wires so computed patches are not
            # dropped, then release the emit workers
            while pending:
                try:
                    self._consume(pending.popleft(), pending)
                except Exception:
                    logger.exception("final sharded consume failed")
            for lane in self.lanes:
                lane.emit_q.put(None)
            if parent._ckpt is not None:
                # SIGTERM graceful drain: gather the shutdown checkpoint
                # after the in-flight wires flushed (see the single-lane
                # loop's finally)
                try:
                    parent._ckpt.final(self._ckpt_snapshot(parent._now()))
                except Exception:
                    logger.exception("final checkpoint failed")

    def _staged(self) -> bool:
        return any(
            k.buffer.pending
            for lane in self.lanes
            for k in (lane.engine.nodes, lane.engine.pods)
        )

    # --------------------------------------- crash-durable restarts (ckpt)

    def _ckpt_service(self, dispatched: bool) -> None:
        """The sharded twin of ClusterEngine._ckpt_service: the stacked
        device state lives here, the row pools live on the lanes. Pool
        walks take each lane's stage_lock (pure dict/array reads — never
        blocking work); device reads/scatters happen lock-free on this
        thread, which owns the stacked state."""
        parent = self.parent
        now = parent._now()
        r = parent._restore
        if r is not None:
            if r.expired() or (not r.gate_ready and not r.remaining):
                s = r.finish()
                parent._close_restore(r)
                logger.info(
                    "checkpoint refine closed: %d refined, %d stale",
                    s["refined"], s["stale"],
                )
            else:
                self._ckpt_refine(r, now)
            # tick until the pipeline flushes every pre-refine wire —
            # their consumes re-arm the stale fresh-arm wake (see
            # ClusterEngine._ckpt_service)
            parent._ckpt_force_ticks = (
                max(1, int(parent.config.pipeline_depth)) + 2
            )
        if parent._ckpt_force_ticks > 0:
            parent._ckpt_force_ticks -= 1
            parent._idle_wake = time.monotonic()
        parent._ckpt_gate(dispatched, staged=self._staged())
        ck = parent._ckpt
        if ck is not None and ck.due():
            ck.submit(self._ckpt_snapshot(now))

    def _lane_kind(self, lane: ShardLane, kind: str):
        e = lane.engine
        return e.nodes if kind == "nodes" else e.pods

    def _ckpt_refine(self, r, now: float) -> None:
        """Match checkpoint entries per lane (the key->lane mapping is
        the pool's own), then scatter ONE refine run per kind into the
        stacked state at each lane's offset. A matched row released by a
        concurrent drain worker right after the match is harmless: its
        re-acquisition's staged init flushes AFTER this scatter (the
        flush runs on this same thread) and overwrites the refined
        fields."""
        from kwok_tpu.ops.updates import refine_flush

        for kind in _KINDS:
            if not r.kinds.get(kind):
                continue
            state = self.stacked.get(kind)
            if state is None:
                continue
            # current deadlines of the whole stacked kind: entries with a
            # delay residue are consumed only once their row is ARMED
            # (finite fire_at) — see ClusterEngine._ckpt_refine
            cur_fire = np.asarray(state.fire_at)
            runs = []
            for li, lane in enumerate(self.lanes):
                k = self._lane_kind(lane, kind)
                with lane.stage_lock:
                    staged = (
                        k.buffer.staged_rows() if k.buffer.pending
                        else frozenset()
                    )
                    idx, fire, hb, gen = r.match_kind(
                        kind, k.pool, staged, now,
                        phase_h=k.phase_h, fire=cur_fire,
                        offset=li * self.r,
                    )
                if idx.size:
                    runs.append((li, idx, fire, hb, gen))
            for li, idx, fire, hb, gen in runs:
                state = refine_flush(
                    state, idx, fire, hb, gen, offset=li * self.r
                )
            self.stacked[kind] = state

    def _ckpt_snapshot(self, now: float) -> dict:
        """Gather the checkpoint rows across lanes: one host copy of the
        stacked timer fields per kind, then a per-lane pool walk under
        that lane's stage_lock."""
        from kwok_tpu.ops.tick import gather_deadlines
        from kwok_tpu.resilience import checkpoint as ckpt_mod

        kinds: dict = {}
        for kind in _KINDS:
            state = self.stacked.get(kind)
            if state is None:
                kinds[kind] = {}
                continue
            fire, hb, gen = gather_deadlines(state)
            ents: dict = {}
            for li, lane in enumerate(self.lanes):
                k = self._lane_kind(lane, kind)
                with lane.stage_lock:
                    staged = (
                        k.buffer.staged_rows() if k.buffer.pending
                        else frozenset()
                    )
                    ents.update(ckpt_mod.gather_rows(
                        kind, k.pool, k.phase_h, fire, hb, gen, staged,
                        now, offset=li * self.r,
                    ))
            kinds[kind] = ents
        return {"kinds": kinds}

    # ----------------------------------------------------- dispatch/consume

    def dispatch(self) -> "_LanePending | None":
        """Flush every lane's staged writes into the stacked state and
        dispatch the fused kernel (the single-lane _tick_dispatch, minus
        drain and emit — those live on the lane workers)."""
        parent = self.parent
        if parent._ha_hold:
            # observe-only standby (resilience/ha.py): flush every
            # lane's staged writes into the stacked state (mirrors stay
            # current, buffers stay bounded) but never run the kernel —
            # nothing arms, nothing fires, no emit items are produced.
            # Same swap-under-stage-lock protocol as the live path.
            self._ensure_stacked()
            swapped: list[tuple[int, str, UpdateBuffer]] = []
            want = self.r
            for li, lane in enumerate(self.lanes):
                e = lane.engine
                with lane.stage_lock:
                    for kind, k in (("nodes", e.nodes), ("pods", e.pods)):
                        want = max(want, k.capacity)
                        if k.buffer.pending:
                            swapped.append((li, kind, k.buffer))
                            k.buffer = UpdateBuffer()
            if want > self.r:
                self._regrow(want)
            for li, kind, buf in swapped:
                self.stacked[kind] = buf.flush(
                    self.stacked[kind], offset=li * self.r
                )
            tel = parent.telemetry
            tel.set_gauge(
                "nodes_managed",
                sum(len(lane.engine.nodes.pool) for lane in self.lanes),
            )
            tel.set_gauge(
                "pods_managed",
                sum(len(lane.engine.pods.pool) for lane in self.lanes),
            )
            parent._idle_wake = None  # no timers can be due while held
            if not parent._ha_hold:
                # takeover raced this hold dispatch: restore the plane's
                # explicit wake the None above would otherwise clobber
                # (the plane flips _ha_hold before writing 0.0)
                parent._idle_wake = 0.0
            return None
        if parent.config.profile_dir:
            parent._maybe_profile()
        t0 = time.perf_counter()
        now = parent._now()
        if now >= REBASE_AFTER:
            parent._epoch += now
            for lane in self.lanes:
                lane.engine._epoch = parent._epoch
            for kind in _KINDS:
                self.stacked[kind] = rebase_times(self.stacked[kind], now)
            parent._inc("epoch_rebases_total")
            logger.info("epoch rebase at engine time %.1fs", now)
            now = 0.0
        self._ensure_stacked()
        # swap full buffers out under each lane's stage lock (cheap), then
        # flush them into the stacked state lock-free: the drain workers
        # keep staging into the fresh buffers while the scatters dispatch
        swapped: list[tuple[int, str, UpdateBuffer]] = []
        want = self.r
        any_rows = False
        for li, lane in enumerate(self.lanes):
            e = lane.engine
            with lane.stage_lock:
                for kind, k in (("nodes", e.nodes), ("pods", e.pods)):
                    want = max(want, k.capacity)
                    if k.buffer.pending:
                        swapped.append((li, kind, k.buffer))
                        k.buffer = UpdateBuffer()
                        any_rows = True
                    elif len(k.pool):
                        any_rows = True
        if want > self.r:
            self._regrow(want)
        r = self.r
        for li, kind, buf in swapped:
            self.stacked[kind] = buf.flush(
                self.stacked[kind], offset=li * r
            )
        t_flush = time.perf_counter()
        tel = parent.telemetry
        tel.set_gauge(
            "nodes_managed",
            sum(len(lane.engine.nodes.pool) for lane in self.lanes),
        )
        tel.set_gauge(
            "pods_managed",
            sum(len(lane.engine.pods.pool) for lane in self.lanes),
        )
        tel.inc("ticks_total")
        tel.observe_stage("flush", t_flush - t0)
        if not any_rows:
            parent._idle_wake = None  # empty engine: sleep until events
            return None
        fused = parent._get_fused()
        now_base = now - (fused.steps - 1) * fused.dt
        (nout, pout), wire = fused(
            (self.stacked["nodes"], self.stacked["pods"]), now_base
        )
        self.stacked["nodes"] = nout.state
        self.stacked["pods"] = pout.state
        prefetch(wire)
        t_end = time.perf_counter()
        tel.span("tick.dispatch", t0, t_end, "dispatch")
        return _LanePending(
            wire=wire,
            r=r,
            cap=r * self.n,
            seqs=[lane.engine._release_seq for lane in self.lanes],
            now=now,
            mono=time.monotonic(),
            host_s=t_end - t0,
        )

    def _consume(self, p: _LanePending, pending, inline: bool = False) -> None:
        """Consume the oldest in-flight wire: slice it per lane and hand
        each lane its view (emit worker does mirrors + patches). With
        inline=True (tick_once) lanes process on the calling thread."""
        parent = self.parent
        t0 = time.perf_counter()
        counters, masks_fn, dues, rows_fn = unpack_wire(
            np.asarray(p.wire), [p.cap, p.cap], rows=True
        )
        t_wire = time.perf_counter()
        nd = float(dues.min())
        parent._idle_wake = (
            None if nd == float("inf")
            else p.mono + max(0.0, nd - p.now)
        )
        if counters.any():
            now_str = now_rfc3339()
            masks = masks_fn()
            rows = (
                rows_fn()
                if (int(counters[0]) or int(counters[1])) else None
            )
            views = lane_views(masks, rows, self.n, p.r)
            for li, lane in enumerate(self.lanes):
                for ki, kind in enumerate(_KINDS):
                    dirty, deleted, hb, ph, cb = views[li][ki]
                    if not (dirty.any() or deleted.any() or hb.any()):
                        continue
                    item = (
                        kind, dirty, deleted, hb, ph, cb,
                        p.seqs[li], now_str,
                    )
                    if inline:
                        lane._process_emit(item)
                    else:
                        lane.emit_q.put(item)
        # release-log pruning rides the emit queue BEHIND this tick's
        # items: pruning here directly would race the emit workers —
        # entries between a queued item's seq and the oldest pending
        # dispatch's seq would vanish before that item's stale filter ran
        for li, lane in enumerate(self.lanes):
            nxt = next(
                (q.seqs[li] for q in pending),
                lane.engine._release_seq,
            )
            if inline:
                lane._prune_now(nxt)
            else:
                lane.emit_q.put(("__prune__", nxt))
        t_end = time.perf_counter()
        tel = parent.telemetry
        tel.observe_tick(t_end - t0 + p.host_s)
        tel.observe_stage("kernel", t_wire - t0)
        tel.span(
            "tick.consume", t0, t_end, "consume",
            {"wire_wait_us": round((t_wire - t0) * 1e6, 1)},
        )

    # ------------------------------------------------------------------ grow

    def _regrow(self, want: int) -> None:
        """A lane's pool grew past the per-lane row budget: grow every
        lane to the new common capacity and rebuild the stacked state
        (the federation _maybe_regrow pattern)."""
        new_r = want
        if self.parent._mesh is not None:
            from kwok_tpu.parallel.mesh import pad_to_multiple

            new_r = pad_to_multiple(new_r, self.parent._mesh)
        old_r = self.r
        logger.info(
            "lane regrow (%d lanes): %d -> %d rows/lane",
            self.n, old_r, new_r,
        )
        for lane in self.lanes:
            with lane.stage_lock:
                for k in (lane.engine.nodes, lane.engine.pods):
                    if k.capacity < new_r:
                        k.grow(new_r)
        fused = self.parent._get_fused()
        for kind in _KINDS:
            host = to_host(self.stacked[kind])
            stacked = new_row_state(new_r * self.n)
            for c in range(self.n):
                for f in RowState._fields:
                    getattr(stacked, f)[
                        c * new_r : c * new_r + old_r
                    ] = getattr(host, f)[c * old_r : (c + 1) * old_r]
            self.stacked[kind] = fused.place(stacked)
        self.r = new_r

    # ------------------------------------------------------------ sync mode

    def tick_once(self) -> None:
        """One synchronous sharded step (tests, tools): route + drain every
        queue inline, dispatch, consume with inline emit. Patch-for-patch
        identical to the threaded pipeline — same routing, same lane
        application order, same wire slicing."""
        self.drain_inline()
        p = self.dispatch()
        if p is not None:
            self._consume(p, deque(), inline=True)

    def drain_inline(self) -> None:
        """Route the parent queue and apply every lane queue to quiescence
        (XUPD fan-outs re-enqueue, hence the outer loop)."""
        parent = self.parent
        raw_buf: dict = {}
        progressed = True
        while progressed:
            progressed = False
            while True:
                try:
                    item = parent._q.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    continue
                parent._drain_apply(item, raw_buf, self.route, self.n)
                progressed = True
            if raw_buf:
                parent._drain_flush(raw_buf, self.route, self.n)
                progressed = True
            for lane in self.lanes:
                while True:
                    try:
                        item = lane.q.get_nowait()
                    except queue.Empty:
                        break
                    if item is None:
                        continue
                    with lane.stage_lock:
                        lane._apply_item(item)
                    progressed = True
