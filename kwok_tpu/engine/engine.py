"""ClusterEngine: the TPU-backed fake kubelet.

Architecture (replaces pkg/kwok/controllers/controller.go + node_controller.go
+ pod_controller.go):

  watch threads ──> ingest queue ──> tick thread ──> patch executor
                                      │    ▲
                                      ▼    │
                               device RowState (resident)

- Watch threads re-watch forever with 5s backoff on error
  (node_controller.go:241-254 semantics).
- The tick thread is the ONLY mutator of engine state: it drains the ingest
  queue into staged row writes, flushes them to the device, runs the jitted
  tick, and turns the dirty/deleted/heartbeat masks into patch jobs.
- The executor bounds API fan-out (default 16, matching the reference's
  parallelTasks pools, controller.go:118-136).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from kwok_tpu import cni
from kwok_tpu.edge.ippool import IPPool
from kwok_tpu.edge.kubeclient import (
    ADDED,
    BOOKMARK,
    DELETED,
    KubeClient,
    TooLargeResourceVersion,
    TooManyRequests,
    WatchExpired,
)
from kwok_tpu.edge.merge import node_status_patch_needed, pod_status_patch_needed
from kwok_tpu.edge.render import (
    _NODE_CONDITION_META,
    now_rfc3339,
    render_node_heartbeat,
    render_node_status,
    render_pod_status,
    rfc3339,
)
from kwok_tpu.edge.selectors import parse_selector
from kwok_tpu.models import (
    compile_emit_templates,
    compile_rules,
    default_node_rules,
    default_pod_rules,
)
from kwok_tpu.models.defaults import SEL_HEARTBEAT, SEL_MANAGED, SEL_ON_MANAGED_NODE
from kwok_tpu.models.lifecycle import (
    NODE_PHASES,
    POD_PHASES,
    LifecycleRule,
    ResourceKind,
)
from kwok_tpu.ops.state import RowState, grow as grow_state, new_row_state
from kwok_tpu.ops.tick import (
    REBASE_AFTER,
    MultiTickKernel,
    prefetch,
    rebase_times,
    to_host,
    unpack_wire,
)
from kwok_tpu.ops.updates import UpdateBuffer
from kwok_tpu.engine.rowpool import (
    EF_RENDER,
    EF_RGATES,
    EF_SCALAR,
    RowPool,
)
from kwok_tpu.resilience import faults as resilience_faults
from kwok_tpu.resilience import ha as resilience_ha
from kwok_tpu.resilience.policy import (
    PATCH_RETRY,
    PUMP_RESEND,
    WATCH_RECONNECT,
    Degradation,
)
from kwok_tpu.resilience.watchdog import Watchdog
from kwok_tpu.telemetry import EngineTelemetry
from kwok_tpu.telemetry.errors import swallowed, wire_reject
from kwok_tpu.workers import spawn_worker

logger = logging.getLogger("kwok_tpu.engine")

# URL-escape k8s names/namespaces in patch paths. quote() already
# short-circuits all-safe strings through a C-level rstrip, so a
# hand-rolled "fast path" only loses (measured 2.2x slower); the alias
# just keeps the hot emit loops free of attribute lookups.
from urllib.parse import quote as _q  # noqa: E402

# Whole-process sampling attribution: set KWOK_TPU_SAMPLE_PROF=<path.json>
# and a sampler thread snapshots every engine thread's stack (tick, watch
# ingest, patch executor) until stop() dumps per-thread hot-frame counts.
# This exists because the engine's CPU is spread across threads a
# main-thread profiler never sees — it is how the cost model's
# "unattributed residual" gets hunted down. (cProfile can't do this on
# 3.12: one sys.monitoring tool per process.)
from kwok_tpu import profiling  # noqa: E402

_NODE_READY_BITS = 1 << NODE_PHASES.condition_bit("Ready")
# status keys whose strategic merge is plain replacement — when the current
# status has only these, merge(current, rendered) == rendered exactly
_SCALAR_STATUS_KEYS = frozenset({"phase", "hostIP", "podIP", "startTime"})
_PENDING = POD_PHASES.phase_id("Pending")
_NODE_READY = NODE_PHASES.phase_id("Ready")
_NODE_OBSERVED = NODE_PHASES.phase_id("Observed")


@dataclasses.dataclass
class EngineConfig:
    """Mirrors KwokConfigurationOptions
    (pkg/apis/v1alpha1/kwok_configuration_types.go:30-81)."""

    manage_all_nodes: bool = False
    manage_nodes_with_annotation_selector: str = ""
    manage_nodes_with_label_selector: str = ""
    disregard_status_with_annotation_selector: str = ""
    disregard_status_with_label_selector: str = ""
    cidr: str = "10.0.0.1/24"
    node_ip: str = "196.168.0.1"
    enable_cni: bool = False  # accepted for parity; real CNI is out of scope
    tick_interval: float = 0.05
    # Inner simulated ticks per device dispatch (ops/tick.MultiTickKernel
    # steps): >1 amortizes dispatch round-trips on remote/tunneled devices.
    # Counters stay exact; a row transitioning more than once per dispatch
    # is patched once with its final state (the engine's normal coalescing).
    tick_substeps: int = 1
    heartbeat_interval: float = 30.0
    parallelism: int = 16
    initial_capacity: int = 4096
    # Max device dispatches in flight before the tick loop blocks on the
    # oldest. >1 pipelines the loop: tick N+1 (and the ingest drain feeding
    # it) is dispatched while tick N's wire is still crossing the device
    # link, so per-tick wall is max(RTT, host work) instead of their sum —
    # the difference between TPU-helped and TPU-penalized on a remote/
    # tunneled chip. 1 = the old fully-synchronous loop.
    pipeline_depth: int = 8
    # Hash-partitioned host lanes for the drain+emit pipeline (engine/
    # lanes.py): objects shard by key at ingest; each lane runs its own
    # drain worker, staged-row buffers, emit worker, and pump connection
    # group, so drain+emit for shard A overlaps shard B and the tick
    # thread shrinks to kernel dispatch + per-shard wire handoff. 1 = the
    # classic single-lane engine (the library/test default — every
    # synchronous test drives engine state directly); 0 = auto
    # (config.types.auto_drain_shards: cpu_count capped by
    # max_drain_shards) — what the CLI defaults to in production.
    drain_shards: int = 1
    # cap on the AUTO lane count; 0 = config.types.DEFAULT_MAX_DRAIN_SHARDS
    max_drain_shards: int = 0
    # Process lanes (engine/proclanes.py, ISSUE 15): when true (and
    # drain_shards resolves to >1), each lane is a spawned worker
    # PROCESS running the full single-lane engine over its shard — the
    # GIL escape. The parent keeps watch ingest + the router and ships
    # raw event bytes over per-lane shared-memory rings; children drain,
    # tick, and emit on true cores, checkpoint to lane<i>.ckpt.json, and
    # are respawned (budget/ledger semantics) by a process supervisor.
    # False (the default) keeps the threaded ShardLanes byte-unchanged —
    # no shm arena, pipe, or process exists. Requires an HTTP --master;
    # refused with use_mesh, ha_role, and federation.
    lane_procs: bool = False
    node_rules: list[LifecycleRule] | None = None
    pod_rules: list[LifecycleRule] | None = None
    use_mesh: bool = False
    # when set, a JAX profiler trace of ticks [2, 102) is written here
    # (SURVEY.md §5.1: the reference has no tracing at all; we add device
    # traces + the per-tick timing counters in `metrics`)
    profile_dir: str = ""
    # when set, the engine's span tracer (telemetry.trace) dumps its ring
    # as Chrome trace-event JSON here at stop(); KWOK_TPU_TRACE=<path>
    # works too. The tracer itself is always on — this only controls the
    # at-exit dump (the live view is the HTTP /debug/trace endpoint).
    trace_dump: str = ""
    # when set (or KWOK_TPU_FLIGHT_DIR), any FRESH /readyz degradation
    # reason triggers a best-effort grab of the apiserver's
    # /debug/flight dump into this directory — the flight-recorder
    # post-mortem for "why did we degrade" (HTTP masters only; merge it
    # with the trace dump via `python -m kwok_tpu.telemetry.timeline`)
    flight_dir: str = ""
    # 1-in-N sampling for per-event ingest->patch spans (the end-to-end
    # per-pod attribution the cost model cannot see); 0 disables
    trace_sample_every: int = 256
    # Deterministic fault-injection spec (resilience/faults.py grammar;
    # docs/resilience.md). "" = disabled (falls back to KWOK_TPU_FAULTS);
    # when set, the client transport, pump, and workers are wrapped.
    faults: str = ""
    # Graceful degradation: shed routed events when a lane queue is
    # deeper than this instead of letting it grow without bound while a
    # lane is down (kwok_dropped_jobs_total + kwok_degraded{reason=}).
    # 0 = never shed (the library/test default: correctness tests rely
    # on lossless ingest).
    shed_queue_depth: int = 0
    # Watchdog restart budget for supervised lane workers: more than
    # `budget` restarts of one worker within `window` seconds stops
    # supervision and marks the engine degraded (/readyz 503).
    worker_restart_budget: int = 5
    worker_restart_window: float = 30.0
    # Crash-durable restarts (resilience/checkpoint.py): when set, the
    # irreplaceable per-row scalars — (uid, rv, remaining-delay residue,
    # heartbeat-wheel phase, transition generation) — are checkpointed
    # to <dir>/<name>.ckpt.json every checkpoint_interval seconds
    # (atomic rename), and a cold start re-lists then refines matching
    # rows' timers from the file instead of resetting every in-flight
    # delay. "" = disabled (falls back to KWOK_TPU_CHECKPOINT_DIR); the
    # literal "off" disables even under the env var (lane children).
    # Disabled means disabled: no writer thread, no device gathers, no
    # per-tick cost beyond one attribute test.
    checkpoint_dir: str = ""
    checkpoint_interval: float = 2.0
    # Anti-entropy auditor (resilience/antientropy.py): a paced
    # background pass diffing a budgeted window of apiserver objects
    # against engine rows by (uid, rv, phase), classifying divergence
    # (missed-event / double-apply / stale-row / ghost-row) and
    # repairing per row via re-ingest. 0 = off (the default; falls back
    # to KWOK_TPU_AUDIT_INTERVAL); negative = forced off even under the
    # env var (lane children). Off means off: no thread, no LISTs, no
    # per-tick cost.
    audit_interval: float = 0.0
    # Warm-standby high availability (resilience/ha.py): "" = off (the
    # zero-cost default — no elector thread, no client/pump wrapping, no
    # fence check on the hot path). "primary" races to the
    # coordination.k8s.io Lease at startup and serves while renewing it;
    # "standby" runs observe-only (watches+ingests, arms nothing, emits
    # nothing), tails the primary's checkpoint stream, and takes over
    # when the lease expires. Every outward write of an HA engine is
    # fenced on still-holding-the-lease, locally AND server-side.
    ha_role: str = ""
    # holderIdentity + this engine's checkpoint file name under HA
    # (<dir>/<identity>.ckpt.json — the lease names the holder, so the
    # standby knows which file to tail). "" = hostname-pid.
    ha_identity: str = ""
    lease_name: str = "kwok-tpu-engine"
    lease_namespace: str = "kube-system"
    # lease TTL in seconds (whole seconds on the wire); the failure
    # detection budget — a dead primary is unservable for at most this
    # long before the standby may acquire
    lease_duration: float = 2.0
    # renew cadence; 0 = lease_duration / 3 (client-go's shape)
    lease_renew_interval: float = 0.0

    def validate(self) -> None:
        if not (
            self.manage_all_nodes
            or self.manage_nodes_with_annotation_selector
            or self.manage_nodes_with_label_selector
        ):
            # controller.go:98 "no nodes are managed"
            raise ValueError("no nodes are managed")
        if self.lane_procs and self.use_mesh:
            raise ValueError(
                "lane_procs is host-CPU sharding; use_mesh owns device "
                "placement — configure one or the other"
            )
        if self.lane_procs and self.ha_role:
            raise ValueError(
                "lane_procs + ha_role is not supported (the lease fence "
                "cannot span lane processes yet)"
            )


def _rv_of(meta: dict) -> int:
    """metadata.resourceVersion as an int, 0 when absent OR unparseable.
    Tolerant by contract: the hostile-wire tier deliberately delivers
    garbled-but-parseable objects (a flipped digit turns \"1234\" into
    \"12x4\"), and an unguarded int() here killed the ingest path — a
    corrupt rv simply means the object carries no usable identity, the
    same as a missing one."""
    try:
        return int(meta.get("resourceVersion") or 0)
    except (TypeError, ValueError):
        return 0


def _ctr_blob(containers) -> bytes:
    """Container list -> the codec renderer's input format
    ("name\\x1fimage" records joined by \\x1e)."""
    if not containers:
        return b""
    return b"\x1e".join(
        f"{c.get('name') or ''}\x1f{c.get('image') or ''}".encode()
        for c in containers
    )


def _selector_bits(table, extra: tuple[str, ...]) -> dict[str, int]:
    names = list(table.selector_names)
    for e in extra:
        if e not in names:
            names.append(e)
    if len(names) > 32:
        raise ValueError("too many selector bits")
    return {n: i for i, n in enumerate(names)}


@dataclasses.dataclass
class _PendingTick:
    """A dispatched-but-unconsumed tick in the pipelined loop."""

    wire: object  # device array; self-contained (pack_rows wire)
    caps: list  # per-kind capacities AT DISPATCH (grow may change them)
    seq: int  # engine._release_seq at dispatch (stale-mask filtering)
    now: float  # engine time of the dispatch (idle-wake arithmetic)
    mono: float  # monotonic clock at dispatch — idle-wake must anchor
    # here, NOT at consume time, or every timer cycle stretches by the
    # dispatch->consume pipeline lag (measured: ~one tick_interval of
    # heartbeat drift per cycle)
    host_s: float  # host seconds spent in the dispatch half


class _PumpGroup:
    """Several independent native pump connection groups, each with its
    own lock. The old shape — ONE Pump behind ONE global lock — serialized
    every emit batch even though the pump held nconn=4 sockets: two
    executor workers with ready batches queued on the lock instead of the
    wire. Here a sender claims the first free group (non-blocking probe,
    round-robin start so load spreads) and only blocks when every group is
    busy — two concurrent sends ride two different connection groups."""

    def __init__(self, pumps) -> None:
        self._pumps = [(p, threading.Lock()) for p in pumps]
        self._next = 0  # racy round-robin hint; exactness doesn't matter

    def __len__(self) -> int:
        return len(self._pumps)

    def _on_claimed_group(self, fn):
        """Run fn(pump) on the first free connection group (non-blocking
        probe, round-robin start), blocking on the start group only when
        every group is busy — the ONE claim discipline send() and the
        fused emit share."""
        n = len(self._pumps)
        self._next += 1
        start = self._next % n
        for i in range(n):
            p, lock = self._pumps[(start + i) % n]
            if lock.acquire(blocking=False):
                try:
                    # fn blocks on the wire BY DESIGN: this leaf lock
                    # exists to serialize sends on one pump connection
                    # group; nothing else is ever taken under it
                    return fn(p)
                finally:
                    lock.release()
        p, lock = self._pumps[start]
        with lock:
            return fn(p)

    def send(self, reqs):
        return self._on_claimed_group(lambda p: p.send(reqs))

    def emit_spliced(self, native_mod, kw: dict):
        """Fused template render+send (ISSUE 14) on one claimed
        connection group — the same probe-then-block group discipline as
        send(), serializing exactly like any other batch on that group's
        leaf lock. Returns None when the pumps are NOT plain native
        pumps (fault plane, HA fence, test stubs): the caller then
        renders and sends as two calls through send(), so every wrapper
        keeps seeing whole request batches and a fused call can never
        tunnel past a fence."""
        if not isinstance(self._pumps[0][0], native_mod.Pump):
            return None
        return self._on_claimed_group(
            lambda p: native_mod.emit_pods(pump=p, **kw)
        )

    def send_ordered(self, batches):
        """Send several batches back-to-back on ONE group (a strip batch
        must complete before its delete batch); returns their statuses."""
        n = len(self._pumps)
        self._next += 1
        p, lock = self._pumps[self._next % n]
        with lock:
            # kwoklint: disable=blocking-under-lock -- ordered strip-before-delete batches must ride ONE serialized connection group; the leaf lock is the ordering mechanism
            return [p.send(reqs) for reqs in batches]

    def close(self) -> None:
        for p, lock in self._pumps:
            with lock:
                p.close()


class _Kind:
    """Per-resource-kind engine state (device arrays + host bookkeeping)."""

    def __init__(self, table, capacity: int):
        self.table = table
        self.capacity = capacity
        self.state: RowState = new_row_state(capacity)  # host until start()
        self.pool = RowPool(capacity)
        self.buffer = UpdateBuffer()
        self.phase_h = np.zeros(capacity, np.int32)
        self.cond_h = np.zeros(capacity, np.uint32)
        # row -> release generation (engine._release_seq at release time):
        # lets a pipelined consume skip mask bits of rows freed (and maybe
        # re-acquired) after that tick was dispatched
        self.released_at: dict[int, int] = {}

    def grow(self, new_capacity: int) -> None:
        host = to_host(self.state)
        host = grow_state(host, new_capacity)
        self.state = host
        self.capacity = new_capacity
        self.pool.grow(new_capacity)
        extra = new_capacity - self.phase_h.shape[0]
        self.phase_h = np.concatenate([self.phase_h, np.zeros(extra, np.int32)])
        self.cond_h = np.concatenate([self.cond_h, np.zeros(extra, np.uint32)])


class ClusterEngine:
    def __init__(
        self,
        client: KubeClient,
        config: EngineConfig,
        *,
        telemetry: EngineTelemetry | None = None,
    ) -> None:
        config.validate()
        # Fault plane (resilience/faults.py): None unless a spec is
        # configured — the disabled case wraps nothing and costs nothing.
        # Wrapping is idempotent, so lane engines handed an
        # already-wrapped parent client do not double-inject.
        self._faults = resilience_faults.from_config(config.faults)
        if self._faults is not None:
            client = self._faults.wrap_client(client)
            rate = self._faults.spec.rate("clock.jump")
            if rate is not None and rate.p > 0:
                # hostile clock: skew every engine `now` read. Installed
                # as an instance attribute only when the spec asks, so
                # the unfaulted _now stays a two-op method (zero-cost
                # contract).
                self._now = self._skewed_now
        # Warm-standby HA (resilience/ha.py): None unless ha_role is
        # configured — the disabled case wraps nothing and costs nothing.
        # The fence wraps OUTSIDE the fault plane: chaos injects into the
        # real transport, fencing decides whether the write may try at
        # all. Lane children are built with ha_role="" and share the
        # parent's plane (ShardLane.__init__), so there is ONE elector
        # and ONE fence per engine.
        self._ha = resilience_ha.from_config(config)
        if self._ha is not None:
            client = self._ha.wrap_client(client)
        # observe-only gate: True while an HA engine is NOT the leader.
        # The tick loops flush staged ingest writes (mirrors stay
        # current, buffers stay bounded) but never run the transition
        # kernel — nothing arms, nothing fires, nothing emits. The HA
        # plane opens the gate at acquisition/takeover.
        self._ha_hold = self._ha is not None
        self.client = client
        self.config = config
        self.ippool = IPPool(config.cidr)
        # Telemetry: labeled registry + span tracer. A FederatedEngine
        # passes a shard-labeled slice of its shared registry so /metrics
        # exports per-shard series instead of last-writer-wins scalars.
        self.telemetry = telemetry if telemetry is not None else EngineTelemetry()
        self.tracer = self.telemetry.tracer

        self._manage_annotation = parse_selector(
            config.manage_nodes_with_annotation_selector
        )
        self._disregard_annotation = parse_selector(
            config.disregard_status_with_annotation_selector
        )
        self._disregard_label = parse_selector(
            config.disregard_status_with_label_selector
        )

        node_rules = (
            config.node_rules if config.node_rules is not None else default_node_rules()
        )
        pod_rules = (
            config.pod_rules if config.pod_rules is not None else default_pod_rules()
        )
        ntab = compile_rules(node_rules, ResourceKind.NODE)
        ptab = compile_rules(pod_rules, ResourceKind.POD)
        self.node_bits = _selector_bits(ntab, (SEL_MANAGED, SEL_HEARTBEAT))
        self.pod_bits = _selector_bits(ptab, (SEL_MANAGED, SEL_ON_MANAGED_NODE))
        # phase vocabulary comes from the compiled table (Stage docs may
        # extend it past the canonical prefix; compiler.compile_rules)
        self._pod_phases = ptab.space.phases
        self._pod_phase_ids = {
            name: i for i, name in enumerate(ptab.space.phases)
        }

        hb_bit = self.node_bits[SEL_HEARTBEAT]
        self._mesh = None
        if config.use_mesh:
            from kwok_tpu.parallel import make_mesh
            from kwok_tpu.parallel.mesh import pad_to_multiple

            self._mesh = make_mesh()
            cap = pad_to_multiple(config.initial_capacity, self._mesh)
        else:
            cap = config.initial_capacity
        # nodes + pods tick in ONE dispatch: on remote/tunneled devices the
        # per-call latency dominates the row math (ops/tick.MultiTickKernel).
        # Built lazily so engines whose tick a FederatedEngine drives (it
        # owns its own stacked kernels) never allocate device rule tables.
        self._fused_specs = [
            (ntab, config.heartbeat_interval, (), hb_bit),
            (ptab, config.heartbeat_interval, (), -1),
        ]
        self._fused: MultiTickKernel | None = None
        self._owns_tick = True  # False when a FederatedEngine drives us

        # Sharded host lanes (engine/lanes.py) own ALL row state: the
        # parent's kinds then exist only as the structural default for
        # code paths tests drive directly, so they stay at a token
        # capacity instead of duplicating the configured budget in dead
        # host arrays. Resolved here (before allocation); the LaneSet
        # itself is built at the end of __init__, once the shared state
        # it wires into the lanes exists.
        from kwok_tpu.config.types import resolve_drain_shards

        self._n_lanes = resolve_drain_shards(
            config.drain_shards, config.max_drain_shards
        )
        parent_cap = cap if self._n_lanes <= 1 else min(cap, 1024)
        self.nodes = _Kind(ntab, parent_cap)
        self.pods = _Kind(ptab, parent_cap)

        self.node_has: set[str] = set()  # nodesSets (need-heartbeat membership)
        self.pods_by_node: dict[str, set[tuple[str, str]]] = {}

        self._epoch = time.time()
        self.start_time = rfc3339(None)
        # SimpleQueue: lock-free C implementation — the ingest edge hits
        # this once per watch event, where Queue's condition-variable dance
        # showed up in scale profiles
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._watches: dict[str, object] = {}  # kind -> current watch handle
        # kinds whose next reconnect must take the full list+RESYNC path
        # regardless of the thread-local resume revision (resync_streams;
        # guarded by _gen_lock like the rest of the stream bookkeeping)
        self._resync_req: set[str] = set()
        self._threads: list[threading.Thread] = []
        self._running = False
        self._executor: ThreadPoolExecutor | None = None
        # ONE lock for all IP/meta allocation bookkeeping: pool get/use/put,
        # podIP/cni commits, the cni_pending flag, and row-release reads in
        # _pod_deleted. A single lock makes the allocate-vs-delete races
        # tractable; it is NEVER held across provider calls (cni.setup may
        # do netns/network I/O) or any other blocking work.
        self._alloc_lock = threading.Lock()

        # record fast-path gate: disregard selectors and a live CNI
        # provider both force the full-parse path (per-event attribute
        # chases + cni.available() calls showed up at 10k+ events/drain).
        # Evaluated here and again in start() — cni providers load before
        # the engine starts (kwok/cli.py).
        self._record_needs_full_path = (
            self._disregard_annotation is not None
            or self._disregard_label is not None
            or (config.enable_cni and cni.available())
        )
        # Native C++ egress codec: batch-renders heartbeat patch bytes for
        # the O(nodes)-every-30s hot loop. Optional — pure-Python renderers
        # are the fallback; KWOK_TPU_NATIVE=0 disables it explicitly.
        self._codec = None
        if os.environ.get("KWOK_TPU_NATIVE", "1") != "0":
            from kwok_tpu import native

            if native.available():
                self._codec = native
        # AOT-template native emit (ISSUE 14): each compiled pod rule's
        # patch body lowered to a byte template with hole offsets; the
        # emit hot path splices per-row column values in C and ships the
        # batch in the same GIL-free call. KWOK_TPU_NATIVE_EMIT=0 keeps
        # the previous path (per-row meta gather + kwok_render_pod_statuses)
        # at zero cost — one attribute test per emit batch, no column
        # maintenance at ingest.
        self._emit_tpl = None
        if self._codec is not None and os.environ.get(
            "KWOK_TPU_NATIVE_EMIT", "1"
        ) != "0":
            try:
                self._emit_tpl = self._codec.EmitTable(
                    compile_emit_templates(ptab)
                )
            except Exception:
                logger.debug(
                    "emit templates unavailable; generic native emit "
                    "path stays active", exc_info=True,
                )
                self._emit_tpl = None
        #: ingest stages the emit byte columns only when the template
        #: path can consume them
        self._emit_cols = self._emit_tpl is not None
        self._node_ip_b = (config.node_ip or "").encode()
        self._pump_base_b = b""
        self._gone_id = self._pod_phase_ids.get("Gone", -1)
        # Tick-thread batch parser + per-kind resume revisions (written by
        # the tick thread as it parses, read by the watch loops on
        # reconnect; GIL-atomic dict ops)
        self._batch_parser = None
        if self._codec is not None:
            try:
                self._batch_parser = self._codec.EventParser()
            except Exception:
                logger.debug(
                    "native EventParser unavailable; per-event Python "
                    "parse path stays active", exc_info=True,
                )
                self._batch_parser = None
        # Native pre-partitioned routing (ingest.cc ABI 7): the batch
        # parser computes each event's lane and per-lane index runs in the
        # same C call, so the router (or the single-lane drain) stops
        # hashing/dispatching per event in Python. KWOK_TPU_NATIVE_ROUTE=0
        # forces the per-record Python route loop (escape hatch + the
        # ordering oracle's reference arm).
        self._native_route = (
            os.environ.get("KWOK_TPU_NATIVE_ROUTE", "1") != "0"
        )
        self._watch_rv: dict[str, int] = {}
        # monotonic stamp of the last rewind-triggered resync: bounds the
        # full-LIST rate if a pathological store keeps rewinding
        # (_note_rv_rewind)
        self._rv_rewind_at = 0.0
        # monotonic stamp of the last corrupt-input integrity resync:
        # under a garbling storm EVERY batch carries doubt, and an
        # unbounded cut-and-relist loop would LIST-storm the apiserver
        # (_integrity_resync; same bound as the rewind path)
        self._wire_resync_at = 0.0
        # kinds with unserved integrity doubt + the one deferral timer
        # (guarded by _gen_lock like the rest of the stream bookkeeping)
        self._wire_doubt: set[str] = set()
        self._wire_timer: "threading.Timer | None" = None
        # per-kind watch selector opts, captured by _spawn_watch — the
        # anti-entropy auditor lists through the SAME selectors so its
        # apiserver window matches what the engine is supposed to track
        self._watch_opts: dict[str, dict] = {}
        # per-kind watch-stream generation, bumped whenever a stream is
        # known compacted (410): RAW lines still queued from the dead
        # stream belong to the old generation and must not repopulate
        # _watch_rv with pre-compaction revisions (advisor r4: a resume
        # that died before parsing any NEW line would resurrect the stale
        # rv and eat a second 410 + full re-list). The watch thread
        # enqueues ONE "GEN" marker per stream instead of tagging every
        # line (zero per-line cost on the batched ingest path); the tick
        # thread mirrors it into _drain_gen as markers drain.
        self._stream_gen: dict[str, int] = {}
        self._drain_gen: dict[str, int] = {}
        self._gen_lock = threading.Lock()
        self._dropped_jobs = 0  # patch jobs rejected during shutdown
        # monotonic stamp of the last shed-clear stream resync (written
        # by lane drain workers; see lanes._SHED_RESYNC_MIN_S)
        self._shed_resync_at = 0.0
        # readiness for /readyz: set once start() finishes warm-up
        self.ready = False
        # Batched pipelined egress (native/pump.cc): one C++ call sends a
        # whole tick's status patches over pooled keep-alive connections,
        # GIL-free. Plain-HTTP apiservers only (the mock/lab edge); TLS
        # clusters use the executor path below. Built lazily on first emit
        # as a _PumpGroup: several connection groups with per-group locks,
        # so concurrent emit workers never serialize on one global lock.
        self._pump = None
        self._pump_tried = False
        # optional outermost pump wrapper (applied after faults/HA):
        # process-lane children park emit frames in their shared-memory
        # crash-replay slot here. None = zero cost.
        self._pump_wrap = None
        self._pump_groups = max(1, int(os.environ.get(
            "KWOK_TPU_PUMP_GROUPS", "4"
        )))
        self._pump_nconn = 2
        # monotonic wake-up for the idle tick loop; 0 = tick immediately,
        # None = nothing scheduled on device (sleep until an event arrives)
        self._idle_wake: float | None = 0.0
        # bumped on every row release; _PendingTick.seq snapshots it at
        # dispatch so consume can tell which mask bits went stale
        self._release_seq = 0
        self._hb_cond_meta = [
            (name, *_NODE_CONDITION_META.get(name, ("KwokRule", name)))
            for name in NODE_PHASES.conditions
        ]
        # 1-in-N ingest->patch trace sampling (0 disables); the counter is
        # tick-thread-only, so plain int arithmetic is race-free
        self._trace_every = max(0, int(config.trace_sample_every))
        self._trace_n = 0
        # Degraded-mode ledger (kwok_degraded{reason=}; /readyz answers
        # 503 while any reason is active) + the worker watchdog (built in
        # start() unless a FederatedEngine installed a shared one first).
        # Every FRESH degradation edge auto-grabs the apiserver's flight
        # recorder (ISSUE 11): the post-mortem of the requests that led
        # into the transition, saved before the ring overwrites them.
        self._degradation = Degradation(
            self.telemetry.registry, on_set=self._flight_dump_on_degrade
        )
        self._watchdog: Watchdog | None = None
        # Crash-durable restarts (resilience/checkpoint.py). The dir
        # resolves config < KWOK_TPU_CHECKPOINT_DIR (same precedence as
        # the fault plane); "off" disables even under the env var. The
        # Checkpointer/RestoreSession are built in start(); a
        # FederatedEngine names members via _ckpt_name/_worker_suffix
        # before starting them.
        self._ckpt_dir = (
            config.checkpoint_dir
            or os.environ.get("KWOK_TPU_CHECKPOINT_DIR", "")
        ).strip()
        if self._ckpt_dir == "off":
            self._ckpt_dir = ""
        self._ckpt = None  # resilience.checkpoint.Checkpointer | None
        self._restore = None  # resilience.checkpoint.RestoreSession | None
        self._ckpt_name = "engine"
        if self._ha is not None:
            # under HA the lease's holderIdentity IS the checkpoint file
            # name: the standby learns which <identity>.ckpt.json to tail
            # from the lease object itself (resilience/ha.py _tail_peer)
            self._ckpt_name = self._ha.identity
        self._worker_suffix = ""
        # Anti-entropy auditor (resilience/antientropy.py): config < env
        # (same precedence as faults/checkpoint); a NEGATIVE config value
        # forces off even under the env var — lane children use it, ONE
        # auditor per engine (the parent's, over the shared client).
        if config.audit_interval < 0:
            self._audit_interval = 0.0
        elif config.audit_interval > 0:
            self._audit_interval = float(config.audit_interval)
        else:
            env_aud = os.environ.get("KWOK_TPU_AUDIT_INTERVAL", "").strip()
            try:
                self._audit_interval = (
                    float(env_aud) if env_aud and env_aud != "off" else 0.0
                )
            except ValueError:
                logger.warning(
                    "KWOK_TPU_AUDIT_INTERVAL=%r is not a number; "
                    "auditor stays off", env_aud,
                )
                self._audit_interval = 0.0
        self._auditor = None  # resilience.antientropy.AntiEntropyAuditor
        # guards the startup catch-up bookkeeping below (drain workers of
        # several lanes mark their RESYNCs concurrently); level 84 in the
        # kwoklint lock table — a leaf like the other resilience locks
        self._ckpt_lock = threading.Lock()
        # /readyz startup gate: kinds whose first full re-list has not
        # completed yet (None = gate not armed / already finished)
        self._startup_pending: "set[str] | None" = None
        self._startup_lanes: dict[str, set] = {}
        self._startup_flush_wait = False
        self._startup_t0 = 0.0
        # iterations left during which the tick loop is forced awake
        # after a timer refine: in-flight wires dispatched BEFORE the
        # refine still carry fresh-arm deadlines, and each of their
        # consumes overwrites the idle wake — the loop must keep
        # dispatching until a post-refine wire's consume recomputes the
        # wake from the refined state (device-owning thread only)
        self._ckpt_force_ticks = 0
        # Hash-partitioned host lanes: threaded ShardLanes
        # (engine/lanes.py) by default; worker PROCESSES over shared-
        # memory arenas (engine/proclanes.py) behind lane_procs — the
        # GIL escape, default off so the threaded path stays
        # byte-unchanged. Lane children are constructed with
        # drain_shards=1 / lane_procs=False, so neither can recurse.
        self._lanes = None
        self._proc = None
        if self._n_lanes > 1 and config.lane_procs:
            # mesh/HA combinations are refused in EngineConfig.validate
            from kwok_tpu.engine.proclanes import ProcLaneSet

            self._proc = ProcLaneSet(self, self._n_lanes)
        elif self._n_lanes > 1:
            from kwok_tpu.engine.lanes import LaneSet

            self._lanes = LaneSet(self, self._n_lanes)

    @property
    def metrics(self) -> dict:
        """Legacy flat view of the registry (tests, cost model, tooling).
        The authoritative surface is ``telemetry.registry`` — labeled
        families with real histograms — rendered by ``metrics_text()``."""
        return self.telemetry.legacy_dict()

    def metrics_text(self) -> str:
        """Prometheus text exposition of the full labeled registry. With
        process lanes on, every lane child's shm telemetry snapshot is
        merged in (shard-labeled lane families + aggregated engine
        families), so `/metrics` stays one pane of glass — family-and-
        label identical to the threaded exposition."""
        if self._proc is not None:
            return self._proc.merged_metrics_text()
        return self.telemetry.registry.render()

    def process_metrics_text(self) -> str:
        """The process-global error/fault exposition block. With process
        lanes on, lane children's swallowed-error / wire-reject / fault
        counters aggregate into the parent's share instead of silently
        vanishing; otherwise the in-process registry renders as-is
        (empty string when nothing has moved)."""
        if self._proc is not None:
            return self._proc.merged_process_text()
        from kwok_tpu.telemetry.errors import render_nonempty

        return render_nonempty()

    def trace_chrome(self) -> dict:
        """The span ring as a Chrome trace-event document."""
        return self.tracer.chrome_trace()

    def _inc(self, name: str, v=1) -> None:
        self.telemetry.inc(name, v)

    @property
    def degraded(self) -> bool:
        """Degraded mode: shedding load or out of worker restart budget.
        The HTTP server's /readyz answers 503 while this is True (the
        engine is alive — /livez stays 200 — but should not be sent
        load it will drop)."""
        return self._degradation.active

    @property
    def startup_resync_pending(self) -> bool:
        """True while the startup catch-up gate is open: the first full
        re-list (+ checkpoint reconcile, when one is armed) has not
        completed, so /readyz answers 503 with reason startup_resync."""
        return self._running and self._startup_pending is not None

    def _flight_dump_on_degrade(self, reason: str) -> None:
        """Degradation edge hook (Degradation.on_set): snapshot the
        apiserver's flight recorder before its bounded ring overwrites
        the requests that led into the transition. Best-effort and off
        the degrading thread (a daemon grab thread); only armed when a
        dump directory is configured and the master is HTTP."""
        dir_ = (
            self.config.flight_dir
            or os.environ.get("KWOK_TPU_FLIGHT_DIR", "")
        ).strip()
        server = getattr(self.client, "server", "")
        if not dir_ or not str(server).startswith("http"):
            return

        def _grab():
            import urllib.request

            try:
                with urllib.request.urlopen(
                    str(server) + "/debug/flight", timeout=3
                ) as r:
                    data = r.read()
                os.makedirs(dir_, exist_ok=True)
                path = os.path.join(
                    dir_, f"flight-{reason}-{int(time.time() * 1000)}.json"
                )
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
                logger.warning(
                    "degraded (%s): apiserver flight dump saved to %s",
                    reason, path,
                )
            except Exception:
                # the apiserver may BE the reason we degraded; a failed
                # post-mortem grab is expected there, never an error
                swallowed("engine.flight_dump")

        threading.Thread(
            target=_grab, name="kwok-flight-dump", daemon=True
        ).start()

    def _worker_budget_exhausted(self, name: str) -> None:
        """Watchdog callback: a supervised worker crashed past its
        restart budget — the lane topology is now partial."""
        if self._degradation.set("worker_restart_budget"):
            logger.error(
                "engine degraded: worker %s out of restart budget", name
            )

    def _worker_restarted_resync(self, name: str) -> None:
        """Watchdog callback, on the restarted worker's own thread: a
        crashed lane worker can take an in-flight item with it (the crash
        may land mid-get or mid-apply), and routed-rv bookkeeping means
        the watch cache will NOT replay it — only a full list+RESYNC
        provably reconciles the loss (the repair path re-patches any
        object whose server state diverged, the fingerprint echo-drop
        no-ops the rest). So a restart completes by resyncing streams:
        restart-the-thread alone would heal the topology but not the
        data."""
        if not self._running:
            return
        if name.startswith("kwok-emit"):
            # emit crashes are LOSSLESS by construction: the in-flight
            # wire slice survives in the lane's crash-replay slot
            # (ShardLane.emit_loop) and is replayed on this same restart —
            # a full-cluster re-list per emit crash would be pure cost
            return
        if name.startswith("kwok-watch"):
            # a restarted watch loop re-lists by CONSTRUCTION (the fresh
            # loop starts with no resume revision), which re-delivers
            # whatever the pill ate — no explicit resync needed, and
            # cutting the OTHER kind's healthy stream would be pure
            # cost. Re-arm the checkpoint refine instead, so any rows
            # the re-list re-initializes resume their timers.
            self._rearm_restore()
            return
        if name.startswith("kwok-audit"):
            # the auditor holds no engine data a crash could eat — its
            # next pass re-lists its window anyway; a full stream resync
            # per audit crash would be pure cost
            return
        if name.startswith("kwok-ha"):
            # the elector's state machine lives on the HAPlane object and
            # survives the restart; it touches no engine rows
            return
        self.resync_streams()
        # one loss class no re-list can reproduce: a cross-lane XUPD
        # managed-ness fan-out the dead worker ate. The pods' re-delivery
        # echo-drops (their objects never changed) and the node's lane
        # skips the fan-out for already-managed nodes — so re-fan every
        # managed node explicitly. Idempotent: the XUPD apply recomputes
        # each pod's bits from the current shared topology. (Only the
        # sharded pipeline has supervised workers, so the lane router is
        # always present here.)
        if self._lanes is not None:
            while True:
                try:
                    nodes = list(self.node_has)
                    break
                except RuntimeError:  # shared set resized mid-copy
                    time.sleep(0)
            for node in nodes:
                self._lanes.route_pod_updates(node)

    def resync_streams(self) -> None:
        """Force every watch stream through the full list+RESYNC path:
        expire the resume revisions (so the reconnect re-lists instead of
        resuming) and cut the live streams. Safe to call from any thread;
        the per-kind watch threads do the actual re-listing."""
        for kind in list(self._watches):
            self._resync_stream(kind)

    def _resync_stream(self, kind: str) -> None:
        """One kind's share of resync_streams: expire + request + cut."""
        self._expire_stream(kind)
        # _watch_rv only feeds the RAW/native paths' resume — the
        # plain-iterator path resumes from a thread-local rv, so the
        # re-list must be requested explicitly; the watch loop
        # consumes this at reconnect AND right after installing a
        # handle, which closes the reconnect race both ways: a handle
        # installed before this flag is the one we re-read and stop
        # below; one installed after sees the flag at its
        # post-install check
        with self._gen_lock:
            self._resync_req.add(kind)
        w = self._watches.get(kind)
        if w is None:
            return
        try:
            w.stop()
        except Exception:
            # a dying/already-replaced handle: the reconnect path
            # owns recovery either way
            swallowed("resync_stream_stop")

    # --------------------------------------------- hostile-wire quarantine

    def _wire_reject(self, kind: str, reason: str, n: int = 1) -> None:
        """Quarantine corrupt wire input: count it
        (kwok_wire_rejects_total{reason=}) and treat it as integrity
        doubt — the full list+RESYNC re-delivers whatever the corruption
        ate, bounded-rate so a garbling storm cannot LIST-storm the
        apiserver. Stale-rv drops are counted by the caller WITHOUT the
        resync (a regressed revision is provably old news, not doubt)."""
        wire_reject(reason, n)
        self._integrity_resync(kind)

    #: minimum seconds between integrity-resync stream cuts: bounds the
    #: full-LIST rate under a sustained garbling storm (the rewind
    #: path's bound). Doubt inside the window is DEFERRED (one timer),
    #: never dropped — a burst whose last corrupt line lands mid-window
    #: with the stream then going quiet must still get its re-list, or
    #: the eaten event stays missing forever.
    _WIRE_RESYNC_MIN_S = 5.0

    def _integrity_resync(self, kind: str) -> None:
        """Request a full list+RESYNC for ``kind`` after corrupt input.
        The expire+request flags are set unconditionally (idempotent —
        the NEXT reconnect re-lists no matter what); the stream CUT that
        forces that reconnect now is paced: immediate when the rate
        window is open, deferred to one shared timer when not. Callers
        may hold a lane's stage_lock, so the cut — socket I/O — always
        runs off-thread (executor job or the timer)."""
        self._expire_stream(kind)
        with self._gen_lock:
            self._resync_req.add(kind)
            self._wire_doubt.add(kind)
        now = time.monotonic()
        # kwoklint: lockfree=_wire_resync_at -- pacing timestamp only: a racy double-pass fires _integrity_fire twice, and that path is idempotent (the doubt set drains under _gen_lock); a lost store just re-opens the rate window early
        if now - self._wire_resync_at >= self._WIRE_RESYNC_MIN_S:
            self._wire_resync_at = now
            logger.warning(
                "corrupt wire input on %s: scheduling full list+RESYNC",
                kind,
            )
            self._submit(self._integrity_fire)
            return
        with self._gen_lock:
            if self._wire_timer is None:
                wait = max(
                    0.05,
                    self._WIRE_RESYNC_MIN_S - (now - self._wire_resync_at),
                )
                t = threading.Timer(wait, self._integrity_fire)
                t.daemon = True
                self._wire_timer = t
                t.start()

    def _integrity_fire(self) -> None:
        """Serve every pending integrity doubt: cut the doubted kinds'
        live streams so their watch loops reconnect (and re-list, per the
        flags) now. Runs on an executor worker or the deferral timer —
        never under a lane lock."""
        with self._gen_lock:
            timer, self._wire_timer = self._wire_timer, None
            kinds = set(self._wire_doubt)
            self._wire_doubt.clear()
        if timer is not None:
            timer.cancel()  # idempotent; closes the fire-vs-arm race
        if not self._running or not kinds:
            return
        self._wire_resync_at = time.monotonic()
        self._inc("watch_integrity_resyncs_total")
        for kind in kinds:
            w = self._watches.get(kind)
            if w is None:
                continue
            try:
                w.stop()
            except Exception:
                swallowed("integrity_resync_stop")

    # ------------------------------------- crash-durable restarts (ckpt)

    def _rearm_restore(self) -> None:
        """Reload the on-disk checkpoint and arm a refill RestoreSession
        (no readiness gate, TTL-bounded): rows a re-list re-initializes
        after a worker/member restart resume their checkpointed timers.
        Safe from any thread — the session reference swap is atomic, and
        only the device-owning loop ever consumes a session."""
        if self._ckpt is None:
            return
        from kwok_tpu.resilience import checkpoint as ckpt_mod

        data = ckpt_mod.load(self._ckpt_dir, self._ckpt_name)
        if data is None:
            return
        session = ckpt_mod.RestoreSession(
            data["kinds"], gate_ready=False, ttl=30.0
        )
        with self._ckpt_lock:
            # the swap pairs with _close_restore's identity check: the
            # device loop closing an OLD session can never clobber a
            # refill armed concurrently from a restarted worker's thread
            self._restore = session
        logger.info(
            "checkpoint refill armed (%s): %d candidate rows",
            self._ckpt_name, session.remaining,
        )

    def _close_restore(self, r) -> None:
        """Drop a finished/expired restore session — but only if it is
        still THE session: _rearm_restore may have swapped a fresh one in
        from another thread between our read and this close."""
        with self._ckpt_lock:
            if self._restore is r:
                self._restore = None

    def _tracked_rv(self, kind: str, obj: dict) -> int:
        """The revision this engine last ingested for ``obj``'s key, or 0
        when the row is unknown. Row meta is read lock-free: dict get and
        list index are GIL-atomic, and meta rv only ever moves FORWARD
        (events are server-delivered), so a stale read can only make the
        rewind check more conservative, never a false positive."""
        meta = obj.get("metadata") or {}
        name = meta.get("name")
        if not name:
            return 0
        if kind == "pods":
            key = (meta.get("namespace") or "default", name)
        else:
            key = name
        lanes = self._lanes
        if lanes is not None:
            from kwok_tpu.engine.rowpool import shard_of

            e = lanes.lanes[shard_of(key, lanes.n)].engine
        else:
            e = self
        k = e.pods if kind == "pods" else e.nodes
        idx = k.pool.lookup(key)
        if idx is None:
            return 0
        m = k.pool.meta[idx] or {}
        try:
            return int(m.get("rv") or 0)
        except (TypeError, ValueError):
            return 0

    def _note_rv_rewind(self, kind: str, name, listed: int,
                        tracked: int) -> None:
        """A re-listed object carries a revision BELOW the one this
        engine already ingested for it — an object's own rv can never
        legitimately decrease, so this is the store-restore /
        blackout-recovery signature (POST /restore keeps the STORE
        counter monotonic but hands back objects carrying their
        snapshot-time revisions; judging per object instead of against a
        stream high-water mark means deletions and bookmarks can never
        fake it). Treat it as a compaction-plus-rewind: drive the
        existing resync_streams() path so no kind keeps resuming — or
        echo-dropping — against revisions from the old world."""
        now = time.monotonic()
        if now - self._rv_rewind_at < 5.0:
            return  # a rewinding-in-a-loop store must not LIST-storm us
        self._rv_rewind_at = now
        self._inc("rv_rewinds_total")
        logger.warning(
            "rv rewind detected on %s re-list (%s listed at rv %d < "
            "ingested rv %d): store restore/blackout recovery; "
            "resyncing all streams", kind, name, listed, tracked,
        )
        self.resync_streams()

    def _mark_resync(self, kind: str, lane: int = 0) -> None:
        """One full re-list snapshot for ``kind`` has been INGESTED (the
        RESYNC marker drained). Under sharded lanes the marker broadcasts
        to every lane, so the kind only counts once all lanes processed
        theirs — rows listed before the marker are then staged
        everywhere."""
        if self._startup_pending is None:
            return
        with self._ckpt_lock:
            sp = self._startup_pending
            if sp is None or kind not in sp:
                return
            done = self._startup_lanes.setdefault(kind, set())
            done.add(lane)
            need = (
                self._n_lanes
                if (self._lanes is not None or self._proc is not None)
                else 1
            )
            if len(done) >= need:
                sp.discard(kind)

    def _ckpt_gate(self, dispatched: bool, staged: bool) -> None:
        """Finish the startup catch-up gate once every kind's first
        re-list has been ingested AND its staged rows have reached the
        device through one arming dispatch (refine runs after that
        dispatch, so matched rows' timers are already restored when
        ready flips)."""
        sp = self._startup_pending
        if sp is None:
            return
        with self._ckpt_lock:
            empty = not sp
        if not empty:
            return
        if not self._startup_flush_wait:
            self._startup_flush_wait = True
            if staged:
                return  # listed rows not flushed yet: one more dispatch
        elif not (dispatched or not staged):
            return
        self._finish_startup()

    def _finish_startup(self) -> None:
        self._startup_pending = None
        self._startup_lanes = {}
        dt = time.monotonic() - self._startup_t0
        self.telemetry.set_gauge("restart_recovery_seconds", dt)
        r = self._restore
        if r is not None and r.gate_ready:
            if r.remaining:
                # rows re-listed but not ARMED yet (a pod's managed bit
                # can arrive via a later XUPD fan-out): readiness flips
                # now — the re-list is ingested — but the session keeps
                # refining for a bounded tail instead of dropping
                # residues the next dispatch would have matched
                r.gate_ready = False
                r.deadline = time.monotonic() + 10.0
                logger.info(
                    "checkpoint reconcile: %d rows refined, %d stale, "
                    "%d awaiting arming (tail refine continues)",
                    r.matched, r.stale, r.remaining,
                )
            else:
                s = r.finish()
                self._close_restore(r)
                logger.info(
                    "checkpoint reconcile done in %.3fs: %d rows "
                    "refined, %d stale dropped",
                    dt, s["refined"], s["stale"],
                )
        else:
            logger.info("startup re-list caught up in %.3fs", dt)
        self.ready = True

    def _ckpt_service(self, dispatched: bool) -> None:
        """Single-lane checkpoint/restore service — one call per tick
        iteration on the tick thread (the only mutator of pools, buffers,
        and device state here). Sharded engines run LaneSet._ckpt_service
        instead; federation members are serviced by the federated loop."""
        now = self._now()
        r = self._restore
        if r is not None:
            if r.expired() or (not r.gate_ready and not r.remaining):
                s = r.finish()
                self._close_restore(r)
                logger.info(
                    "checkpoint refine closed: %d refined, %d stale",
                    s["refined"], s["stale"],
                )
            else:
                self._ckpt_refine(now)
            # Keep the loop TICKING while a restore session is live AND
            # until the pipeline has flushed every pre-refine wire: the
            # idle wake is recomputed from each consumed wire's dues, and
            # wires dispatched before a refine carry the FRESH-arm
            # deadlines — one of their consumes overwriting the wake put
            # the whole engine to sleep past every resumed delay
            # (restart_soak caught it: ticks_total froze at 1 and both
            # waves fired together at the stale wake). Only a
            # POST-refine wire's consume yields the correct deadline.
            self._ckpt_force_ticks = (
                max(1, int(self.config.pipeline_depth)) + 2
            )
        if self._ckpt_force_ticks > 0:
            self._ckpt_force_ticks -= 1
            self._idle_wake = time.monotonic()
        self._ckpt_gate(
            dispatched,
            staged=bool(
                self.nodes.buffer.pending or self.pods.buffer.pending
            ),
        )
        ck = self._ckpt
        if ck is not None and ck.due():
            ck.submit(self._ckpt_snapshot(now))

    def _ckpt_refine(self, now: float) -> None:
        """Scatter checkpointed timer residues into matching rows. Runs
        AFTER the arming dispatch (the kernel re-armed restored rows with
        fresh delays; this overwrites them with ``now + residue``), and
        skips rows whose init is still staged — their device slots are
        not current until the next flush."""
        from kwok_tpu.ops.updates import refine_flush

        r = self._restore
        for k, kind in ((self.nodes, "nodes"), (self.pods, "pods")):
            if not r.kinds.get(kind):
                continue
            staged = (
                k.buffer.staged_rows() if k.buffer.pending else frozenset()
            )
            # current deadlines: an entry with a delay residue is only
            # consumed once the kernel ARMED its row (finite fire_at) —
            # refining earlier is undone by the arming re-arm itself
            cur_fire = np.asarray(k.state.fire_at)
            idx, fire, hb, gen = r.match_kind(
                kind, k.pool, staged, now,
                phase_h=k.phase_h, fire=cur_fire,
            )
            if idx.size:
                k.state = refine_flush(k.state, idx, fire, hb, gen)

    def _ckpt_snapshot(self, now: float) -> dict:
        """Gather the checkpoint rows (single-lane topology): ONE host
        copy of the timer fields per kind plus a pool/meta walk. Runs on
        the tick thread between dispatches, where the state arrays are
        live outputs."""
        from kwok_tpu.ops.tick import gather_deadlines
        from kwok_tpu.resilience import checkpoint as ckpt_mod

        kinds = {}
        for k, kind in ((self.nodes, "nodes"), (self.pods, "pods")):
            fire, hb, gen = gather_deadlines(k.state)
            staged = (
                k.buffer.staged_rows() if k.buffer.pending else frozenset()
            )
            kinds[kind] = ckpt_mod.gather_rows(
                kind, k.pool, k.phase_h, fire, hb, gen, staged, now
            )
        return {"kinds": kinds}

    # ------------------------------------------------------------------ time

    def _now(self) -> float:
        return time.time() - self._epoch

    def _skewed_now(self) -> float:
        """The clock.jump arm of ``_now`` (installed as an instance
        attribute only when the fault spec configures clock.jump): engine
        time plus the plane's bounded, seeded skew. Everything downstream
        — timers, heartbeats, checkpoint residues — sees the hostile
        clock; the restart-soak oracle proves nothing double-fires."""
        return time.time() - self._epoch + self._faults.clock_skew()

    # ------------------------------------------------------- selector checks

    def _node_need_heartbeat(self, node: dict) -> bool:
        """needHeartbeat = nodeSelectorFunc (controller.go:81-101). Label
        selector is pushed down into the watch, so anything we receive in
        that mode already matches."""
        if self.config.manage_all_nodes:
            return True
        if self._manage_annotation is not None:
            annotations = (node.get("metadata") or {}).get("annotations") or {}
            return self._manage_annotation.matches(annotations)
        if self.config.manage_nodes_with_label_selector:
            return True
        return False

    def _disregard(self, obj: dict) -> bool:
        meta = obj.get("metadata") or {}
        if self._disregard_annotation is not None and (meta.get("annotations") or {}):
            if self._disregard_annotation.matches(meta["annotations"]):
                return True
        if self._disregard_label is not None and (meta.get("labels") or {}):
            if self._disregard_label.matches(meta["labels"]):
                return True
        return False

    # ------------------------------------------------------------- lifecycle

    def start(
        self, run_tick_loop: bool = True, spawn_watches: bool = True
    ) -> None:
        """Start watch ingest + the patch executor, and (by default) the tick
        thread. A FederatedEngine passes run_tick_loop=False: it owns a single
        stacked device state for all member clusters and drives their ingest
        queues + emit paths from one shared tick loop. A process-lane child
        passes spawn_watches=False: its events arrive routed from the parent
        over the shared-memory handoff, never from its own watch streams."""
        self._running = True
        self._owns_tick = run_tick_loop
        # supervision + chaos arm before any worker exists (a
        # FederatedEngine installs ONE shared watchdog across members —
        # with member-failover callbacks — before calling start())
        if self._watchdog is None:
            self._watchdog = Watchdog(
                budget=self.config.worker_restart_budget,
                window=self.config.worker_restart_window,
                on_exhausted=self._worker_budget_exhausted,
                on_restart=self._worker_restarted_resync,
            )
        if self._ha is not None:
            # bind BEFORE any worker: registers the kwok_ha_* families,
            # holds the serve gate (/readyz 503, reason ha_standby, until
            # this engine leads) and plants the server-side fencing claim
            # on the HTTP client's headers
            self._ha.bind(self)
        # Startup catch-up gate: /readyz answers 503 (reason
        # startup_resync) until the first full re-list of BOTH kinds has
        # been ingested — a restarted engine must not report ready while
        # its rows are still empty. Armed before the watch threads spawn;
        # the device-owning loop (tick thread / lane coordinator /
        # federated loop) finishes it.
        # kwoklint: lockfree=_startup_pending,_startup_lanes,_startup_flush_wait,_restore,ready -- armed here on the caller's thread BEFORE any worker spawns (happens-before via Thread.start); afterwards only the single device-owning loop mutates them, and stop()'s ready=False is a plain bool store the loop no longer contends once _running drops
        self._startup_pending = {"nodes", "pods"}
        self._startup_lanes = {}
        self._startup_flush_wait = False
        self._startup_t0 = time.monotonic()
        if self._ckpt_dir and self._proc is None:
            # process lanes: the parent holds no rows — the children
            # checkpoint their shards to lane<i>.ckpt.json themselves
            from kwok_tpu.resilience import checkpoint as ckpt_mod

            self._ckpt = ckpt_mod.Checkpointer(
                self._ckpt_dir, self._ckpt_name,
                self.config.checkpoint_interval, telemetry=self.telemetry,
                degradation=self._degradation,
            )
            data = ckpt_mod.load(self._ckpt_dir, self._ckpt_name)
            if data is not None:
                self._restore = ckpt_mod.RestoreSession(
                    data["kinds"], gate_ready=True
                )
                logger.info(
                    "checkpoint %s: %d rows to reconcile after re-list",
                    self._ckpt.path, self._restore.remaining,
                )
            self._ckpt.start()
        if self._faults is not None:
            self._faults.start()
        # start the sampling profiler from the CALLER's thread (usually
        # main): its SIGTERM crash-dump hook can only install there — the
        # tick thread's own maybe_start() is then an idempotent no-op
        profiling.maybe_start()
        self._record_needs_full_path = (
            self._disregard_annotation is not None
            or self._disregard_label is not None
            or (self.config.enable_cni and cni.available())
        )
        self._executor = ThreadPoolExecutor(
            max_workers=self.config.parallelism, thread_name_prefix="kwok-patch"
        )
        if run_tick_loop:
            if self._proc is not None:
                # process lanes: no device state at the parent — the
                # children own their shards' rows, kernels, and pumps
                self._proc.prepare(self._executor)
            elif self._lanes is not None:
                # sharded pipeline: stacked device state + lane workers;
                # the tick thread below runs the lane coordinator loop
                self._lanes.prepare(self._executor)
            else:
                # move state to device (row-sharded placement under a mesh)
                fused = self._get_fused()
                for k in (self.nodes, self.pods):
                    k.state = fused.place(k.state)
                self._warm_scatters()
                self._warm_tick()

        if spawn_watches:
            node_label_sel = (
                self.config.manage_nodes_with_label_selector or None
            )
            # Each watch thread registers its watch FIRST, then lists and
            # emits a resync marker — so events in the register/list gap
            # are covered, and every re-watch after an error resyncs (the
            # reference's watch-then-list ordering,
            # node_controller.go:121-143, made gap-proof).
            self._spawn_watch("nodes", label_selector=node_label_sel)
            self._spawn_watch("pods", field_selector="spec.nodeName!=")

        if run_tick_loop:
            if self._proc is not None:
                self._proc.start_workers(self._threads)
                loop = self._proc.coordinator_loop
            elif self._lanes is not None:
                self._lanes.start_workers(self._threads)
                loop = self._lanes.tick_loop
            else:
                loop = self._tick_loop
            self._threads.append(spawn_worker(loop, name="kwok-tick"))
        if run_tick_loop and self._ha is not None:
            # the elector (resilience/ha.py): renew-or-acquire loop,
            # supervised so a crashed cycle restarts in place (the fence
            # deadline lives on the plane object and survives — a crash
            # window can only be MORE conservative, never less)
            wd = self._watchdog
            self._threads.append(
                wd.spawn(self._ha.run, name="kwok-ha")
                if wd is not None
                else spawn_worker(self._ha.run, name="kwok-ha")
            )
        if run_tick_loop and self._audit_interval > 0 and (
            self._proc is not None
        ):
            # proc-aware anti-entropy (ISSUE 17): the parent holds no
            # rows to diff, so the audit moves INTO the lane children —
            # each runs the auditor over its own hash shard (the
            # interval rides ProcLaneSet._lane_spec; drift degradation
            # mirrors back through the StatusBank BANK_DRIFT upcall).
            # _audit_interval stays nonzero: it IS the propagated value.
            logger.info(
                "anti-entropy audit runs shard-scoped in the %d lane "
                "children (interval %.3fs); the parent spawns no auditor",
                self._proc.n, self._audit_interval,
            )
        if run_tick_loop and self._audit_interval > 0 and self._proc is None:
            # anti-entropy auditor (resilience/antientropy.py): paced
            # apiserver-vs-rows drift detection + per-row repair, off by
            # default; supervised so a crashed pass restarts in place
            # (the restart needs no stream resync — see
            # _worker_restarted_resync)
            from kwok_tpu.resilience.antientropy import AntiEntropyAuditor

            self._auditor = AntiEntropyAuditor(self, self._audit_interval)
            wd = self._watchdog
            self._threads.append(
                wd.spawn(self._auditor.run, name="kwok-audit")
                if wd is not None
                else spawn_worker(self._auditor.run, name="kwok-audit")
            )
        # ready flips on the device-owning loop once the startup catch-up
        # gate (first full re-list + checkpoint reconcile) completes —
        # NOT here: a restarted engine reporting ready with empty rows is
        # exactly the hole the gate closes. Members (run_tick_loop=False)
        # are finished by the FederatedEngine's loop the same way.

    def _warm_scatters(self) -> None:
        """Pre-compile both ingest-scatter widths with all-pad no-op
        batches so the first real ingest wave never pays jit compilation
        inside the serving path (through a tunneled device one compile is
        seconds, and it would land in the middle of a load burst)."""
        from kwok_tpu.ops.updates import (
            BATCH,
            BATCH_LARGE,
            InitBatch,
            UpdateBatch,
            init_rows,
            update_rows,
        )

        for k in (self.nodes, self.pods):
            cap = k.capacity
            for width in (BATCH, BATCH_LARGE):
                idx = np.full(width, cap, np.int32)  # every lane padded
                k.state = init_rows(k.state, InitBatch(
                    idx=idx,
                    active=np.zeros(width, bool),
                    phase=np.zeros(width, np.int32),
                    cond_bits=np.zeros(width, np.uint32),
                    sel_bits=np.zeros(width, np.uint32),
                    has_deletion=np.zeros(width, bool),
                ))
                k.state = update_rows(k.state, UpdateBatch(
                    idx=idx,
                    sel_bits=np.zeros(width, np.uint32),
                    has_deletion=np.zeros(width, bool),
                ))

    def _warm_tick(self) -> None:
        """Compile the fused tick kernel + its packed D2H wire at startup
        with one all-inactive dispatch. The first real dispatch otherwise
        pays XLA compilation inside the serving path — sampled at ~20% of
        the tick thread's wall during a 50k-pod soak, stalling the serial
        lane exactly when the first load burst lands."""
        fused = self._get_fused()
        (nout, pout), wire = fused((self.nodes.state, self.pods.state), 0.0)
        self.nodes.state = nout.state
        self.pods.state = pout.state
        np.asarray(wire)  # complete (and warm) the wire's D2H path

    def _get_fused(self) -> MultiTickKernel:
        # kwoklint: lockfree=_fused -- memoized on the caller's thread before workers spawn (start()/prepare warm it via _warm_tick); workers only ever read the primed value back
        if self._fused is None:
            steps = max(1, int(self.config.tick_substeps))
            self._fused = MultiTickKernel(
                self._fused_specs, mesh=self._mesh, pack=True, pack_rows=True,
                steps=steps, dt=self.config.tick_interval / steps,
            )
        return self._fused

    def stop(self) -> None:
        self._running = False
        self.ready = False
        # the HA elector is NOT stopped here: a gracefully-stopping
        # leader must keep renewing while the drain below flushes its
        # in-flight emits, or the fence lapses mid-drain (lease TTL <<
        # drain deadline) and the tail writes are silently dropped —
        # unrecoverable for a solo primary. Stopped after the executor
        # drains; the lease then expires and a standby takes over.
        if self._watchdog is not None:
            self._watchdog.close()  # shutdown crashes must not restart
        if self._faults is not None:
            self._faults.stop()  # chaos killer thread down first
        with self._gen_lock:
            timer, self._wire_timer = self._wire_timer, None
        if timer is not None:
            timer.cancel()  # pending integrity-doubt cut dies with us
        if getattr(self, "_profiling", False):
            # short runs stop before tick 102; flush the trace anyway —
            # but only if this thread wins the flag (the tick thread's
            # _maybe_profile may be stopping the same trace right now)
            import jax

            with self._gen_lock:
                flush = getattr(self, "_profiling", False)
                self._profiling = False
            if flush:
                try:
                    jax.profiler.stop_trace()
                    logger.info(
                        "profiler trace written to %s", self.config.profile_dir
                    )
                except Exception:
                    logger.exception("profiler stop failed")
        for w in list(self._watches.values()):
            try:
                w.stop()
            except Exception:
                # expected shutdown race: the watch thread may be tearing
                # the same handle down; counted, not silent
                swallowed("engine.stop_watch")
        self._q.put(None)

        # Join order matters under sharded lanes: the tick thread's
        # shutdown path flushes up to pipeline_depth in-flight device
        # ticks and hands their final emit items (then the sentinels) to
        # the lane emit queues — so it must be waited on FIRST, then the
        # emit workers get real time to drain those queues, before the
        # executor below is torn down under them. Single-lane engines
        # have no kwok-emit* threads and see the old behavior.
        def _join_rank(t):
            if t.name == "kwok-tick":
                return 0
            return 1 if t.name.startswith("kwok-emit") else 2

        for t in sorted(self._threads, key=_join_rank):
            if t.name == "kwok-ha":
                continue  # still renewing; stopped after the drain below
            t.join(timeout=(
                60 if t.name == "kwok-tick"
                else 30 if t.name.startswith("kwok-emit") else 5
            ))
        if self._executor:
            self._executor.shutdown(wait=True)
        if self._ha is not None:
            # every drain write is out (or settled): release the lease
            # plane — renewals cease, the fence lapses on its own, and
            # a paired standby takes over within one lease TTL
            self._ha.stop()
            for t in self._threads:
                if t.name == "kwok-ha":
                    t.join(timeout=5)
        if self._ckpt is not None:
            # the tick loop queued the final snapshot in its finally (it
            # was joined above); this drains the writer and joins it
            self._ckpt.stop()
        # the promised total: every lane shares this telemetry, so under
        # sharding this is the whole engine's tally, not one lane's
        dropped = self.telemetry.dropped_jobs_total
        if dropped:
            logger.warning(
                "%d patch jobs dropped during shutdown "
                "(kwok_dropped_jobs_total)", dropped
            )
        profiling.maybe_dump()
        trace_path = self.config.trace_dump or os.environ.get(
            "KWOK_TPU_TRACE", ""
        )
        if trace_path and self._owns_tick:
            # at-stop dump (a crashed scrape target still leaves evidence);
            # the live view is /debug/trace
            try:
                self.tracer.dump(trace_path)
                logger.info("span trace written to %s", trace_path)
            except Exception:
                logger.exception("span trace dump failed")
        if self._pump is not None:
            self._pump.close()
            self._pump = None
        if self._lanes is not None:
            self._lanes.close()  # lane pump groups (client is shared, ours)
        if self._proc is not None:
            # STOP + join + kill-escalate the lane processes, then unlink
            # every shared-memory arena (clean /dev/shm is gated)
            self._proc.close()
        close = getattr(self.client, "close", None)
        if callable(close):  # release pooled keep-alive connections
            close()

    def _spawn_watch(self, kind: str, **sel) -> None:
        opts = {k: v for k, v in sel.items() if v}
        # the anti-entropy auditor lists through the same selectors, so
        # its apiserver window is exactly the set this engine tracks
        self._watch_opts[kind] = dict(opts)

        def loop():
            # capability only: parsing happens on the tick thread
            # (_drain_apply batch path)
            parser = self._batch_parser
            # client-go reflector semantics: list once, then watch with the
            # last-seen resourceVersion; a broken stream resumes from that
            # revision (server replays the gap — no re-list); a 410
            # Expired/WatchExpired answer falls back to the full
            # list+RESYNC path, which is gap-free by construction
            resume_rv = 0
            too_large_tries = 0
            # shared reconnect policy (resilience/policy.py): exponential
            # backoff + full jitter, reset by a healthy handshake cycle —
            # replaces the old flat time.sleep(5)
            backoff = WATCH_RECONNECT.session()
            # storm pacing state: its OWN backoff session (the handshake
            # path resets `backoff` on every success, and every 410 is
            # followed by a successful rv-less re-list handshake — a
            # success-reset counter would never see two in a row), and a
            # stream-lifetime test instead: expiries separated by a
            # stream that LIVED are normal compaction recovery, expiries
            # after short-lived streams are a storm
            storm_backoff = WATCH_RECONNECT.session()
            consecutive_expiries = 0
            stream_t0 = 0.0
            _STORM_STREAM_S = 5.0

            def expiry_pace():
                # a lone 410 re-lists immediately (the normal compaction
                # recovery must stay fast); a compaction STORM — every
                # short-lived stream ending in another expiry — paces its
                # full re-lists with backoff instead of hot-looping them
                nonlocal consecutive_expiries
                if stream_t0 and (
                    time.monotonic() - stream_t0 >= _STORM_STREAM_S
                ):
                    consecutive_expiries = 0
                    storm_backoff.reset()
                consecutive_expiries += 1
                if consecutive_expiries > 1:
                    delay = storm_backoff.next_delay()
                    if delay:
                        storm_backoff.sleep(
                            delay, lambda: not self._running
                        )

            while self._running:
                try:
                    with self._gen_lock:
                        if kind in self._resync_req:
                            # a worker restart (or other healing event)
                            # demanded a full re-list: the thread-local
                            # resume revision cannot vouch for items a
                            # crashed worker took with it
                            self._resync_req.discard(kind)
                            resume_rv = 0
                    try:
                        # allow_bookmarks: client-go's reflector always
                        # opts in — periodic rv-only events keep a QUIET
                        # stream's resume revision ahead of compaction,
                        # avoiding 410 + full re-list storms at scale
                        w = self.client.watch(
                            kind,
                            **opts,
                            allow_bookmarks=True,
                            **(
                                {"resource_version": resume_rv}
                                if resume_rv
                                else {}
                            ),
                        )
                    except WatchExpired:
                        logger.warning(
                            "watch %s resume rv=%d expired; re-listing",
                            kind, resume_rv,
                        )
                        resume_rv = 0
                        # the tick thread's latest-parsed rv predates the
                        # compaction too: a reconnect that dies before any
                        # NEW line is parsed must not resurrect it and eat
                        # a second 410 + re-list
                        self._expire_stream(kind)
                        expiry_pace()
                        continue
                    except TooLargeResourceVersion as e:
                        # server's store is BEHIND our resume revision
                        # (restart reset its clock): client-go retries the
                        # same revision after the server's hint; we bound
                        # the retries so a permanently-reset server
                        # degrades to the gap-free re-list instead of
                        # wedging the watch loop
                        too_large_tries += 1
                        if too_large_tries >= 3:
                            logger.warning(
                                "watch %s resume rv=%d still ahead of "
                                "server (current %d) after %d tries; "
                                "re-listing",
                                kind, resume_rv, e.current, too_large_tries,
                            )
                            resume_rv = 0
                            too_large_tries = 0
                            continue
                        wait = min(e.retry_after, 5.0)
                        logger.warning(
                            "watch %s resume rv=%d ahead of server "
                            "(current %d); retrying in %.1fs",
                            kind, resume_rv, e.current, wait,
                        )
                        time.sleep(wait)
                        continue
                    too_large_tries = 0
                    self._watches[kind] = w  # replaces any dead handle
                    # resync_streams may have raced this handshake (its
                    # flag landed after our loop-top check but before the
                    # install): an rv-resume here would keep a stream
                    # alive that was ordered to re-list — check again now
                    # that the handle is visible to resync's stop()
                    if resume_rv:
                        with self._gen_lock:
                            forced = kind in self._resync_req
                            if forced:
                                self._resync_req.discard(kind)
                        if forced:
                            w.stop()
                            resume_rv = 0
                            continue
                    # a full handshake succeeded: the next connection
                    # failure backs off from scratch, and the storm pacer
                    # judges the NEXT expiry by how long this stream
                    # lives (expiry_pace resets only after a stream that
                    # lived _STORM_STREAM_S)
                    backoff.reset()
                    stream_t0 = time.monotonic()
                    if not resume_rv:
                        # list AFTER the watch registers: the snapshot +
                        # resync marker covers anything missed before/while
                        # down
                        self._inc("watch_relists_total")
                        objs = self.client.list(kind, **opts)
                        rewind = None
                        for obj in objs:
                            self._q.put((kind, ADDED, obj, time.monotonic()))
                            rv = _rv_of(obj.get("metadata") or {})
                            if rv and rewind is None:
                                tracked = self._tracked_rv(kind, obj)
                                if tracked and rv < tracked:
                                    rewind = (
                                        (obj.get("metadata") or {})
                                        .get("name"), rv, tracked,
                                    )
                        self._q.put((kind, "RESYNC", objs, time.monotonic()))
                        if rewind is not None:
                            # store-restore detection: an object re-listed
                            # BELOW its last-ingested revision resyncs
                            # every stream (per-object, so deletions and
                            # bookmarks can never fake it)
                            self._note_rv_rewind(kind, *rewind)
                    expired = False
                    reader = None
                    if parser is not None:
                        make_reader = getattr(w, "native_reader", None)
                        if callable(make_reader):
                            reader = make_reader()
                    raw_iter = getattr(w, "raw_lines", None)
                    if reader is not None:
                        # fully native ingest edge: C++ reads + de-chunks
                        # the stream and returns PACKED line batches; one
                        # queue item per batch, zero per-line Python
                        # objects. Parsing still happens on the tick
                        # thread (parse_blob); ERROR/expired handling is
                        # identical to the per-line path below.
                        self._q.put((
                            kind, "GEN", self._stream_gen.get(kind, 0),
                            time.monotonic(),
                        ))
                        try:
                            while self._running:
                                out = reader.read_batch(timeout_s=1.0)
                                if out is None:
                                    break
                                buf, off = out
                                if len(off) > 1:
                                    self._q.put((
                                        kind, "RAWB", (buf, off),
                                        time.monotonic(),
                                    ))
                                if reader.error is not None:
                                    expired = b'"code":410' in reader.error
                                    logger.warning(
                                        "watch error event: %.200r",
                                        reader.error,
                                    )
                                    break
                        finally:
                            reader.close()
                        # same resume contract as the per-line path: the
                        # tick thread maintains _watch_rv as it parses
                        resume_rv = self._watch_rv.get(kind, 0)
                    elif parser is not None and callable(raw_iter):
                        # native ingest, BATCHED: this thread only queues
                        # raw lines; the tick thread batch-parses a whole
                        # drain's worth in ONE C call (EventParser.
                        # parse_batch). The per-line parse here used to
                        # ping-pong the GIL with the tick thread — the
                        # dominant parse term of the edge roofline on a
                        # 1-core host. ERROR lines are the one thing
                        # detected here, by prefix (both mock servers and
                        # the real apiserver serialize "type" first).
                        self._q.put((
                            kind, "GEN", self._stream_gen.get(kind, 0),
                            time.monotonic(),
                        ))
                        for line in raw_iter():
                            if line.startswith(b'{"type":"ERROR"'):
                                expired = b'"code":410' in line
                                logger.warning(
                                    "watch error event: %.200r", line
                                )
                                break
                            self._q.put(
                                (kind, "RAW", line, time.monotonic())
                            )
                        # resume revision is maintained by the tick
                        # thread as it parses (self._watch_rv). Lines
                        # still queued unparsed at stream death resume a
                        # little EARLY — the server replays them and the
                        # fingerprint echo-drop makes replays no-ops;
                        # resuming early can only duplicate, never skip.
                        # An ABSENT entry means the tick thread popped it
                        # (drain-side 410 defense): the local fallback
                        # would resurrect the pre-compaction revision —
                        # re-list instead.
                        resume_rv = self._watch_rv.get(kind, 0)
                    else:
                        for ev in w:
                            # tolerant parse: a garbled-but-parseable rv
                            # must not kill (or retry-loop) the stream
                            rv = _rv_of(ev.object.get("metadata") or {})
                            if rv:
                                resume_rv = rv
                            if ev.type == BOOKMARK:
                                self._inc("watch_bookmarks_total")
                                continue  # rv-only heartbeat, no object
                            self._q.put(
                                (kind, ev.type, ev.object, time.monotonic())
                            )
                        expired = getattr(w, "expired", False)
                    if expired:
                        resume_rv = 0
                        self._expire_stream(kind)  # see WatchExpired
                        expiry_pace()  # lone 410: immediate re-list
                        continue
                    if not self._running:
                        return
                except WatchExpired:
                    resume_rv = 0
                    expiry_pace()
                except TooManyRequests as e:
                    # a saturated max-inflight band rejected the list/
                    # handshake: throttle by AT LEAST the server's
                    # Retry-After hint (riding the shared backoff so a
                    # persistently-saturated server still converges to
                    # the policy ceiling) — never a hot retry
                    if not self._running:
                        return
                    delay = max(backoff.next_delay() or 0.0, e.retry_after)
                    self.telemetry.add_throttle(delay)
                    logger.warning(
                        "watch %s throttled by apiserver (429); "
                        "retrying in %.2fs", kind, delay,
                    )
                    backoff.sleep(delay, lambda: not self._running)
                except Exception as e:  # re-watch with backoff
                    if not self._running:
                        return
                    delay = backoff.next_delay() or 0.0
                    logger.warning(
                        "watch %s failed: %s; retrying in %.2fs",
                        kind, e, delay,
                    )
                    backoff.sleep(delay, lambda: not self._running)

        # Supervised (ISSUE 7): a chaos pill async-raised into a watch
        # thread used to end ingest for that kind for good behind a 200
        # readyz. Under supervision the loop restarts in place — and a
        # fresh loop re-lists by construction, so the restart IS the
        # recovery. The suffix disambiguates federation members
        # (kwok-watch-pods-m1) for the watchdog's budget accounting and
        # kwok_fed_member_restarts_total.
        name = f"kwok-watch-{kind}{self._worker_suffix}"
        wd = self._watchdog
        self._threads.append(
            wd.spawn(loop, name=name) if wd is not None
            else spawn_worker(loop, name=name)
        )

    # ---------------------------------------------------------------- ingest

    # cap on buffered raw lines per kind before a mid-drain flush: bounds
    # batch-parse latency and memory without giving up amortization
    _RAW_FLUSH_AT = 8192

    def _drain_apply(
        self, item, raw_buf: dict, route=None, route_shards: int = 0
    ) -> None:
        """Apply one queue item on the tick thread. RAW items (undecoded
        watch lines, the native path) buffer per kind for ONE batched C++
        parse; any non-RAW item for a kind flushes its buffer first so
        per-kind event order is preserved (a RESYNC snapshot must not be
        overtaken by lines that preceded it).

        With ``route`` (the sharded pipeline's router thread), parsed
        events are handed to ``route(kind, type_, obj)`` instead of being
        ingested here — the rv/generation bookkeeping (this engine's watch
        threads read it on reconnect) stays with the caller either way.
        ``route_shards`` is the LaneSet width when ``route`` is its
        per-event router (enables the pre-partitioned batch handoff);
        0 for any other route callable."""
        kind, type_, obj = item[:3]
        if type_ == "RAW":
            buf = raw_buf.setdefault(kind, [])
            buf.append(obj)
            if len(buf) >= self._RAW_FLUSH_AT:
                self._drain_flush_kind(kind, raw_buf, route, route_shards)
            return
        if type_ == "RAWB":
            # a packed native-reader batch (buf, off): one entry, many
            # lines — the flush bound counts LINES, same contract as the
            # per-line path (a reconnect flood of full batches must not
            # buffer an unbounded blob for one giant parse)
            buf = raw_buf.setdefault(kind, [])
            buf.append(obj)
            if sum(len(o) - 1 for _, o in buf) >= self._RAW_FLUSH_AT:
                self._drain_flush_kind(kind, raw_buf, route, route_shards)
            return
        if kind in raw_buf:
            self._drain_flush_kind(kind, raw_buf, route, route_shards)
        if type_ == "GEN":
            # stream boundary: lines after this belong to generation `obj`
            self._drain_gen[kind] = obj
            return
        if route is not None:
            route(kind, type_, obj)
            return
        self._ingest_safe(kind, type_, obj)

    def _drain_flush(
        self, raw_buf: dict, route=None, route_shards: int = 0
    ) -> None:
        for kind in list(raw_buf):
            self._drain_flush_kind(kind, raw_buf, route, route_shards)

    def _expire_stream(self, kind: str) -> None:
        """Mark kind's watch stream compacted: the resume revision AND the
        pre-compaction lines' right to set it die together, atomically —
        a flush committing its batch rv concurrently either lands before
        (and is discarded here) or sees the bumped generation (and does
        not commit). Callers: the kind's watch thread (410 on handshake or
        stream) and the tick thread (stale-ERROR defense)."""
        with self._gen_lock:
            self._watch_rv.pop(kind, None)
            self._stream_gen[kind] = self._stream_gen.get(kind, 0) + 1

    def _drain_error_line(self, kind: str, raw: bytes, gen: int) -> None:
        """Defense in depth (advisor r4): an ERROR event that slipped past
        the watch thread's byte-prefix check (a re-serializing intermediary
        could reorder keys) must not flow into ingest as a bogus record; a
        410 from the CURRENT stream additionally invalidates the kind's
        resume revision now instead of deferring to the next reconnect. A
        stale-generation ERROR (its stream already replaced) must not
        clobber the live stream's state."""
        logger.warning("watch error event in drain: %.200r", raw)
        if b'"code":410' in raw:
            with self._gen_lock:
                if gen == self._stream_gen.get(kind, 0):
                    self._watch_rv.pop(kind, None)
                    self._stream_gen[kind] = gen + 1

    def _commit_rv(self, kind: str, gen: int, rv: int) -> None:
        """Advance the kind's resume revision iff its stream is still the
        live one. One locked commit per flushed batch — atomic against a
        concurrent 410 on the watch thread (_expire_stream), which would
        otherwise race the per-line updates and let pre-compaction
        revisions resurrect."""
        with self._gen_lock:
            if gen == self._stream_gen.get(kind, 0):
                self._watch_rv[kind] = rv

    def _drain_flush_kind(
        self, kind: str, raw_buf: dict, route=None, route_shards: int = 0
    ) -> None:
        entries = raw_buf.pop(kind, None)
        if not entries:
            return
        # one generation per buffer: a GEN marker flushes before updating
        # _drain_gen, so every buffered line shares the marker-time value
        gen = self._drain_gen.get(kind, 0)
        latest_rv = 0
        rv_dead = False
        n_rec = 0
        # Pre-partitioned parse: the C parser also computes each event's
        # lane and per-lane index runs. n_shards = the LaneSet's width
        # when this flush routes to it (the caller declares it via
        # route_shards), 1 when this engine ingests inline (single lane /
        # federation member — the columnar survivor path), 0 for any
        # other route callable (per-record loop, unchanged).
        part_shards = 0
        lanes = self._lanes if self._lanes is not None else self._proc
        if self._native_route:
            if route is None:
                part_shards = 1
            elif (
                route_shards > 1
                and lanes is not None
                and route_shards == lanes.n
            ):
                # a stale width (caller's LaneSet differs from ours) falls
                # back to the per-record walk instead of mis-partitioning
                part_shards = route_shards
        _t = time.perf_counter()
        if any(isinstance(x, tuple) for x in entries):
            # packed native-reader batches: concatenate segments and parse
            # straight from the blob (no per-line objects, no _blob loop).
            # A kind's stream is either packed or per-line per connection
            # (and a GEN marker flushes between streams), so entries never
            # actually mix — but this branch normalizes stray line entries
            # in either position, so a mix could only cost speed, never
            # drop events.
            blob_parts: list[bytes] = []
            offs: list[int] = [0]
            base = 0
            for x in entries:
                if isinstance(x, tuple):
                    b, o = x
                    blob_parts.append(b)
                    offs.extend(v + base for v in o[1:])
                    base += o[-1]
                else:
                    blob_parts.append(x)
                    base += len(x)
                    offs.append(base)
            blob = b"".join(blob_parts)
            lines: "list[bytes] | None" = None

            def fallback_lines():
                return [
                    blob[offs[i]: offs[i + 1]] for i in range(len(offs) - 1)
                ]

            try:
                batch = self._batch_parser.parse_blob(
                    blob, offs, kind=kind, n_shards=part_shards
                )
            except Exception:
                logger.exception(
                    "batch parse failed; falling back to per-line parse"
                )
                batch = None
            if batch is None:
                lines = fallback_lines()
        else:
            lines = entries
            try:
                batch = self._batch_parser.parse_raw_batch(
                    lines, kind=kind, n_shards=part_shards
                )
            except Exception:
                logger.exception(
                    "batch parse failed; falling back to per-line parse"
                )
                batch = None
        if batch is None:
            # silently losing up to a whole drain's lines would let
            # _watch_rv advance past them on the next good batch; parse
            # each line individually instead and skip only the ones that
            # are individually unparseable (they could never be ingested
            # anyway — same information loss as the reference dropping a
            # malformed watch line)
            for line in lines:
                try:
                    rec = self._batch_parser.parse(line)
                except Exception:
                    # quarantine + integrity doubt: the line's rv is
                    # unreadable, so nothing after this point in the
                    # stream can vouch for completeness — stop committing
                    # rvs and let the bounded-rate re-list re-deliver
                    logger.warning("unparseable watch line: %.120r", line)
                    self._wire_reject(kind, "unparseable")
                    latest_rv = 0
                    rv_dead = True
                    continue
                if rec.type == "ERROR":
                    self._drain_error_line(kind, line, gen)
                    latest_rv = 0
                    rv_dead = True  # nothing after a stream error counts
                    continue
                if rec.rv and not rv_dead:
                    latest_rv = rec.rv
                if rec.type == "BOOKMARK":
                    self._inc("watch_bookmarks_total")
                    continue
                n_rec += 1
                if route is not None:
                    route(kind, "REC", rec)
                else:
                    self._ingest_safe(kind, "REC", rec)
            if latest_rv:
                self._commit_rv(kind, gen, latest_rv)
            if n_rec:
                self.telemetry.inc_kind("watch_events_total", kind, n_rec)
            self.telemetry.observe_stage(
                "parse", time.perf_counter() - _t
            )
            return
        self.telemetry.observe_stage("parse", time.perf_counter() - _t)
        if batch.partitioned:
            info = batch.route_info
            if info.first_error < 0 and not info.unrouteable:
                # the steady-state fast path: rv/bookmark bookkeeping is
                # three scalars from the C parse, and routable records are
                # handed over as per-lane zero-copy sub-batches (or
                # ingested columnar right here when this engine IS the
                # lane) — no per-event Python in the serial drain.
                if info.latest_rv:
                    self._commit_rv(kind, gen, info.latest_rv)
                if info.bookmarks:
                    self._inc("watch_bookmarks_total", info.bookmarks)
                if info.routable:
                    self.telemetry.inc_kind(
                        "watch_events_total", kind, info.routable
                    )
                    if part_shards > 1:
                        lanes.route_batch(kind, batch)
                    else:
                        self._ingest_record_batch(
                            kind, batch, batch.lane_idx, 0, info.routable
                        )
                return
            # ERROR / nameless records present (rare): the per-record walk
            # below preserves exact ordering and fallback semantics
            batch.ensure_lists()
        bookmarks = 0
        # hot loop: locals beat repeated attribute/method dispatch at
        # O(10k) records per drain
        rvs = batch.rvs
        type_bytes = batch.type_bytes
        record = batch.record
        if route is not None:
            def ingest_record(kind_, rec_):
                route(kind_, "REC", rec_)
        else:
            ingest_record = self._ingest_record
        for i in range(batch.n):
            tb = type_bytes(i)
            if tb == b"ERROR":
                self._drain_error_line(kind, record(i).raw, gen)
                latest_rv = 0
                rv_dead = True  # nothing after a stream error counts
                continue
            # metadata-depth resourceVersion: the watch loop reads this
            # on reconnect (resuming early only duplicates, never skips)
            rv = rvs[i]
            if rv and not rv_dead:
                latest_rv = rv
            if tb == b"BOOKMARK":
                bookmarks += 1
                continue
            # lazy record: the fingerprint echo-drop in _ingest_record
            # touches only flags/fps/ns/name before dropping the
            # steady-state flood
            n_rec += 1
            try:
                ingest_record(kind, record(i))
            except Exception:
                logger.exception("ingest failed for %s REC", kind)
        if latest_rv:
            self._commit_rv(kind, gen, latest_rv)
        if n_rec:
            self.telemetry.inc_kind("watch_events_total", kind, n_rec)
        if bookmarks:
            self._inc("watch_bookmarks_total", bookmarks)

    def _ingest(self, kind: str, type_: str, obj) -> None:
        if type_ == "REC":
            # counted per-batch by _drain_flush_kind: one lock acquisition
            # per drain instead of one per event on the survivor path
            self._ingest_record(kind, obj)
            return
        self.telemetry.inc_kind("watch_events_total", kind)
        if type_ == "RESYNC":
            self._resync(kind, obj)
            return
        if type_ in ("MODIFIED", DELETED) and self._stale_dict_event(
            kind, obj
        ):
            return
        if kind == "nodes":
            if type_ == DELETED:
                self._node_deleted(obj)
            else:
                self._node_upsert(obj)
        else:
            if type_ == DELETED:
                self._pod_deleted(obj)
            else:
                self._pod_upsert(obj)

    def _stale_dict_event(self, kind: str, obj: dict) -> bool:
        """The dict-path stale-rv tier (plain-iterator clients and the
        record path's full-parse fallback): True when this MODIFIED or
        DELETED event's revision regressed below the row's last ingested
        one — a replay, dropped and counted. A replayed DELETED is the
        nastiest shape: applying it releases a LIVE row (the object was
        deleted and re-created at a higher rv since), so it gets the
        same guard; the re-list prune path carries no rv and is exempt
        by construction. ADDED events are never guarded:
        restore-recovery re-lists deliver legitimately regressed
        revisions that must apply (a replayed ADDED resurrecting a
        deleted object's row is the auditor's ghost-row case)."""
        meta = obj.get("metadata") or {}
        try:
            rv = int(meta.get("resourceVersion") or 0)
        except (TypeError, ValueError):
            return False
        if not rv:
            return False
        name = meta.get("name")
        if not name:
            return False
        key = (meta.get("namespace") or "default", name) \
            if kind == "pods" else name
        k = self.pods if kind == "pods" else self.nodes
        idx = k.pool.lookup(key)
        if idx is None:
            return False
        m = k.pool.meta[idx] or {}
        try:
            seen = int(m.get("rv") or 0)
        except (TypeError, ValueError):
            return False
        if seen and rv < seen:
            wire_reject("stale_rv")
            return True
        return False

    def _ingest_record(self, kind: str, rec) -> None:
        """Native-ingest fast path (tick thread): drop events whose
        fingerprints prove the reference's render->merge->compare would be a
        no-op, fully parse the rest.

        Drop rules (conservative: any mismatch -> full Python path):
        - pod MODIFIED with unchanged meta/spec fingerprints whose status
          fingerprint equals either the last fully-processed state (nothing
          new) or the expectation recorded when the engine emitted its own
          patch (the echo of our write — computePatchData would suppress).
        - node MODIFIED with unchanged meta fingerprint and unchanged
          status-minus-conditions fingerprint: configureNode pins conditions
          before comparing (node_controller.go:377), so heartbeat echoes —
          the steady-state event flood — compare equal by construction.
        """
        type_ = rec.type
        if rec.ok and type_ == "MODIFIED":
            if kind == "pods":
                key = (rec.namespace or "default", rec.name)
                k = self.pods
                idx = k.pool.lookup(key)
                if idx is not None:
                    m = k.pool.meta[idx]
                    # stale-rv tier: a MODIFIED whose revision regressed
                    # below what this row already ingested is provably a
                    # replay (wire.dup/wire.stale, reconnect replays) —
                    # an object's own rv never legitimately decreases.
                    # Dropped BEFORE the echo tiers so old content can
                    # never overwrite newer row meta. ADDED stays exempt:
                    # restore-recovery re-lists legitimately deliver
                    # regressed revisions and must apply.
                    if rec.rv and rec.rv < int(m.get("rv") or 0):
                        wire_reject("stale_rv")
                        return
                    if (
                        not (rec.flags & 2)  # no deletionTimestamp
                        and m.get("fp_meta_sel") == rec.fp_meta_sel
                        and m.get("fp_spec") == rec.fp_spec
                    ):
                        if rec.fp_status == m.get("fp_status_done"):
                            return  # identical to what we already processed
                        if rec.fp_status == m.get("fp_expect") and rec.phase == m.get(
                            "expect_phase"
                        ):
                            # our own patch landed exactly as rendered;
                            # swap in the fresh raw line so any later
                            # slow-path render/suppression sees this status
                            m["fp_status_done"] = rec.fp_status
                            m["phase_str"] = rec.phase
                            m["host_ip"] = rec.host_ip
                            m["status_scalar"] = bool(rec.flags & 16)
                            if self._emit_cols:
                                # keep the emit columns tracking the same
                                # server-side facts the meta mirror does
                                pool = k.pool
                                pool.srv_phase[idx] = (
                                    self._pod_phase_ids.get(rec.phase, -1)
                                )
                                pool.host_b[idx] = (
                                    rec.host_ip.encode()
                                    if rec.host_ip else None
                                )
                                if rec.flags & 16:
                                    pool.eflags[idx] |= EF_SCALAR
                                else:
                                    pool.eflags[idx] &= ~EF_SCALAR
                            m["raw"] = rec.raw
                            if rec.rv:
                                # the checkpoint identity must track our
                                # own echo's revision, or every restore
                                # would see a stale (uid, rv) and re-arm
                                m["rv"] = rec.rv
                            m.pop("obj", None)
                            return
            else:
                k = self.nodes
                idx = k.pool.lookup(rec.name)
                if idx is not None:
                    m = k.pool.meta[idx]
                    if rec.rv and rec.rv < int(m.get("rv") or 0):
                        wire_reject("stale_rv")  # see the pod tier above
                        return
                    if m.get("fp_meta_sel") == rec.fp_meta_sel:
                        if rec.fp_status_nc == m.get("fp_nsc_done"):
                            return  # heartbeat echo / no observable drift
                        if rec.fp_status == m.get("fp_expect"):
                            # echo of our own full status patch; keep the
                            # fresh raw line for later slow-path renders
                            m["fp_nsc_done"] = rec.fp_status_nc
                            m["raw"] = rec.raw
                            if rec.rv:
                                m["rv"] = rec.rv  # see the pod echo path
                            m.pop("obj", None)
                            return
        # record-only row init: upsert without any json.loads when the
        # event cannot trigger repair semantics (new/Pending rows)
        if (
            rec.ok
            and kind == "pods"
            and type_ in (ADDED, "MODIFIED")
            and self._pod_upsert_record(rec)
        ):
            return
        # full path: parse the raw line once and run the normal ingest
        try:
            doc = json.loads(rec.raw)
        except ValueError:
            # JSONDecodeError or UnicodeDecodeError — garbled bytes are
            # frequently not valid UTF-8 either
            # corrupt bytes that slipped past the C scanner: quarantine
            # (counted) and treat as integrity doubt — the bounded-rate
            # full re-list re-delivers whatever this line carried
            logger.warning("bad watch line: %.120r", rec.raw)
            self._wire_reject(kind, "unparseable")
            return
        obj = doc.get("object") or {}
        ev_type = doc.get("type") or type_
        if ev_type == "ERROR":
            logger.warning("watch error event: %s", obj)
            return
        if ev_type not in (ADDED, "MODIFIED", DELETED):
            return
        if ev_type in ("MODIFIED", DELETED) and self._stale_dict_event(
            kind, obj
        ):
            return
        if kind == "pods":
            if ev_type == DELETED:
                self._pod_deleted(obj)
                return
            self._pod_upsert(obj)
            key = (rec.namespace or "default", rec.name)
            idx = self.pods.pool.lookup(key)
            if idx is not None and rec.ok:
                m = self.pods.pool.meta[idx]
                m["fp_meta_sel"] = rec.fp_meta_sel
                m["fp_spec"] = rec.fp_spec
                m["fp_status_done"] = rec.fp_status
        else:
            if ev_type == DELETED:
                self._node_deleted(obj)
                return
            self._node_upsert(obj)
            idx = self.nodes.pool.lookup(rec.name)
            if idx is not None and rec.ok:
                m = self.nodes.pool.meta[idx]
                m["fp_meta_sel"] = rec.fp_meta_sel
                m["fp_nsc_done"] = rec.fp_status_nc

    def _ingest_record_batch(self, kind, batch, idx, lo: int, hi: int) -> int:
        """Apply a contiguous partitioned sub-batch (`idx[lo:hi]` indexes
        into `batch`) — the unit the native router hands a lane, and the
        single-lane inline ingest unit. Pods without full-path needs take
        the COLUMNAR survivor path (_pod_ingest_cols); everything else
        replays the per-record path. Returns events applied."""
        n = hi - lo
        if n <= 0:
            return 0
        if kind == "pods" and not self._record_needs_full_path:
            try:
                self._pod_ingest_cols(batch, idx, lo, hi)
                return n
            except Exception:
                # a columnar bug must not drop a whole window: re-run the
                # per-record path. Rows an earlier flush fully applied
                # drop as echoes (their fingerprints are seeded); a
                # partially-applied flush released its fresh rows before
                # re-raising (flush_cols rollback), so the replay's
                # new-row path stages them from scratch
                logger.exception(
                    "columnar ingest failed; replaying per record"
                )
        record = batch.record
        ing = self._ingest_record
        for i in idx[lo:hi].tolist():
            try:
                ing(kind, record(i))
            except Exception:
                logger.exception("ingest failed for %s REC", kind)
        return n

    def _pod_ingest_cols(self, batch, idx, lo: int, hi: int) -> None:
        """Columnar pod ingest over a partitioned sub-batch: one gather
        per fixed-width column (flags/fingerprints/string offsets), the
        echo drop inlined on plain ints, and survivors accumulated into
        ONE RowPool acquire run + ONE staged array block
        (UpdateBuffer.stage_init_array) + vectorized phase/cond mirror
        writes — the 34µs/pod per-event dict churn (_pod_upsert_record +
        lazy-record attribute machinery) becomes a tight loop over
        buffer slices. Per-key event ORDER is preserved exactly: records
        are scanned in stream order; a record that cannot ride the
        columnar buffer flushes it first whenever its key is already
        buffered, then replays through the per-record path."""
        from kwok_tpu.native import (
            REC_TYPE_ADDED,
            REC_TYPE_MASK,
            REC_TYPE_MODIFIED,
        )

        sub = idx[lo:hi]
        ids = sub.tolist()
        flags_l = batch.flags_a[sub].tolist()
        fp_a = batch.fp_a
        fp_status = fp_a[0][sub].tolist()
        fp_spec = fp_a[2][sub].tolist()
        fp_meta = fp_a[3][sub].tolist()
        rvs_l = batch.rvs_a[sub].tolist()
        # string-field boundaries: 11 spans per record (native _REC_STRINGS
        # order: type, ns, name, node, phase, podIP, hostIP, creation,
        # ctrs, ictrs, trueConditions), gathered as 12 boundary columns
        base = sub.astype(np.int64) * 11
        offs = batch.off_a
        col = [offs[base + j].tolist() for j in range(12)]
        c1, c2, c3, c4, c5, c6, c7, c8, c9, c10, c11 = col[1:12]
        buf = batch.buf
        lines = batch.lines
        k = self.pods
        pool = k.pool
        lookup = pool.lookup
        meta = pool.meta
        phase_ids = self._pod_phase_ids
        node_has = self.node_has
        bit_managed = (
            1 << self.pod_bits[SEL_ON_MANAGED_NODE]
            | 1 << self.pod_bits[SEL_MANAGED]
        )
        record = batch.record
        ing = self._ingest_record
        pending: set = set()
        stale_drops = 0  # regressed-rv replays dropped (counted once)
        cols: list = []  # (key, node, meta, cond_bits, has_del)

        def flush_cols() -> None:
            if not cols:
                return
            if self._trace_every:
                # sampled ingest->patch spans: same 1-in-N cadence as the
                # per-record path, without a per-record counter bump
                start = self._trace_n
                ev = self._trace_every
                self._trace_n = start + len(cols)
                j = (ev - (start % ev)) - 1
                t0 = time.perf_counter()
                while j < len(cols):
                    cols[j][2]["_trace_t0"] = t0
                    j += ev
            grow = self._grow
            acquire = pool.acquire
            pods_by_node = self.pods_by_node
            rows = []
            staged = False
            try:
                stage_ecols = (
                    self._stage_pod_ecols if self._emit_cols else None
                )
                for key, _node, m, _cond, _hd in cols:
                    if pool.full:
                        grow(k)
                    row = acquire(key)
                    meta[row] = m  # fresh rows: replace the dict wholesale
                    if stage_ecols is not None:
                        stage_ecols(pool, row, m)
                    rows.append(row)
                # node->pods index registration BEFORE the node_has reads
                # below — the same publication order _pod_upsert_record
                # keeps against a concurrent cross-lane managed-ness
                # snapshot
                for key, node, _m, _cond, _hd in cols:
                    by = pods_by_node.get(node)
                    if by is None:
                        by = pods_by_node[node] = set()
                    by.add(key)
                idx_arr = np.fromiter(rows, np.int32, len(rows))
                cond_arr = np.fromiter(
                    (c[3] for c in cols), np.uint32, len(cols)
                )
                sel_arr = np.fromiter(
                    (bit_managed if c[1] in node_has else 0 for c in cols),
                    np.uint32, len(cols),
                )
                del_arr = np.fromiter(
                    (c[4] for c in cols), bool, len(cols)
                )
                # host mirrors BEFORE the stage call: written to a freed
                # row they are harmless (the rollback below releases it),
                # while mirrors written AFTER staging would open a window
                # where a crash leaves staged rows with stale mirrors
                # whose seeded fingerprints echo-drop the re-delivery.
                # stage_init_array is the point of no return — the flag
                # flips on the very next bytecode, so the rollback can
                # never release a row whose init is already staged (an
                # orphan init would activate a freed index at flush).
                k.phase_h[idx_arr] = _PENDING
                k.cond_h[idx_arr] = cond_arr
                k.buffer.stage_init_array(
                    idx_arr, _PENDING, cond_arr, sel_arr, del_arr
                )
                staged = True
            except BaseException:
                # rollback: a row acquired here but never staged would
                # otherwise look like an existing Pending row to the
                # per-record replay (_pod_upsert_record takes the update
                # branch, the seeded fingerprints drop the event as an
                # echo) and stay inactive on device forever. Releasing
                # the fresh rows makes the replay's new-row stage_init
                # path the one that runs — the idempotency the replay
                # fallback in _ingest_record_batch relies on. Every key
                # here was absent from the pool at scan time (the
                # eligibility gate requires row is None) and the lane's
                # stage lock is held, so release cannot hit a row some
                # other event owns.
                if not staged:
                    for (key, node, _m, _c, _hd), _row in zip(cols, rows):
                        pool.release(key)
                        by = pods_by_node.get(node)
                        if by is not None:
                            by.discard(key)
                raise
            cols.clear()
            pending.clear()

        for j, i in enumerate(ids):
            f = flags_l[j]
            tcode = f & REC_TYPE_MASK
            s, e = c2[j], c3[j]
            name = buf[s:e].decode("utf-8", "surrogateescape")
            s, e = c1[j], c2[j]
            ns = (
                buf[s:e].decode("utf-8", "surrogateescape")
                if e > s else "default"
            )
            key = (ns or "default", name)
            row = lookup(key)
            if f & 1 and tcode == REC_TYPE_MODIFIED and row is not None and (
                key not in pending
            ):
                # inlined first-tier echo drop (_ingest_record's
                # steady-state MODIFIED case) on plain gathered ints
                m = meta[row]
                if rvs_l[j] and rvs_l[j] < (m.get("rv") or 0):
                    # inlined stale-rv tier (see _ingest_record): a
                    # regressed-revision replay never overwrites the row
                    stale_drops += 1
                    continue
                if (
                    not (f & 2)
                    and m.get("fp_meta_sel") == fp_meta[j]
                    and m.get("fp_spec") == fp_spec[j]
                    and fp_status[j] == m.get("fp_status_done")
                ):
                    continue  # identical to what we already processed
            eligible = (
                f & 1
                and tcode in (REC_TYPE_ADDED, REC_TYPE_MODIFIED)
                and row is None
                and key not in pending
                and c4[j] > c3[j]  # nodeName present
                and c6[j] == c5[j]  # no podIP (alloc-lock path)
            )
            if eligible:
                s, e = c4[j], c5[j]
                phase_s = (
                    buf[s:e].decode("utf-8", "surrogateescape")
                    if e > s else ""
                )
                if phase_ids.get(phase_s or "Pending", _PENDING) != _PENDING:
                    eligible = False  # repair render on first sighting
            if not eligible:
                if key in pending:
                    flush_cols()  # an earlier buffered event for this key
                try:
                    ing("pods", record(i))
                except Exception:
                    logger.exception("ingest failed for pods REC")
                continue
            cond = 0
            s, e = c10[j], c11[j]
            if e > s:
                for t_ in buf[s:e].split(b"\x1f"):
                    tn = t_.decode()
                    if tn in POD_PHASES.conditions:
                        cond |= 1 << POD_PHASES.condition_bit(tn)
            has_del = bool(f & 2)
            s = c6[j]
            e = c7[j]
            host_ip = (
                buf[s:e].decode("utf-8", "surrogateescape") if e > s else ""
            )
            s = c7[j]
            e = c8[j]
            creation = (
                buf[s:e].decode("utf-8", "surrogateescape") if e > s else ""
            )
            node = buf[c3[j]:c4[j]].decode("utf-8", "surrogateescape")
            m = {
                "name": name,
                "namespace": key[0],
                "node": node,
                "disregard": False,
                "raw": lines[i],
                "finalizers": bool(f & 4),
                "has_del": has_del,
                "creation": creation,
                "ctrs": buf[c8[j]:c9[j]],
                "ictrs": buf[c9[j]:c10[j]],
                "rgates": bool(f & 8),
                "phase_str": phase_s,
                "host_ip": host_ip,
                "status_scalar": bool(f & 16),
                "rv": rvs_l[j],  # checkpoint identity; uid lazily from raw
                # fingerprint seeding: the echo of this object's next
                # server state drops without a parse
                "fp_meta_sel": fp_meta[j],
                "fp_spec": fp_spec[j],
                "fp_status_done": fp_status[j],
            }
            pending.add(key)
            cols.append((key, node, m, cond, has_del))
        flush_cols()
        if stale_drops:
            wire_reject("stale_rv", stale_drops)

    def _resync(self, kind: str, objs: list[dict]) -> None:
        """Free rows for objects that vanished while the watch was down."""
        if kind == "nodes":
            seen = {(o.get("metadata") or {}).get("name") for o in objs}
            k = self.nodes
            stale = [key for key in k.pool.keys() if key not in seen]
            for name in stale:
                self._node_deleted({"metadata": {"name": name}})
        else:
            seen = {
                (
                    (o.get("metadata") or {}).get("namespace") or "default",
                    (o.get("metadata") or {}).get("name"),
                )
                for o in objs
            }
            k = self.pods
            stale = [key for key in k.pool.keys() if key not in seen]
            for ns, name in stale:
                self._pod_deleted({"metadata": {"namespace": ns, "name": name}})
        # startup catch-up gate: this kind's first full re-list is now
        # ingested on this lane (lane engines forward their index)
        self._mark_resync(kind)

    def _node_upsert(self, node: dict) -> None:
        meta = node.get("metadata") or {}
        name = meta.get("name")
        if not name:
            return
        # Once a node enters the managed set it stays until Deleted
        # (nodesSets has no removal on Modified, node_controller.go:256-268).
        need_hb = self._node_need_heartbeat(node) or name in self.node_has
        k = self.nodes
        idx = k.pool.lookup(name)
        if not need_hb and idx is None:
            return  # never entered the managed set (WatchNodes Added gate)
        need_lock = not self._disregard(node)
        bits = 0
        if need_hb:
            bits |= 1 << self.node_bits[SEL_HEARTBEAT]
            if need_lock:
                bits |= 1 << self.node_bits[SEL_MANAGED]
        new_row = idx is None
        meta_rv = _rv_of(meta)
        if new_row:
            if k.pool.full:
                self._grow(k)
            idx = k.pool.acquire(name)
            # crash/chaos-pill rollback, same contract as the pod paths:
            # an acquired-but-never-staged row would swallow every later
            # event for this node without ever activating. Mirrors write
            # BEFORE the stage call (harmless on a rolled-back row);
            # stage_init is the point of no return — the flag flips on
            # the very next bytecode, so the rollback can never release a
            # row whose init is already staged (an orphan init would
            # activate a freed index at flush).
            staged = False
            try:
                phase = self._node_phase_from_status(node)
                k.phase_h[idx] = phase
                k.cond_h[idx] = _NODE_READY_BITS
                k.buffer.stage_init(
                    idx, True, phase=phase, cond_bits=_NODE_READY_BITS,
                    sel_bits=bits, has_deletion=False,
                )
                staged = True
            except BaseException:
                if not staged:
                    k.pool.release(name)
                raise
        else:
            k.buffer.stage_update(idx, bits, False)
        m = k.pool.meta[idx]
        m.update(name=name, obj=node)
        m.pop("raw", None)
        # checkpoint identity (resilience/checkpoint.py): rv + uid of the
        # last ingested revision — a restore refines timers only for rows
        # whose (uid, rv) still match
        if meta_rv:
            m["rv"] = meta_rv
        m["uid"] = meta.get("uid") or ""
        # same invalidation as _pod_upsert: dict-path content may differ
        # from what the stored fingerprints describe
        for fp_key in ("fp_meta_sel", "fp_nsc_done", "fp_expect"):
            m.pop(fp_key, None)
        if need_hb and name not in self.node_has:
            self.node_has.add(name)
            self._update_pods_on_node(name)
        # repair: reference re-locks on every event with no-op suppression
        # (LockNode from WatchNodes Added|Modified)
        if need_hb and need_lock and k.phase_h[idx] == _NODE_READY:
            current = node.get("status") or {}
            rendered = render_node_status(
                node, int(k.cond_h[idx]), self.config.node_ip,
                now_rfc3339(), self.start_time,
            )
            if node_status_patch_needed(current, rendered):
                self._submit(self._patch_node_status, name, idx)

    def _node_deleted(self, node: dict) -> None:
        name = (node.get("metadata") or {}).get("name")
        k = self.nodes
        with self._alloc_lock:
            # same discipline as _pod_deleted: the release and its
            # sequence stamp are one atomic step — concurrent deletes
            # must never mint duplicate released_at generations (the
            # stale-mask filter keys on them)
            idx = k.pool.release(name)
            if idx is not None:
                self._release_seq += 1
                k.released_at[idx] = self._release_seq
        if idx is not None:
            k.buffer.stage_init(idx, False)
        if name in self.node_has:
            self.node_has.discard(name)
            self._update_pods_on_node(name)

    def _node_phase_from_status(self, node: dict) -> int:
        for cond in (node.get("status") or {}).get("conditions") or []:
            if cond.get("type") == "Ready" and cond.get("status") == "True":
                return _NODE_READY
        return _NODE_OBSERVED

    def _pod_bits(self, pod_meta: dict) -> int:
        nh = pod_meta.get("node") in self.node_has
        bits = 0
        if nh:
            bits |= 1 << self.pod_bits[SEL_ON_MANAGED_NODE]
            if not pod_meta.get("disregard"):
                bits |= 1 << self.pod_bits[SEL_MANAGED]
        return bits

    def _pod_upsert(self, pod: dict) -> None:
        meta = pod.get("metadata") or {}
        name = meta.get("name")
        ns = meta.get("namespace") or "default"
        if not name:
            return
        key = (ns, name)
        node_name = (pod.get("spec") or {}).get("nodeName") or ""
        if not node_name:
            return
        k = self.pods
        idx = k.pool.lookup(key)
        new_row = idx is None
        if new_row:
            if k.pool.full:
                self._grow(k)
            idx = k.pool.acquire(key)
        m = k.pool.meta[idx]
        spec = pod.get("spec") or {}
        status = pod.get("status") or {}
        m.update(
            name=name,
            namespace=ns,
            node=node_name,
            disregard=self._disregard(pod),
            obj=pod,
            finalizers=bool(meta.get("finalizers")),
            has_del="deletionTimestamp" in meta,
            # uniform derived fields — the batch emit path reads ONLY these
            # (rows initialized from native records have no parsed obj)
            creation=meta.get("creationTimestamp") or "",
            ctrs=_ctr_blob(spec.get("containers")),
            ictrs=_ctr_blob(spec.get("initContainers")),
            rgates=bool(spec.get("readinessGates")),
            phase_str=status.get("phase") or "",
            host_ip=status.get("hostIP") or "",
            status_scalar=set(status) <= _SCALAR_STATUS_KEYS,
            # checkpoint identity: the restore's (uid, rv) match key
            rv=_rv_of(meta),
            uid=meta.get("uid") or "",
        )
        m.pop("raw", None)  # the parsed object supersedes any raw line
        if self._trace_every:
            # kwoklint: lockfree=_trace_n -- sampling cadence counter: a lost racy increment only shifts WHICH event gets traced, never correctness, and the hot ingest path must not take a lock for it
            self._trace_n += 1
            if self._trace_n % self._trace_every == 0:
                # sampled end-to-end trace: the patch ack closes the span
                m["_trace_t0"] = time.perf_counter()
        # fingerprints describe the record-path state; this dict-path event
        # (list/resync or fallback) may carry different content, so stale
        # fingerprints must never justify dropping a later revert-to-known
        # event (the caller re-stores fresh ones when it has them)
        for fp_key in ("fp_status_done", "fp_spec", "fp_meta_sel",
                       "fp_expect", "expect_phase"):
            m.pop(fp_key, None)
        pod_ip = status.get("podIP")
        if pod_ip:
            with self._alloc_lock:
                if self.ippool.contains(pod_ip):
                    # pin pool-range IPs on (re)list so a restarted engine
                    # neither reassigns them nor hands them to another pod
                    self.ippool.use(pod_ip)
                m["podIP"] = pod_ip
                if self.config.enable_cni and cni.available():
                    # a live provider owns every IP it may have assigned —
                    # even ones inside the pool CIDR — so deletion must go
                    # through cni.remove (CNI DEL is idempotent); the pinned
                    # pool slot then simply stays retired
                    m["cni"] = True
        if self._emit_cols:
            self._stage_pod_ecols(k.pool, idx, m)
        has_del = m["has_del"]
        # register in the node->pods index BEFORE reading node_has for the
        # selector bits: under sharded lanes a concurrent node
        # managed-ness flip snapshots this index for its XUPD fan-out —
        # registering first guarantees either the bits see the flip or
        # the fan-out sees the pod (and FIFO-per-key re-stages it); the
        # single-lane engine is single-threaded here, so order is free
        self.pods_by_node.setdefault(node_name, set()).add(key)
        bits = self._pod_bits(m)
        if new_row:
            phase = self._pod_phase_ids.get(
                status.get("phase") or "Pending", _PENDING
            )
            cond = 0
            for c in status.get("conditions") or []:
                t = c.get("type")
                if t in POD_PHASES.conditions and c.get("status") == "True":
                    cond |= 1 << POD_PHASES.condition_bit(t)
            k.buffer.stage_init(
                idx, True, phase=phase, cond_bits=cond, sel_bits=bits,
                has_deletion=has_del,
            )
            k.phase_h[idx] = phase
            k.cond_h[idx] = cond
        else:
            k.buffer.stage_update(idx, bits, has_del)
        # repair path (LockPod on every event + computePatchData
        # suppression); the ingest-side render never enters a CNI
        # provider — rows needing provider I/O defer the whole repair to
        # the executor job, which re-renders and no-op-suppresses itself
        managed = bool(bits >> self.pod_bits[SEL_MANAGED] & 1)
        if managed and not has_del and k.phase_h[idx] != _PENDING:
            rendered, defer = self._render_pod_ingest(idx)
            if defer or (
                rendered is not None
                and pod_status_patch_needed(status, rendered)
            ):
                self._submit(self._patch_pod_status, key, idx)

    def _stage_pod_ecols(self, pool, idx: int, m: dict) -> None:
        """Columnar emit inputs (ISSUE 14): encode this row's emit-time
        byte values ONCE at upsert, so the native emit batch never walks
        the meta dict per dirty row. Callers gate on self._emit_cols and
        call AFTER the meta dict (including any podIP pin) is final."""
        f = EF_RENDER
        if m.get("rgates"):
            f |= EF_RGATES
        if m.get("status_scalar"):
            f |= EF_SCALAR
        pool.eflags[idx] = f
        pool.srv_phase[idx] = self._pod_phase_ids.get(
            m.get("phase_str") or "", -1
        )
        h = m.get("host_ip")
        pool.host_b[idx] = h.encode() if h else None
        c = m.get("creation")
        pool.start_b[idx] = c.encode() if c else b""
        pool.ctr_b[idx] = m.get("ctrs") or b""
        pool.ictr_b[idx] = m.get("ictrs") or b""
        ip = m.get("podIP")
        if ip:
            pool.ip_b[idx] = ip.encode()
        if pool.path_b[idx] is None:
            pool.path_b[idx] = (
                f"/api/v1/namespaces/{_q(m.get('namespace') or 'default')}"
                f"/pods/{_q(m['name'])}"
            ).encode()

    @staticmethod
    def _lazy_obj(m) -> dict | None:
        """Parsed object, lazily decoding the raw watch line for rows whose
        last event was handled on the native record path."""
        obj = m.get("obj")
        if obj is None and "raw" in m:
            try:
                doc = json.loads(m["raw"])
            except ValueError:  # garbled raw line (or bad UTF-8)
                return None
            obj = doc.get("object") or {}
            m["obj"] = obj
        return obj

    def _pod_obj(self, m) -> dict | None:
        return self._lazy_obj(m)

    def _pod_upsert_record(self, rec) -> bool:
        """Row init/update straight from a native record — no json.loads.
        Returns False when the event needs the full path: repair semantics
        on a transitioned row (render + merge against the real status), a
        live CNI provider, or configured disregard selectors (they match on
        labels/annotations the record does not carry)."""
        name = rec.name
        node_name = rec.node_name
        if not name or not node_name:
            return True  # same early-outs as _pod_upsert
        if self._record_needs_full_path:
            return False
        ns = rec.namespace or "default"
        key = (ns, name)
        k = self.pods
        idx = k.pool.lookup(key)
        new_row = idx is None
        if not new_row and int(k.phase_h[idx]) != _PENDING:
            return False  # LockPod repair needs the full object
        if new_row and self._pod_phase_ids.get(
            rec.phase or "Pending", _PENDING
        ) != _PENDING:
            # first sighting already past Pending: the reference would run
            # the repair render+merge against the real status right away
            return False
        flags = rec.flags
        has_del = bool(flags & 2)
        # rollback discipline (same contract as the columnar flush_cols):
        # a crash — or a chaos pill, any BaseException — between acquire
        # and stage_init would leave a row that LOOKS tracked (lookup
        # hits, but inactive and unfingerprinted) so the resync
        # re-delivery takes the update branch and never activates it;
        # releasing makes the re-delivery's new-row path the one that
        # runs. But ONLY un-staged rows may be released: releasing after
        # stage_init would orphan the staged init, activating a freed
        # index at the next buffer flush.
        staged = [not new_row]  # existing rows have nothing to roll back
        try:
            return self._pod_upsert_record_apply(
                rec, k, key, idx, new_row, flags, has_del, name, ns,
                node_name, staged,
            )
        except BaseException:
            if not staged[0]:
                k.pool.release(key)
                by = self.pods_by_node.get(node_name)
                if by is not None:
                    by.discard(key)
            raise

    def _pod_upsert_record_apply(
        self, rec, k, key, idx, new_row, flags, has_del, name, ns,
        node_name, staged,
    ) -> bool:
        """The mutation body of _pod_upsert_record, crash-rollback-wrapped
        by its caller. Fingerprints seed LAST: an event interrupted before
        they land is re-processed on re-delivery, never echo-dropped."""
        if new_row:
            if k.pool.full:
                self._grow(k)
            idx = k.pool.acquire(key)
            # fresh rows replace the pool's empty meta dict wholesale: a
            # dict display is one C-level allocation vs a kwargs update
            m = {
                "name": name,
                "namespace": ns,
                "node": node_name,
                "disregard": False,
                "raw": rec.raw,
                "finalizers": bool(flags & 4),
                "has_del": has_del,
                "creation": rec.creation,
                "ctrs": rec.containers,
                "ictrs": rec.init_containers,
                "rgates": bool(flags & 8),
                "phase_str": rec.phase,
                "host_ip": rec.host_ip,
                "status_scalar": bool(flags & 16),
                "rv": rec.rv,  # checkpoint identity; uid lazily from raw
            }
            k.pool.meta[idx] = m
        else:
            m = k.pool.meta[idx]
            m.update(
                name=name,
                namespace=ns,
                node=node_name,
                disregard=False,
                raw=rec.raw,
                finalizers=bool(flags & 4),
                has_del=has_del,
                creation=rec.creation,
                ctrs=rec.containers,
                ictrs=rec.init_containers,
                rgates=bool(flags & 8),
                phase_str=rec.phase,
                host_ip=rec.host_ip,
                status_scalar=bool(flags & 16),
                rv=rec.rv,
            )
            m.pop("obj", None)  # the raw line supersedes any stale object
            m.pop("uid", None)  # re-extracted from the fresh raw on demand
        if self._trace_every:
            self._trace_n += 1
            if self._trace_n % self._trace_every == 0:
                m["_trace_t0"] = time.perf_counter()
        if rec.pod_ip:
            with self._alloc_lock:
                if self.ippool.contains(rec.pod_ip):
                    self.ippool.use(rec.pod_ip)
                m["podIP"] = rec.pod_ip
        if self._emit_cols:
            self._stage_pod_ecols(k.pool, idx, m)
        by_node = self.pods_by_node.get(node_name)
        if by_node is None:
            by_node = self.pods_by_node[node_name] = set()
        # index registration before the node_has read — see _pod_upsert
        by_node.add(key)
        bits = self._pod_bits(m)
        if new_row:
            phase = self._pod_phase_ids.get(rec.phase or "Pending", _PENDING)
            cond = 0
            if rec.true_conditions:
                for t in rec.true_conditions.split(b"\x1f"):
                    tn = t.decode()
                    if tn in POD_PHASES.conditions:
                        cond |= 1 << POD_PHASES.condition_bit(tn)
            # mirrors BEFORE the stage call (harmless on a rolled-back
            # row); stage_init is the point of no return and the flag
            # flips on the very next bytecode, so the caller's rollback
            # can never release a row whose init is already staged
            k.phase_h[idx] = phase
            k.cond_h[idx] = cond
            k.buffer.stage_init(idx, True, phase, cond, bits, has_del)
            staged[0] = True
        else:
            k.buffer.stage_update(idx, bits, has_del)
        # repair path not needed: rows here are Pending, where the
        # reference always patches on transition, never on repair
        m["fp_meta_sel"] = rec.fp_meta_sel
        m["fp_spec"] = rec.fp_spec
        m["fp_status_done"] = rec.fp_status
        return True

    def _pod_deleted(self, pod: dict) -> None:
        meta = pod.get("metadata") or {}
        key = (meta.get("namespace") or "default", meta.get("name"))
        k = self.pods
        idx = k.pool.lookup(key)
        if idx is None:
            return
        m = k.pool.meta[idx]
        node_name = m.get("node")
        with self._alloc_lock:
            # release inside the lock: a cni setup committing concurrently
            # either lands before (we see m["cni"] and remove) or its
            # liveness check sees the released row and undoes itself
            k.pool.release(key)
            self._release_seq += 1
            k.released_at[idx] = self._release_seq
            cni_owned = bool(m.get("cni"))
            ip = m.get("podIP") or (pod.get("status") or {}).get("podIP")
        if cni_owned:
            # cni.Remove on Deleted (pod_controller.go:329-343). The
            # provider call does netns/network I/O, so it runs as an
            # executor job: the delete event is applied on the ingest path
            # — the tick thread, or under lanes a drain worker HOLDING its
            # stage_lock — which must never block on a provider (kwoklint
            # blocking-under-lock caught the old inline call). CNI DEL is
            # idempotent, so the async hop is safe against replays.
            ns_ = m.get("namespace") or "default"
            name_ = m.get("name") or ""
            uid_ = ((pod.get("metadata") or {}).get("uid")) or ""
            if not self._submit(
                self._cni_remove_job, ns_, name_, uid_, count_drop=False
            ):
                # executor already shut down (stop() racing a final
                # drain): leaking the provider's netns/IP across restarts
                # is worse than one blocking provider call on the closing
                # ingest path — run the teardown inline, like the
                # pre-executor code always did
                self._cni_remove_job(ns_, name_, uid_)
        elif ip and self.ippool.contains(ip):
            # recycle pool-allocated IPs (pod_controller.go:334-337) — also
            # covers the cni-enabled-but-no-provider fallback
            self.ippool.put(ip)
        if node_name and node_name in self.pods_by_node:
            self.pods_by_node[node_name].discard(key)
        k.buffer.stage_init(idx, False)

    def _cni_remove_job(self, ns: str, name: str, uid: str) -> None:
        """Executor half of the Deleted-event CNI teardown (runs inline
        only as _pod_deleted's executor-shutdown fallback)."""
        try:
            if cni.available():
                # kwoklint: disable=blocking-under-lock -- runs on the executor; the only under-lock caller is _pod_deleted's shutdown-time fallback, where leaking the provider netns across restarts is worse than one blocking call on the closing drain path
                cni.remove(ns, name, uid)
        except Exception:
            logger.exception("cni remove failed")

    def _update_pods_on_node(self, node_name: str) -> None:
        """Re-evaluate pods bound to a node whose managed-ness changed
        (LockPodsOnNode wiring, controller.go:113-115)."""
        k = self.pods
        for key in self.pods_by_node.get(node_name, set()):
            idx = k.pool.lookup(key)
            if idx is None:
                continue
            m = k.pool.meta[idx]
            k.buffer.stage_update(idx, self._pod_bits(m), m.get("has_del", False))

    # ------------------------------------------------------------------ grow

    def _grow(self, k: _Kind) -> None:
        new_cap = max(k.capacity * 2, 1024)
        if self._mesh is not None:
            from kwok_tpu.parallel.mesh import pad_to_multiple

            new_cap = pad_to_multiple(new_cap, self._mesh)
        logger.info("growing row pool %d -> %d", k.capacity, new_cap)
        k.grow(new_cap)
        if self._owns_tick:
            k.state = self._get_fused().place(k.state)
        # else: a FederatedEngine drives this engine; it rebuilds its own
        # stacked device state from the new capacities (_maybe_regrow)

    # ------------------------------------------------------------- tick loop

    # Idle backstop: with no staged writes and no device timer pending, the
    # loop still wakes this often (one cheap dispatch) as a safety net.
    _IDLE_MAX = 60.0

    def _tick_loop(self) -> None:
        """Pipelined tick loop (pipeline_depth > 1, the default).

        Each iteration drains ingest, consumes any in-flight ticks whose
        wire has already landed on host, then dispatches the next tick. The
        device round-trip of tick N therefore overlaps the drain/dispatch/
        emit work of ticks N+1..N+depth-1 instead of serializing in front
        of it — on a remote/tunneled TPU this is the difference between the
        engine being RTT-bound and host-bound. Consume order is FIFO, so
        per-object patch order is exactly the synchronous loop's."""
        interval = self.config.tick_interval
        depth = max(1, int(self.config.pipeline_depth))
        from collections import deque

        pending: "deque" = deque()
        profiling.maybe_start()
        try:
            while self._running:
                deadline = time.monotonic() + interval
                # Nothing staged, nothing in flight, and no timer due before
                # the next tick? Sleep until the device-reported deadline
                # (ops/tick.next_due): an idle engine — even at 1M rows —
                # dispatches nothing. Incoming watch events wake the queue
                # and pull the deadline back in.
                if (
                    not pending
                    and self._q.empty()
                    and not self.nodes.buffer.pending
                    and not self.pods.buffer.pending
                ):
                    wake = self._idle_wake
                    if wake is None:
                        deadline = time.monotonic() + self._IDLE_MAX
                    elif wake > deadline:
                        deadline = min(wake, time.monotonic() + self._IDLE_MAX)
                lag_max = 0.0
                drain_s = 0.0
                drain_t0 = 0.0  # perf_counter of the first drained item
                got_event = False
                raw_buf: dict = {}
                # drain ingest until the next tick is due; while ticks are
                # in flight, wait in short slices so a wire landing
                # mid-drain is consumed (and its patches emitted) promptly
                # instead of after the full drain window
                while True:
                    timeout = deadline - time.monotonic()
                    if timeout <= 0:
                        break
                    try:
                        item = self._q.get(
                            timeout=min(timeout, 0.005) if pending
                            else timeout
                        )
                    except queue.Empty:
                        if pending and self._wire_ready(pending[0]):
                            try:
                                self._tick_consume(pending.popleft())
                                self._prune_released(
                                    pending[0].seq if pending
                                    else self._release_seq
                                )
                            except Exception:
                                logger.exception("tick consume failed")
                        continue
                    if item is None:
                        if not self._running:
                            return
                        # explicit wake (the HA plane enqueues one when
                        # it opens the takeover gate on a quiet cluster):
                        # end this drain window now so the dispatch gate
                        # re-reads _idle_wake instead of sleeping out the
                        # old idle deadline
                        deadline = min(deadline, time.monotonic())
                        continue
                    if not got_event:
                        got_event = True
                        # an event arriving during an idle sleep must be
                        # ticked within one normal interval
                        deadline = min(deadline, time.monotonic() + interval)
                    lag_max = max(lag_max, time.monotonic() - item[3])
                    _t = time.perf_counter()
                    if not drain_t0:
                        drain_t0 = _t
                    self._drain_apply(item, raw_buf)
                    drain_s += time.perf_counter() - _t
                    # keep draining whatever is immediately available
                    while True:
                        try:
                            item = self._q.get_nowait()
                        except queue.Empty:
                            break
                        if item is None:
                            if not self._running:
                                return
                            continue
                        lag_max = max(lag_max, time.monotonic() - item[3])
                        _t = time.perf_counter()
                        self._drain_apply(item, raw_buf)
                        drain_s += time.perf_counter() - _t
                if raw_buf:
                    _t = time.perf_counter()
                    if not drain_t0:
                        drain_t0 = _t
                    self._drain_flush(raw_buf)
                    drain_s += time.perf_counter() - _t
                tel = self.telemetry
                if got_event:
                    # enqueue -> processing delay of the slowest event
                    tel.observe_watch_lag(lag_max)
                else:
                    tel.set_gauge("watch_lag_seconds", lag_max)
                tel.set_gauge("ingest_queue_depth", self._q.qsize())
                tel.set_gauge("tick_inflight", len(pending))
                if drain_t0:  # real drain work happened this window
                    tel.observe_stage("drain", drain_s)
                    # one span per drain window: start anchored at the
                    # first drained item, duration = active drain time
                    # (the waits between bursts are excluded)
                    tel.span(
                        "tick.drain", drain_t0, drain_t0 + drain_s, "drain"
                    )
                did_dispatch = False
                try:
                    # consume every tick whose wire has landed (free);
                    # a full pipeline blocks on the oldest, so `depth`
                    # bounds in-flight memory and mirror staleness
                    while pending and (
                        len(pending) >= depth or self._wire_ready(pending[0])
                    ):
                        self._tick_consume(pending.popleft())
                        self._prune_released(
                            pending[0].seq if pending else self._release_seq
                        )
                    # dispatch only when something calls for a tick: an
                    # event drained, writes staged, or a device timer due.
                    # Without this gate the pipeline keeps itself awake
                    # (one tick always in flight -> the idle sleep never
                    # engages) and an idle engine would tick forever.
                    wake = self._idle_wake
                    if (
                        got_event
                        or self.nodes.buffer.pending
                        or self.pods.buffer.pending
                        or (wake is not None and time.monotonic() >= wake)
                    ):
                        did_dispatch = True
                        p = self._tick_dispatch()
                        if p is not None:
                            pending.append(p)
                except Exception:
                    logger.exception("tick failed")
                    # re-arm: staged work may already be flushed into
                    # device state with no event left to trigger the
                    # gate — without a wake the engine would idle-sleep
                    # on it until an unrelated event arrives
                    self._idle_wake = time.monotonic() + interval
                if (
                    self._startup_pending is not None
                    or self._ckpt is not None
                ):
                    # crash-durable restarts: startup reconcile + the
                    # cadenced checkpoint gather (one attribute test per
                    # iteration when disabled — zero-cost contract)
                    try:
                        self._ckpt_service(did_dispatch)
                    except Exception:
                        logger.exception("checkpoint service failed")
        finally:
            # stopping: flush in-flight ticks so patches already computed
            # on device are not dropped (stop() joins us, then shuts the
            # executor down with wait=True)
            while pending:
                try:
                    self._tick_consume(pending.popleft())
                except Exception:
                    logger.exception("final tick consume failed")
            if self._ckpt is not None:
                # SIGTERM graceful drain: the shutdown checkpoint is
                # gathered HERE — after the in-flight ticks flushed, on
                # the thread that owns device state — and queued behind
                # any periodic write still in flight
                try:
                    self._ckpt.final(self._ckpt_snapshot(self._now()))
                except Exception:
                    logger.exception("final checkpoint failed")

    def _ingest_safe(self, kind, type_, obj) -> None:
        """One malformed event must not kill the tick thread."""
        try:
            self._ingest(kind, type_, obj)
        except Exception:
            logger.exception("ingest failed for %s %s", kind, type_)

    def _maybe_profile(self) -> None:
        # the flag transition is claimed under _gen_lock: stop()'s flush
        # path contends with this during shutdown, and whoever flips the
        # flag owns the matching profiler call — a double stop_trace
        # raises inside the tick loop otherwise
        ticks = self.telemetry.ticks_total
        if ticks == 2 and not getattr(self, "_profiling", False):
            import jax

            with self._gen_lock:
                if getattr(self, "_profiling", False):
                    return
                self._profiling = True
            jax.profiler.start_trace(self.config.profile_dir)
            logger.info("profiler trace started -> %s", self.config.profile_dir)
        elif ticks >= 102 and getattr(self, "_profiling", False):
            import jax

            with self._gen_lock:
                if not getattr(self, "_profiling", False):
                    return
                self._profiling = False
            jax.profiler.stop_trace()
            logger.info("profiler trace written to %s", self.config.profile_dir)

    def tick_once(self) -> None:
        """One synchronous engine step: dispatch the fused kernel and
        consume its wire immediately. The pipelined loop (_tick_loop) calls
        the two halves separately with up to pipeline_depth ticks in
        flight; semantics per tick are identical. A sharded engine runs
        the lane coordinator's synchronous step instead (route + drain +
        dispatch + consume with inline emit)."""
        if self._lanes is not None:
            self._lanes.tick_once()
            return
        p = self._tick_dispatch()
        if p is not None:
            self._tick_consume(p)
        self._prune_released(self._release_seq)

    @staticmethod
    def _wire_ready(p) -> bool:
        ready = getattr(p.wire, "is_ready", None)
        return ready() if callable(ready) else True

    def _prune_released(self, min_seq: int) -> None:
        """Drop release-log entries no in-flight tick can still consult
        (everything at or before the oldest pending dispatch's seq)."""
        for k in (self.nodes, self.pods):
            if k.released_at:
                k.released_at = {
                    idx: s for idx, s in k.released_at.items() if s > min_seq
                }

    def _tick_dispatch(self) -> "_PendingTick | None":
        """First half of a tick: flush staged ingest writes and dispatch the
        fused kernel. Returns a _PendingTick whose wire materializes on host
        asynchronously (prefetch), or None when nothing is on device."""
        if self._ha_hold:
            # observe-only standby (resilience/ha.py): flush staged
            # ingest writes so the device mirrors stay current and the
            # UpdateBuffer stays bounded, but never run the transition
            # kernel — nothing arms (fire_at stays +inf), nothing fires,
            # nothing emits. The HA plane flips _ha_hold at takeover and
            # the next dispatch arms everything fresh; the checkpoint
            # refine then overwrites matched rows with resumed residues.
            for k in (self.nodes, self.pods):
                if k.buffer.pending:
                    k.state = k.buffer.flush(k.state)
            tel = self.telemetry
            tel.set_gauge("nodes_managed", len(self.nodes.pool))
            tel.set_gauge("pods_managed", len(self.pods.pool))
            self._idle_wake = None  # no timers can be due while held
            if not self._ha_hold:
                # the takeover gate opened while this hold dispatch ran:
                # the None above would clobber the plane's explicit wake
                # and a quiet cluster would idle-sleep past the whole
                # reconcile window — restore the wake (order safe both
                # ways: the plane flips _ha_hold before writing 0.0)
                self._idle_wake = 0.0
            return None
        if self.config.profile_dir:
            self._maybe_profile()
        t0 = time.perf_counter()
        now = self._now()
        if now >= REBASE_AFTER:
            # f32 engine time: re-zero the epoch before resolution decays
            # (ops/tick.REBASE_AFTER) — long-soak heartbeats stay sub-16ms
            self._epoch += now
            for k in (self.nodes, self.pods):
                k.state = rebase_times(k.state, now)
            self._inc("epoch_rebases_total")
            logger.info("epoch rebase at engine time %.1fs", now)
            now = 0.0
        work = False
        for k in (self.nodes, self.pods):
            if k.buffer.pending:
                k.state = k.buffer.flush(k.state)
                work = True
            elif len(k.pool):
                work = True
        t_flush = time.perf_counter()
        tel = self.telemetry
        tel.set_gauge("nodes_managed", len(self.nodes.pool))
        tel.set_gauge("pods_managed", len(self.pods.pool))
        tel.inc("ticks_total")
        tel.observe_stage("flush", t_flush - t0)
        if not work:
            self._idle_wake = None  # empty engine: sleep until events
            return None
        fused = self._get_fused()
        # with substeps, the scan runs at now_base + i*dt; anchor the
        # LAST substep at wall-now so firing never runs ahead of time
        now_base = now - (fused.steps - 1) * fused.dt
        (nout, pout), wire = fused(
            (self.nodes.state, self.pods.state), now_base
        )
        self.nodes.state = nout.state
        self.pods.state = pout.state
        # the whole tick summary — counters, bit-packed masks, AND the
        # post-tick phase/cond rows (pack_rows) — in ONE self-contained D2H
        # transfer whose copy starts now and overlaps everything until
        # consume. Output states are never read on host, so the next
        # dispatch is free to donate them.
        prefetch(wire)
        t_end = time.perf_counter()
        tel.span("tick.dispatch", t0, t_end, "dispatch")
        return _PendingTick(
            wire=wire,
            caps=[self.nodes.capacity, self.pods.capacity],
            seq=self._release_seq,
            now=now,
            mono=time.monotonic(),
            host_s=t_end - t0,
        )

    def _tick_consume(self, p: "_PendingTick") -> None:
        """Second half of a tick: block until p's wire is on host (free when
        it landed during the pipeline window), refresh the fired rows'
        phase/cond mirrors, and emit patches."""
        t0 = time.perf_counter()
        counters, masks_fn, dues, rows_fn = unpack_wire(
            np.asarray(p.wire), p.caps, rows=True
        )
        t_wire = time.perf_counter()
        nd = float(dues.min())
        self._idle_wake = (
            None if nd == float("inf")
            else p.mono + max(0.0, nd - p.now)
        )
        emit_s = 0.0
        if counters.any():
            now_str = now_rfc3339()
            masks = masks_fn()
            rows = None
            for i, (k, kind) in enumerate(
                ((self.nodes, "nodes"), (self.pods, "pods"))
            ):
                n_trans = int(counters[i])
                n_hb = int(counters[2 + i])
                if n_trans:
                    self.telemetry.inc_kind(
                        "transitions_total", kind, n_trans
                    )
                if not (n_trans or n_hb):
                    continue
                dirty, deleted, hb = masks[i]
                # mask bits of rows released since this tick's dispatch
                # describe the OLD occupant (the row may already belong to
                # a new object): the release path did their teardown.
                # Rows at indices beyond this dispatch's capacity (pool
                # grew mid-window, then the new occupant was released)
                # have no mask bits to clear.
                cap = dirty.shape[0]
                stale = [
                    idx for idx, s in k.released_at.items()
                    if s > p.seq and idx < cap
                ]
                if stale:
                    dirty[stale] = False
                    deleted[stale] = False
                    hb[stale] = False
                if n_trans:
                    idxs = np.nonzero(dirty | deleted)[0]
                    if idxs.size:
                        if rows is None:
                            rows = rows_fn()
                        ph, cb = rows[i]
                        # refresh ONLY the fired rows: rows acquired after
                        # this dispatch already hold their ingest-time
                        # mirror values and are absent from this tick's
                        # state; quiet rows cannot have changed
                        k.phase_h[idxs] = ph[idxs]
                        k.cond_h[idxs] = cb[idxs]
                _t = time.perf_counter()
                self._emit(kind, k, dirty, deleted, hb, now_str)
                _t1 = time.perf_counter()
                emit_s += _t1 - _t
                self.telemetry.span(
                    "tick.emit", _t, _t1, "emit", {"kind": kind}
                )
        elapsed = time.perf_counter() - t0 + p.host_s
        tel = self.telemetry
        tel.observe_tick(elapsed)
        tel.observe_stage("kernel", t_wire - t0)
        if emit_s:
            tel.observe_stage("emit", emit_s)
        tel.span(
            "tick.consume", t0, time.perf_counter(), "consume",
            {"wire_wait_us": round((t_wire - t0) * 1e6, 1)},
        )

    # ------------------------------------------------------------------ emit

    def _submit(self, fn, *args, count_drop: bool = True) -> bool:
        """Run fn on the patch executor (inline in synchronous mode).
        Returns False only when the executor is already shut down —
        callers with must-run teardown work (CNI remove) pass
        count_drop=False and fall back inline, so the job is neither
        dropped nor counted as such (kwok_dropped_jobs_total means
        'rejected AND not run')."""
        if self._executor is None:
            fn(*args)  # synchronous mode (tests may call tick_once directly)
            return True
        try:
            self._executor.submit(self._safe, fn, *args)
            return True
        except RuntimeError:
            if not count_drop:
                return False
            # executor shut down while a tick was still in flight — we
            # are stopping; jobs are dropped, but never silently. One
            # warning + a count (also exported as kwok_dropped_jobs_total;
            # stop() logs the final tally): a flushed tick can carry
            # O(10k) jobs and per-job lines would flood the shutdown log.
            with self._gen_lock:
                self._dropped_jobs += 1
                first = self._dropped_jobs == 1
            self._inc("dropped_jobs_total")
            if first:
                logger.warning(
                    "patch jobs dropped during shutdown (first: %s%r); "
                    "total reported at stop",
                    getattr(fn, "__name__", fn), args[:1],
                )
            return False

    @staticmethod
    def _transient(e: Exception) -> bool:
        """Connection-shaped failures worth retrying (apiserver restart,
        dropped keep-alive, injected blackout). HTTP status errors are
        definitive answers, not transport loss — never retried."""
        import http.client
        import urllib.error

        if isinstance(e, urllib.error.HTTPError):
            return False
        return isinstance(
            e, (ConnectionError, TimeoutError, OSError,
                http.client.HTTPException)
        )

    def _safe(self, fn, *args) -> None:
        # transport-level failures retry with backoff (shared
        # resilience policy, deadline-capped) so an apiserver restart
        # window doesn't silently eat patches: a lost status patch has
        # no retrigger — the server never echoes the expected state, so
        # the engine and the cluster would disagree until an unrelated
        # event touched the object
        backoff = None
        while True:
            try:
                fn(*args)
                return
            except TooManyRequests as e:
                # 429 from a saturated max-inflight band: retryable, but
                # THROTTLED — sleep at least the server's Retry-After
                # hint under the shared policy deadline. Other HTTP
                # statuses stay definitive answers (never retried).
                if not self._running:
                    self._inc("patch_errors_total")
                    return
                if backoff is None:
                    backoff = PATCH_RETRY.session()
                delay = backoff.next_delay()
                if delay is None:  # policy deadline: give up
                    self._inc("patch_errors_total")
                    logger.warning(
                        "patch job still throttled (429) past the retry "
                        "deadline; giving up"
                    )
                    return
                delay = max(delay, e.retry_after)
                self.telemetry.add_throttle(delay)
                backoff.sleep(delay, lambda: not self._running)
            except Exception as e:
                if not (self._running and self._transient(e)):
                    self._inc("patch_errors_total")
                    logger.exception("patch job failed")
                    return
                if backoff is None:
                    backoff = PATCH_RETRY.session()
                delay = backoff.next_delay()
                if delay is None:  # policy deadline: give up
                    self._inc("patch_errors_total")
                    logger.exception("patch job failed after retries")
                    return
                backoff.sleep(delay, lambda: not self._running)

    def _get_pump(self):
        """Native pump bound to the client's plain-HTTP endpoint, or None
        (TLS/in-process clients keep the executor path)."""
        # kwoklint: lockfree=_pump,_pump_tried,_pump_base,_pump_base_b -- memoized via _pump_tried before any contending worker runs (LaneSet.prepare primes it; see the blocking-under-lock note below); stop() clears _pump only after every worker is joined
        if self._pump_tried:
            return self._pump
        self._pump_tried = True
        if self._codec is None:
            return None
        server = getattr(self.client, "server", "")
        if not isinstance(server, str) or not server.startswith("http://"):
            return None
        host = getattr(self.client, "_host", None)
        port = getattr(self.client, "_port", None)
        base = getattr(self.client, "_base_path", "") or ""
        if not host or not port:
            return None
        token = getattr(self.client, "token", None)
        extra = f"Authorization: Bearer {token}\r\n" if token else ""
        if self._ha is not None:
            # every pump request carries the fencing claim: the servers
            # validate it at processing time under the store lock, so a
            # revived zombie's in-flight batches die server-side even
            # when they slipped past FencedPump before the pause
            extra += self._ha.fence_header_line()
        try:
            pumps = [
                # kwoklint: disable=blocking-under-lock -- construction is memoized via _pump_tried: lane emit workers (the only under-lock callers) are primed by LaneSet.prepare before any worker starts; all other callers run on the lock-free tick thread or executor
                self._codec.Pump(
                    host, int(port), nconn=self._pump_nconn,
                    header_extra=extra,
                )
                for _ in range(self._pump_groups)
            ]
            if self._faults is not None:
                # chaos: the fault plane reproduces pump.cc's failure
                # contract (drop / short write / delay) on demand
                pumps = [self._faults.wrap_pump(p) for p in pumps]
            if self._ha is not None:
                # fence OUTSIDE the fault plane: a write the fence drops
                # must never reach the chaos layer, let alone the wire
                pumps = [self._ha.wrap_pump(p) for p in pumps]
            if self._pump_wrap is not None:
                # outermost: the process-lane emit crash-replay slot
                # must see exactly the frames that go on the wire
                pumps = [self._pump_wrap(p) for p in pumps]
            self._pump = _PumpGroup(pumps)
            self._pump_base = base
            self._pump_base_b = base.encode()
        except Exception:
            logger.exception("native pump unavailable; using executor egress")
            self._pump = None
        return self._pump

    def _node_path_b(self, pool, idx: int, name: str) -> bytes:
        """URL-quoted node path bytes, cached as the pool's path column
        on first emit (node upserts are too rare to stage eagerly)."""
        pb = pool.path_b[idx]
        if pb is None:
            pb = pool.path_b[idx] = f"/api/v1/nodes/{_q(name)}".encode()
        return pb

    def _emit_nodes_native(self, k, idxs: list[int]) -> None:
        """Render node status patches in Python (cold-ish: node transitions
        are rare relative to pods) but ship them in ONE pump batch instead
        of a round-trip per node."""
        now = now_rfc3339()
        base = self._pump_base_b
        reqs, sent = [], []
        for idx in idxs:
            name = k.pool.key_of(idx)
            m = k.pool.meta[idx]
            if name is None or not m:
                continue
            node = self._lazy_obj(m) or {}
            current = node.get("status") or {}
            rendered = render_node_status(
                node, int(k.cond_h[idx]), self.config.node_ip, now,
                self.start_time,
            )
            if not node_status_patch_needed(current, rendered):
                continue
            body = json.dumps({"status": rendered}, separators=(",", ":")).encode()
            reqs.append((
                "PATCH",
                base + self._node_path_b(k.pool, idx, name) + b"/status",
                body,
                "application/strategic-merge-patch+json",
            ))
            # bare/scalar-only current status: the merged echo will be
            # exactly this document — let ingest drop it by fingerprint
            sent.append((idx, m if set(current) <= _SCALAR_STATUS_KEYS else None))
        if reqs:
            fps = self._codec.fingerprint_statuses([r[2] for r in reqs])
            if fps is not None:
                for (_idx, m2), fp in zip(sent, fps):
                    if m2 is not None:
                        m2["fp_expect"] = int(fp)
            self._submit(self._pump_send, reqs, [i for i, _ in sent], "nodes")

    def _emit(self, kind, k, dirty, deleted, hb, now_str) -> None:
        if kind == "nodes":
            node_rows = [int(i) for i in np.nonzero(dirty)[0]]
            if len(node_rows) > 1 and self._get_pump() is not None:
                self._emit_nodes_native(k, node_rows)
                node_rows = []
            for idx in node_rows:
                name = k.pool.key_of(idx)
                if name is not None:
                    self._submit(self._patch_node_status, name, idx)
            hb_rows = [
                (name, int(idx))
                for idx in np.nonzero(hb)[0]
                if (name := k.pool.key_of(int(idx))) is not None
            ]
            if self._codec is not None and len(hb_rows) > 1:
                self._emit_heartbeats_native(k, hb_rows, now_str)
            else:
                for name, idx in hb_rows:
                    self._submit(self._heartbeat_node, name, idx, now_str)
        else:
            dirty_rows = [int(i) for i in np.nonzero(dirty)[0]]
            if len(dirty_rows) > 1 and self._get_pump() is not None:
                dirty_rows = self._emit_pods_native(k, dirty_rows)
            for idx in dirty_rows:
                key = k.pool.key_of(idx)
                if key is not None:
                    self._submit(self._patch_pod_status, key, idx)
            del_rows = [
                (key, int(idx))
                for idx in np.nonzero(deleted)[0]
                if (key := k.pool.key_of(int(idx))) is not None
            ]
            if len(del_rows) > 1 and self._get_pump() is not None:
                self._emit_deletes_native(k, del_rows)
            else:
                for key, idx in del_rows:
                    self._submit(self._delete_pod, key, idx)

    _POD_KIND = {"Running": 0, "Succeeded": 1, "Failed": 2}

    def _emit_pods_native(self, k, idxs: list[int]) -> list[int]:
        """Batch path for transition-driven pod patches. With compiled
        emit templates (the default) the whole batch is a columnar
        gather + ONE fused C render+send call (_emit_pods_tpl); with
        KWOK_TPU_NATIVE_EMIT=0 (or no templates) the previous shape —
        per-row meta gather + codec.render_pod_statuses + pump send —
        runs unchanged. Returns the rows that must take the Python path
        (readiness gates, CNI, suppression checks, missing state). Runs
        on the tick thread — the only row mutator — so rows cannot
        vanish mid-batch."""
        if self._emit_tpl is not None:
            return self._emit_pods_tpl(k, idxs)
        slow: list[int] = []
        sent_idx: list[int] = []
        kinds_l: list[int] = []
        conds_l: list[int] = []
        phases: list[bytes] = []
        hosts: list[bytes] = []
        ips: list[bytes] = []
        starts: list[bytes] = []
        ctrs: list[bytes] = []
        ictrs: list[bytes] = []
        paths: list[str] = []
        phase_names: list[str] = []
        cni_live = self.config.enable_cni and cni.available()
        base = self._pump_base
        node_ip = self.config.node_ip
        pod_kind = self._POD_KIND
        pool_key_of = k.pool.key_of
        meta = k.pool.meta
        phase_h = k.phase_h
        cond_h = k.cond_h
        all_phases = self._pod_phases
        for idx in idxs:
            key = pool_key_of(idx)
            m = meta[idx]
            if key is None or not m or ("obj" not in m and "raw" not in m):
                continue
            phase_name = all_phases[int(phase_h[idx])]
            if phase_name == "Gone":
                continue
            if cni_live or m.get("rgates"):
                slow.append(idx)
                continue
            if m.get("phase_str") == phase_name:
                # target phase already on the server: the reference would
                # run the full merge/no-op check — keep that path exact
                slow.append(idx)
                continue
            ip = m.get("podIP")
            if not ip:
                with self._alloc_lock:
                    ip = m.get("podIP")
                    if not ip:
                        ip = self.ippool.get()
                        m["podIP"] = ip
            ns, name = key
            sent_idx.append(idx)
            kinds_l.append(pod_kind.get(phase_name, 0))
            conds_l.append(int(cond_h[idx]))
            phases.append(phase_name.encode())
            phase_names.append(phase_name)
            hosts.append((m.get("host_ip") or node_ip).encode())
            ips.append(ip.encode())
            starts.append((m.get("creation") or now_rfc3339()).encode())
            ctrs.append(m.get("ctrs") or b"")
            ictrs.append(m.get("ictrs") or b"")
            paths.append(
                f"{base}/api/v1/namespaces/{_q(ns)}/pods/"
                f"{_q(name)}/status"
            )
        if not sent_idx:
            return slow
        bodies = self._codec.render_pod_statuses(
            np.array(kinds_l, np.uint8),
            np.array(conds_l, np.uint32),
            phases,
            list(POD_PHASES.conditions[:3]),
            hosts,
            ips,
            starts,
            ctrs,
            ictrs,
        )
        if bodies is None:
            return slow + sent_idx
        # Record the expected post-patch status fingerprint so the ingest
        # fast path can drop the echo of this very patch. Valid only when
        # the current status has scalar-replace keys exclusively — then the
        # server's strategic merge yields exactly the rendered document.
        fps = self._codec.fingerprint_statuses(bodies)
        if fps is not None:
            for idx, pn, fp in zip(sent_idx, phase_names, fps):
                m = meta[idx]
                if m.get("status_scalar"):
                    m["fp_expect"] = int(fp)
                    m["expect_phase"] = pn
        ctype = "application/strategic-merge-patch+json"
        reqs = [
            ("PATCH", path, body, ctype)
            for path, body in zip(paths, bodies)
        ]
        self._submit(self._pump_send, reqs, sent_idx, "pods")
        return slow

    _EMIT_CTYPE = "application/strategic-merge-patch+json"

    def _emit_pods_tpl(self, k, idxs: list[int]) -> list[int]:
        """The AOT-template emit gather (ISSUE 14): classify rows off the
        staged byte columns — no meta dict walks, no per-row .encode(),
        no f-string paths, `now` hoisted per batch — and hand ONE job to
        the executor whose body is a single render+send C call. Same
        slow-path semantics as the legacy gather: CNI rows, readiness
        gates, and already-at-phase rows (the no-op merge check) keep
        falling back to edge/render.py via _patch_pod_status."""
        if self.config.enable_cni and cni.available():
            return list(idxs)  # provider I/O: every row takes the slow path
        pool = k.pool
        ef = pool.eflags
        srv = pool.srv_phase
        ipc = pool.ip_b
        pathc = pool.path_b
        tgt = k.phase_h[idxs].tolist()
        tpl_of = self._emit_tpl.phase_tpl
        n_tpl = len(tpl_of)
        gone = self._gone_id
        slow: list[int] = []
        sel: list[int] = []
        tpls: list[int] = []
        # the classify loop appends to the fewest lists it can; every
        # column gather below runs as a tight comprehension over the
        # selection (roughly half the interpreter cost of growing a
        # dozen lists inside this loop — this gather IS emit_render_us)
        for pos, idx in enumerate(idxs):
            f = ef[idx]
            if not f & EF_RENDER:
                # released row / no renderable state: skip, exactly like
                # the legacy gather's key/meta guard
                continue
            pid = tgt[pos]
            if pid == gone:
                continue
            if f & EF_RGATES or srv[idx] == pid:
                # readiness gates, or the target phase is already on the
                # server (the reference's full merge/no-op check)
                slow.append(idx)
                continue
            t = tpl_of[pid] if 0 <= pid < n_tpl else -1
            if t < 0 or pathc[idx] is None:
                slow.append(idx)
                continue
            sel.append(pos)
            tpls.append(t)
        if not sel:
            return slow
        rows = [idxs[p] for p in sel]
        nipb = self._node_ip_b
        hostc = pool.host_b
        startc = pool.start_b
        ctrc = pool.ctr_b
        ictrc = pool.ictr_b
        conds = k.cond_h[idxs][sel]
        pids = [tgt[p] for p in sel]
        hosts = [hostc[i] or nipb for i in rows]
        ips = [ipc[i] for i in rows]
        starts = [startc[i] or b"" for i in rows]
        ctrs = [ctrc[i] or b"" for i in rows]
        ictrs = [ictrc[i] or b"" for i in rows]
        paths = [pathc[i] for i in rows]
        scalars = [ef[i] & EF_SCALAR for i in rows]  # truthy ints
        # allocation deferred (column None): first transitions arrive in
        # bulk, so the whole batch takes ONE _alloc_lock hold below
        need_ip = [(ri, rows[ri]) for ri, ip in enumerate(ips) if ip is None]
        if need_ip:
            meta = pool.meta
            dropped = 0
            with self._alloc_lock:
                missing: list[tuple[int, int, dict]] = []
                for ri, idx in need_ip:
                    m = meta[idx]
                    if m is None:
                        dropped += 1  # row vanished: pruned below
                        continue
                    ip_s = m.get("podIP")
                    if ip_s:
                        ips[ri] = ipc[idx] = ip_s.encode()
                    else:
                        missing.append((ri, idx, m))
                if missing:
                    fresh = self.ippool.get_many(len(missing))
                    for (ri, idx, m), ip_s in zip(missing, fresh):
                        m["podIP"] = ip_s
                        ips[ri] = ipc[idx] = ip_s.encode()
            if dropped:
                keep = [i for i, ip in enumerate(ips) if ip]
                conds = conds[keep]
                for col in (rows, tpls, hosts, ips, starts, ctrs,
                            ictrs, paths, pids, scalars):
                    col[:] = [col[i] for i in keep]
        if rows:
            self._submit(
                self._emit_send_pods, rows,
                np.asarray(tpls, np.int32), conds,
                hosts, ips, starts, ctrs, ictrs, paths, pids, scalars,
                now_rfc3339().encode(),
            )
        return slow

    def _emit_send_pods(
        self, rows, tpls, conds, hosts, ips, starts, ctrs, ictrs, paths,
        pids, scalars, now_b,
    ) -> None:
        """One executor job for a template emit batch: splice bodies into
        the slab and ship them in a single GIL-free C call when a plain
        native pump group is available, or render-then-send through the
        wrapper chain (faults / HA fence / stub pumps) so every wrapper
        keeps seeing whole request batches. Fingerprint seeding, the
        whole-frame resend contract, degradation shedding and the
        per-object fallback are identical to the legacy _pump_send."""
        _t = time.perf_counter()
        codec = self._codec
        kw = dict(
            tpl=self._emit_tpl, tpl_ids=tpls, cond_bits=conds,
            hosts=hosts, ips=ips, starts=starts, ctrs=ctrs, ictrs=ictrs,
            now=now_b, base=self._pump_base_b,
        )
        # bare stub pumps (tests, cost model) have no emit_spliced: they
        # take the render-then-send split path like any wrapped pump
        spliced = getattr(self._pump, "emit_spliced", None)
        res = (
            spliced(codec, {**kw, "paths": paths})
            if spliced is not None else None
        )
        fused = res is not None
        if not fused:
            # render-only (paths omitted: the C side never sees them, the
            # request frames below carry them to the wrapped send)
            res = codec.emit_pods(**kw)
        if res is None:  # codec raced away: per-object Python path
            for idx in rows:
                key = self.pods.pool.key_of(idx)
                if key is not None:
                    self._submit(self._patch_pod_status, key, idx)
            return
        bodies, fps, status, slab_bytes = res
        base = self._pump_base_b
        if fused:
            if (status == 0).any():
                # connection deaths: re-frame the complete batch and run
                # the standard whole-frame resend (only failed indices
                # are actually resent)
                reqs = [
                    ("PATCH", base + p + b"/status", body, self._EMIT_CTYPE)
                    for p, body in zip(paths, bodies)
                ]
                status = self._pump_resend_frames(reqs, status)
            else:
                self._pump_note_outcome(len(rows), status)
        else:
            reqs = [
                ("PATCH", base + p + b"/status", body, self._EMIT_CTYPE)
                for p, body in zip(paths, bodies)
            ]
            status = self._pump_send_frames(reqs)
        # Echo-drop seeding (PR 7): valid only for scalar-replace server
        # statuses, where the strategic merge yields exactly the rendered
        # document. Seeded after the send returns — the watch echo rides
        # the router's parse window (ms) while this runs in µs, and a
        # missed seed only costs the echo a full ingest pass, never
        # correctness.
        meta = self.pods.pool.meta
        phases = self._pod_phases
        fps_l = fps.tolist()
        st_l = status.tolist()
        for i, idx in enumerate(rows):
            if scalars[i] and 200 <= st_l[i] < 300:
                m = meta[idx]
                if m is not None:
                    m["fp_expect"] = fps_l[i]
                    m["expect_phase"] = phases[pids[i]]
        self._inc("emit_native_total", len(rows))
        self._inc("emit_slab_bytes_total", slab_bytes)
        self._pump_send_tail(status, rows, "pods", len(rows), _t)

    def _pump_send_frames(self, reqs):
        """Send one batch, resending WHOLE FRAMES for requests whose
        connection died (status 0). pump.cc's failure contract hands a
        dead connection's unsent/unread suffix back as status 0 and
        re-dials on the next call — so a short write mid-frame is
        recovered here by resending those requests' complete frames on a
        fresh connection, bounded by the shared resend policy, instead
        of leaking every mid-frame loss to the per-object slow path (or,
        for heartbeats, dropping it outright — the old behavior).

        When the deadline expires with the ENTIRE batch still dead the
        pump target is down: the engine degrades (kwok_degraded{reason=
        "pump"}) and the caller sheds instead of flooding the executor
        with doomed per-object retries."""
        return self._pump_resend_frames(reqs, self._pump.send(reqs))

    def _pump_resend_frames(self, reqs, status):
        """The retry half of _pump_send_frames, starting from a status
        array an initial send already produced — the fused template emit
        enters here (its first send happened inside the C call) with
        request frames rebuilt from the body slab."""
        if (status == 0).any():
            backoff = PUMP_RESEND.session()
            while self._running:
                delay = backoff.next_delay()
                if delay is None:
                    break  # policy deadline
                backoff.sleep(delay, lambda: not self._running)
                fail = np.nonzero(status == 0)[0]
                sub = [reqs[i] for i in fail.tolist()]
                status[fail] = self._pump.send(sub)
                if not (status == 0).any():
                    break
        self._pump_note_outcome(len(reqs), status)
        return status

    def _pump_note_outcome(self, n, status) -> None:
        """Degradation bookkeeping shared by every pump batch outcome."""
        if n and (status == 0).all():
            if self._degradation.set("pump"):
                logger.error(
                    "engine degraded: pump egress down past the resend "
                    "deadline (shedding batches)"
                )
        elif (status != 0).any():
            if self._degradation.clear("pump"):
                logger.info("pump egress recovered; shedding stops")

    def _pump_send(self, reqs, idxs, kind) -> None:
        """One executor job sends the whole batch (with whole-frame
        resend of connection failures); rows still failing are retried
        through the per-object Python path — unless the pump target is
        down outright, in which case the batch is shed and counted."""
        _t = time.perf_counter()
        status = self._pump_send_frames(reqs)
        self._pump_send_tail(status, idxs, kind, len(reqs), _t)

    def _pump_send_tail(self, status, idxs, kind, n, _t) -> None:
        """Telemetry + shedding + per-object fallback shared by the
        legacy request-tuple batches and the fused template emit."""
        _t1 = time.perf_counter()
        tel = self.telemetry
        tel.pump_hist.observe(_t1 - _t)
        tel.inc("pump_requests_total", n)
        tel.span(
            "pump.send", _t, _t1, "pump", {"kind": kind, "n": n}
        )
        if n and (status == 0).all() and (
            "pump" in self._degradation.reasons
        ):
            # pump target down past the resend deadline: shed the batch
            # (counted) instead of converting it into thousands of
            # doomed per-object jobs that would wedge the executor
            with self._gen_lock:
                self._dropped_jobs += n
            self._inc("dropped_jobs_total", n)
            return
        ok = int(((status >= 200) & (status < 300)).sum())
        if kind == "heartbeat":
            self._inc("heartbeats_total", ok)
        else:
            self._inc("status_patches_total", ok)
        _now = time.perf_counter()
        # sampled end-to-end traces: only pay the per-ack meta lookup when
        # sampling is on (ingest can only have stamped _trace_t0 then)
        want_trace = self._trace_every and kind == "pods"
        for st, idx in zip(status.tolist(), idxs):
            if 200 <= st < 300 or st == 404:
                if want_trace:
                    m = self.pods.pool.meta[idx]
                    t0e = m.pop("_trace_t0", None) if m else None
                    if t0e is not None:
                        # (key, rv) correlation context: ties this span
                        # to the apiserver flight record / store-commit
                        # stamp for the same object (timeline.py merge)
                        key = self.pods.pool.key_of(idx)
                        tel.span(
                            "pod.ingest_to_patch", t0e, _now, "event",
                            {
                                "key": f"{key[0]}/{key[1]}" if key else "",
                                "rv": m.get("rv"),
                            },
                        )
                continue  # 404 = object deleted server-side; Python path
                # treats that as a no-op too
            if kind == "pods":
                key = self.pods.pool.key_of(idx)
                if key is not None:
                    self._submit(self._patch_pod_status, key, idx)
            elif kind == "nodes":
                name = self.nodes.pool.key_of(idx)
                if name is not None:
                    self._submit(self._patch_node_status, name, idx)
            elif kind == "heartbeat":
                # a heartbeat whose frame died used to be DROPPED here
                # (one warning, no resend): fall back to the per-object
                # Python path like the other kinds — a freshly-rendered
                # heartbeat is always valid
                name = self.nodes.pool.key_of(idx)
                if name is not None:
                    self._inc("patch_errors_total")
                    logger.warning(
                        "heartbeat pump send failed for %s: %s; "
                        "falling back to per-object patch", name, st,
                    )
                    self._submit(
                        self._heartbeat_node, name, idx, now_rfc3339()
                    )

    def _patch_node_status(self, name: str, idx: int) -> None:
        k = self.nodes
        m = k.pool.meta[idx]
        if not m:
            return
        node = self._lazy_obj(m) or {}
        current = node.get("status") or {}
        rendered = render_node_status(
            node, int(k.cond_h[idx]), self.config.node_ip,
            now_rfc3339(), self.start_time,
        )
        if not node_status_patch_needed(current, rendered):
            return
        _t = time.perf_counter()
        self.client.patch_status("nodes", None, name, {"status": rendered})
        self.telemetry.observe_patch_rtt(
            "node_status", time.perf_counter() - _t
        )
        self._inc("status_patches_total")

    def _heartbeat_node(self, name: str, idx: int, now_str: str) -> None:
        k = self.nodes
        rendered = render_node_heartbeat(int(k.cond_h[idx]), now_str, self.start_time)
        _t = time.perf_counter()
        self.client.patch_status("nodes", None, name, {"status": rendered})
        self.telemetry.observe_patch_rtt(
            "heartbeat", time.perf_counter() - _t
        )
        self._inc("heartbeats_total")

    def _emit_heartbeats_native(self, k, hb_rows, now_str: str) -> None:
        """One C++ call renders every due heartbeat's patch bytes; the
        workers then only do HTTP (KeepNodeHeartbeat's batch, minus the
        per-object template execution)."""
        idxs = np.array([i for _, i in hb_rows], np.int64)
        start = self.start_time.encode()
        bodies = self._codec.render_heartbeats(
            k.cond_h[idxs], self._hb_cond_meta, now_str, [start] * len(hb_rows)
        )
        if bodies is None:  # codec raced away; fall back
            for name, idx in hb_rows:
                self._submit(self._heartbeat_node, name, idx, now_str)
            return
        if self._get_pump() is not None:
            base = self._pump_base_b
            pool = k.pool
            npb = self._node_path_b
            reqs = [
                (
                    "PATCH",
                    base + npb(pool, idx, name) + b"/status",
                    body,
                    "application/strategic-merge-patch+json",
                )
                for (name, idx), body in zip(hb_rows, bodies)
            ]
            self._submit(
                self._pump_send, reqs, [i for _, i in hb_rows], "heartbeat"
            )
            return
        for (name, _idx), body in zip(hb_rows, bodies):
            self._submit(self._send_heartbeat_bytes, name, body)

    def _send_heartbeat_bytes(self, name: str, body: bytes) -> None:
        _t = time.perf_counter()
        self.client.patch_status("nodes", None, name, body)
        self.telemetry.observe_patch_rtt(
            "heartbeat", time.perf_counter() - _t
        )
        self._inc("heartbeats_total")

    def _render_pod_pre(self, idx: int):
        """Shared render preamble: the row's meta dict + target phase
        name, or None when the row has no object or is Gone."""
        k = self.pods
        m = k.pool.meta[idx]
        if not m or self._pod_obj(m) is None:
            return None
        phase_name = self._pod_phases[int(k.phase_h[idx])]
        if phase_name == "Gone":
            return None
        return m, phase_name

    def _pool_ip(self, m: dict, idx: int) -> "str | None":
        """Pool-backed IP lookup/allocate — pure bookkeeping under
        _alloc_lock, never provider I/O, so it is ingest-path safe.
        None when the row vanished since the caller looked it up."""
        with self._alloc_lock:  # check+allocate atomic across workers
            ip = m.get("podIP")
            if not ip:
                if self.pods.pool.meta[idx] is not m:
                    return None  # row deleted since this job was queued
                ip = self.ippool.get()
                m["podIP"] = ip
        return ip

    def _render_pod(self, idx: int):
        """Full render for executor workers: may enter the CNI provider
        (netns/network I/O). Never call on the ingest path — the tick
        thread, or a lane drain worker holding its stage_lock — which
        uses _render_pod_ingest instead."""
        pre = self._render_pod_pre(idx)
        if pre is None:
            return None
        m, phase_name = pre
        ip = m.get("podIP")
        if not ip and self.config.enable_cni and cni.available():
            # real-CNI path (configurePod's cni.Setup branch,
            # pod_controller.go:382-391); falls back to the pool when no
            # provider is registered (the non-Linux stub contract)
            ip, row_gone = self._cni_allocate(m, idx)
            if row_gone or (ip is None and m.get("cni_pending")):
                return None  # deleted mid-setup / another worker mid-setup
        if not ip:
            ip = self._pool_ip(m, idx)
            if ip is None:
                return None
        return render_pod_status(
            m["obj"], phase_name, int(self.pods.cond_h[idx]),
            self.config.node_ip, ip,
        )

    def _render_pod_ingest(self, idx: int):
        """Ingest-path render: NEVER enters the CNI provider, so it is
        safe on the tick thread and under a lane's stage_lock (kwoklint
        blocking-under-lock caught the old single _render_pod doing
        provider I/O from the drain path). Returns (rendered, defer):
        defer=True means provider I/O is required — the caller submits
        the work to an executor job instead (_patch_pod_status re-renders
        with the full path and suppresses no-ops itself)."""
        pre = self._render_pod_pre(idx)
        if pre is None:
            return None, False
        m, phase_name = pre
        ip = m.get("podIP")
        if not ip:
            if self.config.enable_cni and cni.available():
                return None, True
            ip = self._pool_ip(m, idx)
            if ip is None:
                return None, False
        return render_pod_status(
            m["obj"], phase_name, int(self.pods.cond_h[idx]),
            self.config.node_ip, ip,
        ), False

    def _cni_allocate(self, m: dict, idx: int) -> tuple[str | None, bool]:
        """Allocate a pod IP through the CNI provider.

        Returns (ip, row_gone). The provider call runs OUTSIDE every lock (it
        may block on netns/network I/O); _alloc_lock only guards the
        pending-flag and the liveness-checked commit, so a deletion racing
        with setup either sees the committed `cni` flag (and removes) or the
        commit sees the released row (and undoes its own allocation).
        """
        ns = m.get("namespace") or "default"
        name = m.get("name") or ""
        uid = ((m.get("obj") or {}).get("metadata") or {}).get("uid") or ""
        with self._alloc_lock:
            if m.get("podIP"):
                return m["podIP"], False
            if m.get("cni_pending"):
                return None, False
            m["cni_pending"] = True
        try:
            ips = cni.setup(ns, name, uid)
        except Exception:
            logger.exception("cni setup failed; falling back to IP pool")
            ips = None
        undo = False
        with self._alloc_lock:
            m.pop("cni_pending", None)
            if not ips:
                return None, self.pods.pool.meta[idx] is not m
            if self.pods.pool.meta[idx] is m:  # row still ours: commit
                m["podIP"] = ips[0]
                m["cni"] = True
            else:
                undo = True
        if undo:  # deleted mid-setup; release the fresh allocation
            try:
                cni.remove(ns, name, uid)
            except Exception:
                logger.exception("cni remove (undo) failed")
            return None, True
        return ips[0], False

    def _patch_pod_status(self, key, idx: int) -> None:
        k = self.pods
        m = k.pool.meta[idx]
        if not m:
            return
        # consume any sampled ingest stamp up front: a suppressed/skipped
        # patch must not leave it behind for a later unrelated patch to
        # close with an arbitrarily inflated duration
        t0e = m.pop("_trace_t0", None) if self._trace_every else None
        rendered = self._render_pod(idx)
        if rendered is None:
            return
        current = (self._pod_obj(m) or {}).get("status") or {}
        if not pod_status_patch_needed(current, rendered):
            return
        ns, name = key
        _t = time.perf_counter()
        self.client.patch_status("pods", ns, name, {"status": rendered})
        _t1 = time.perf_counter()
        self.telemetry.observe_patch_rtt("pod_status", _t1 - _t)
        if t0e is not None:  # sampled ingest->patch end-to-end span
            self.telemetry.span(
                "pod.ingest_to_patch", t0e, _t1, "event",
                {"key": f"{ns}/{name}", "rv": m.get("rv")},
            )
        self._inc("status_patches_total")

    def _delete_pod(self, key, idx: int) -> None:
        """Finalizer strip + grace-0 delete (DeletePod,
        pod_controller.go:155-183)."""
        ns, name = key
        m = self.pods.pool.meta[idx]
        if m and m.get("finalizers"):
            self.client.patch_meta("pods", ns, name, {"metadata": {"finalizers": None}})
        _t = time.perf_counter()
        self.client.delete("pods", ns, name, grace_seconds=0)
        self.telemetry.observe_patch_rtt(
            "pod_delete", time.perf_counter() - _t
        )
        self._inc("deletes_total")

    def _emit_deletes_native(self, k, del_rows) -> None:
        """Batch the DeletePod flow: all finalizer strips in one pump call,
        then all grace-0 deletes (global order preserves each pod's
        strip-before-delete)."""

        strips, strip_rows, deletes = [], [], []
        base = self._pump_base_b
        for (ns, name), idx in del_rows:
            m = k.pool.meta[idx]
            pb = k.pool.path_b[idx]
            if pb is None:  # column not staged (legacy path): build once
                pb = k.pool.path_b[idx] = (
                    f"/api/v1/namespaces/{_q(ns)}/pods/{_q(name)}"
                ).encode()
            path = base + pb
            if m and m.get("finalizers"):
                strips.append((
                    "PATCH", path, b'{"metadata":{"finalizers":null}}',
                    "application/merge-patch+json",
                ))
                strip_rows.append(((ns, name), idx))
            deletes.append(("DELETE", path, b'{"gracePeriodSeconds":0}'))
        self._submit(
            self._pump_send_deletes, strips, strip_rows, deletes, del_rows
        )

    def _pump_send_deletes(self, strips, strip_rows, deletes, del_rows) -> None:
        retry: set[int] = set()
        if strips:
            # one connection group for both batches: every pod's strip
            # completes before its grace-0 delete is issued
            strip_status, status = self._pump.send_ordered([strips, deletes])
            # a failed strip leaves finalizers on the pod, turning the
            # grace-0 delete into a graceful mark — those rows must go
            # through the per-object strip+delete fallback
            for st, (_key, idx) in zip(strip_status.tolist(), strip_rows):
                if not (200 <= st < 300 or st == 404):
                    retry.add(idx)
        else:
            status = self._pump.send(deletes)
        # 404 = already gone server-side; the per-object path counts every
        # issued delete, so the batch path matches that accounting
        ok = int(((status >= 200) & (status < 300)).sum())
        ok += int((status == 404).sum())
        self._inc("deletes_total", ok)
        for st, (key, idx) in zip(status.tolist(), del_rows):
            if idx in retry or not (200 <= st < 300 or st == 404):
                self._submit(self._delete_pod, key, idx)
