"""Shared-memory arenas for the process-lane pipeline (ISSUE 15).

The threaded ShardLanes overlap only where stages release the GIL
(LANES_r07 called the 2.2x threaded win "the floor"). Process lanes
(engine/proclanes.py) put each lane's drain+apply+emit on a true core;
this module is the cross-process substrate they stand on:

- ``RawRing``   — a single-producer/single-consumer byte ring hosted on
  one ``multiprocessing.shared_memory`` segment per lane. The parent
  router writes each parse window's raw event lines ONCE (bytes are
  copied, never re-serialized — no JSON re-encode, no pickle of event
  payloads) and ships a tiny ``(offset, length)`` descriptor over the
  lane's pipe; the child maps the same pages and slices the blob out.
- ``InflightSlot`` — the cross-process twin of ShardLane's
  ``_emit_inflight`` crash-replay slot: the child parks its rendered
  emit frames in shared memory BEFORE the pump send and clears the slot
  after every frame is acknowledged, so a SIGKILL mid-send cannot lose
  an emit slice (device transitions fire exactly once; the parent
  replays the slot before respawning the lane — at-least-once, absorbed
  by the echo drop / repair no-op exactly like the pump's whole-frame
  resend).
- ``StatusBank`` — one int64 row per lane (numpy views over a shared
  buffer, per-lane slices): liveness heartbeat, readiness, resync
  progress, managed counts, queue depth. The parent's coordinator
  scrapes it for /metrics gauges, the startup gate, and the supervisor's
  hung-child detection — no pipe round-trips on the monitoring path.

Lifecycle discipline: the PARENT creates and unlinks every segment
(``close(unlink=True)`` on clean stop AND around respawns); children
only attach and close. Spawned children share the parent's
resource-tracker process, so the tracker entry lives exactly as long as
the parent's registration and a SIGKILLed child can never take the
arena down with it; the gate in benchmarks/proc_soak.py proves
/dev/shm ends empty either way.
"""

from __future__ import annotations

import logging
import time
import uuid
from multiprocessing import shared_memory

import numpy as np

logger = logging.getLogger("kwok_tpu.shm")

# header slots (int64 each) shared by the ring/slot layouts
_HDR_I64 = 8
_HDR_BYTES = _HDR_I64 * 8


def arena_name(tag: str) -> str:
    return f"kwoktpu-{tag}-{uuid.uuid4().hex[:10]}"


class Arena:
    """One shared_memory segment + a header/payload numpy view split."""

    def __init__(self, name: str, size: int = 0, create: bool = False):
        if create:
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=size
            )
        else:
            # attach: the child shares the parent's resource-tracker
            # process (spawn passes the tracker fd), so the attach-side
            # register dedups against the parent's create-side one and
            # the segment's tracker entry lives exactly until the parent
            # unlinks — a SIGKILLed child can never take the arena down
            self.shm = shared_memory.SharedMemory(name=name)
        self.name = name
        self.size = self.shm.size
        self.created = create
        self.hdr = np.frombuffer(
            self.shm.buf, dtype=np.int64, count=_HDR_I64
        )
        self.payload = self.shm.buf[_HDR_BYTES:]

    def close(self, unlink: bool = False) -> None:
        # release the numpy views first: SharedMemory.close() refuses
        # while exported buffers are alive
        self.hdr = None
        self.payload = None
        try:
            self.shm.close()
        except BufferError:
            logger.debug("arena %s still referenced at close", self.name)
            return
        if unlink and self.created:
            try:
                self.shm.unlink()
            except FileNotFoundError:
                pass


class RawRing:
    """SPSC byte ring: the parent writes raw-line blobs, the child reads
    them by (absolute offset, length) descriptors received over its pipe.

    Header: [0]=w total bytes produced (pads included), [1]=r total bytes
    consumed (child-written), [3]=payload capacity (layout check; slot
    [2] is reserved). Blobs never
    straddle the wrap point — the writer pads to the boundary and the
    descriptor's offset already accounts for it, so the reader's consume
    (``r = offset + length``) retires the pad implicitly.
    """

    W, R, CAP = 0, 1, 3

    def __init__(self, name: str, size: int = 0, create: bool = False):
        self.arena = Arena(name, size + _HDR_BYTES if create else 0, create)
        self.cap = self.arena.size - _HDR_BYTES
        if create:
            self.arena.hdr[self.CAP] = self.cap
        elif int(self.arena.hdr[self.CAP]) != self.cap:
            raise ValueError(
                f"ring {name}: capacity mismatch "
                f"({self.arena.hdr[self.CAP]} != {self.cap})"
            )
        self.name = name

    # ------------------------------------------------------------ producer

    def free_bytes(self) -> int:
        hdr = self.arena.hdr
        return self.cap - int(hdr[self.W] - hdr[self.R])

    def try_write(self, blob) -> int | None:
        """Append ``blob`` contiguously; returns its absolute offset or
        None when the ring lacks space (caller paces/sheds — see
        ProcLane.ship)."""
        n = len(blob)
        if n > self.cap:
            raise ValueError(f"blob {n}B exceeds ring capacity {self.cap}B")
        hdr = self.arena.hdr
        w = int(hdr[self.W])
        pos = w % self.cap
        pad = self.cap - pos if pos + n > self.cap else 0
        if self.cap - int(w - hdr[self.R]) < pad + n:
            return None
        start = w + pad
        spos = start % self.cap
        self.arena.payload[spos:spos + n] = blob
        # publish AFTER the payload copy: int64 store is atomic, and the
        # descriptor (the reader's only pointer into the ring) is sent
        # over the pipe after this returns — double-fenced by the pipe
        hdr[self.W] = start + n
        return start

    def reset(self) -> None:
        """Respawn path: drop unconsumed bytes (their descriptors died
        with the child's pipe; the post-respawn stream resync re-delivers
        the events)."""
        hdr = self.arena.hdr
        hdr[self.R] = int(hdr[self.W])

    # ------------------------------------------------------------ consumer

    def read(self, offset: int, length: int) -> bytes:
        pos = offset % self.cap
        out = bytes(self.arena.payload[pos:pos + length])
        self.arena.hdr[self.R] = offset + length
        return out

    def close(self, unlink: bool = False) -> None:
        self.arena.close(unlink=unlink)


class InflightSlot:
    """One pending emit batch, durable across a lane-process SIGKILL.

    Header: [0]=state (0 empty / 1 armed), [1]=payload length. The writer
    orders state=0 -> payload -> length -> state=1 (disarm-first, so a
    RE-arm torn mid-copy cannot leave state=1 over a mix of old and new
    bytes); the (single, post-mortem) reader checks state first — a torn
    write parks as "empty", which only widens the at-least-once replay
    the checkpoint machinery already absorbs.
    """

    STATE, LEN = 0, 1

    def __init__(self, name: str, size: int = 0, create: bool = False):
        self.arena = Arena(name, size + _HDR_BYTES if create else 0, create)
        self.cap = self.arena.size - _HDR_BYTES
        self.name = name

    def arm(self, payload: bytes) -> bool:
        if len(payload) > self.cap:
            # oversized batch: the slot degrades to the pre-ISSUE-15
            # contract (checkpoint-replay only) instead of truncating
            return False
        hdr = self.arena.hdr
        hdr[self.STATE] = 0  # disarm-first: a torn RE-arm reads "empty"
        self.arena.payload[: len(payload)] = payload
        hdr[self.LEN] = len(payload)
        hdr[self.STATE] = 1
        return True

    def torn_arm(self, payload: bytes) -> None:
        """Fault-injection twin of :meth:`arm` (``shm.torn``): the writer
        dies mid-copy — disarm fires, a PREFIX of the payload lands, and
        length/state are never written. The documented invariant under
        test: the torn re-arm parks as "empty" (:meth:`peek` -> None)."""
        hdr = self.arena.hdr
        hdr[self.STATE] = 0  # disarm-first, exactly like arm()
        k = max(1, min(len(payload), self.cap) // 2)
        self.arena.payload[:k] = payload[:k]
        # ...writer SIGKILLed here: no LEN store, no state=1

    def clear(self) -> None:
        self.arena.hdr[self.STATE] = 0

    def peek(self) -> bytes | None:
        hdr = self.arena.hdr
        if int(hdr[self.STATE]) != 1:
            return None
        n = int(hdr[self.LEN])
        if not 0 <= n <= self.cap:
            return None
        return bytes(self.arena.payload[:n])

    def close(self, unlink: bool = False) -> None:
        self.arena.close(unlink=unlink)


# StatusBank fields (one int64 row per lane)
BANK_ALIVE_NS = 0      # child heartbeat, monotonic ns of the CHILD's clock
BANK_READY = 1         # child engine.ready
BANK_RESYNC = 2        # bitmask: 1 = nodes re-list ingested, 2 = pods
BANK_NODES = 3         # len(nodes.pool)
BANK_PODS = 4          # len(pods.pool)
BANK_QDEPTH = 5        # child ingest-queue depth
BANK_EVENTS = 6        # events applied (child watch_events_total proxy)
BANK_PID = 7           # child's own pid (supervisor sanity)
# child -> parent upcall counters (the child has no watch streams of its
# own; the parent's coordinator turns deltas into the real stream cuts)
BANK_INTEG_NODES = 8   # integrity-doubt resync requests (nodes)
BANK_INTEG_PODS = 9    # integrity-doubt resync requests (pods)
BANK_REWIND = 10       # re-listed-rv-rewind detections (store restore)
BANK_DRIFT = 11        # 1 while the child's auditor holds a "drift"
#                        degraded reason (unrepaired-divergence streak);
#                        the parent mirrors it into its own /readyz
BANK_FIELDS = 12


class StatusBank:
    """Per-lane int64 status rows; children own their row, the parent
    reads all of them (single-writer-per-row, no locks)."""

    def __init__(self, name: str, lanes: int = 0, create: bool = False):
        size = lanes * BANK_FIELDS * 8 if create else 0
        self.arena = Arena(name, size + _HDR_BYTES if create else 0, create)
        n = (self.arena.size - _HDR_BYTES) // (BANK_FIELDS * 8)
        self.rows = np.frombuffer(
            self.arena.shm.buf, dtype=np.int64, offset=_HDR_BYTES,
            count=n * BANK_FIELDS,
        ).reshape(n, BANK_FIELDS)
        self.name = name

    def row(self, i: int) -> np.ndarray:
        return self.rows[i]

    def close(self, unlink: bool = False) -> None:
        self.rows = None
        self.arena.close(unlink=unlink)


class MetricsBank:
    """Per-lane telemetry-snapshot slab (ISSUE 16): the child serializes
    its whole metrics registry into shared memory; the parent merges the
    snapshots into one `/metrics` exposition.

    Header: [0]=seq (a seqlock stamp: odd while the child is mid-write,
    even once the slab is consistent), [1]=payload length. Single writer
    (the lane child), any number of readers (the parent's scrape). The
    writer bumps seq to odd BEFORE touching payload/length and to even
    after, so a reader that observes an odd or changed seq retries
    instead of parsing half a slab; publication needs no lock and the
    scrape path costs the child nothing.
    """

    SEQ, LEN = 0, 1

    def __init__(self, name: str, size: int = 0, create: bool = False):
        self.arena = Arena(name, size + _HDR_BYTES if create else 0, create)
        self.cap = self.arena.size - _HDR_BYTES
        self.name = name

    def write(self, payload: bytes) -> bool:
        """Publish one snapshot; False when it exceeds the slab (the
        reader keeps the previous consistent snapshot)."""
        if len(payload) > self.cap:
            return False
        hdr = self.arena.hdr
        seq = int(hdr[self.SEQ])
        if seq % 2:  # a crashed writer left the slab mid-write: restamp
            seq += 1
        hdr[self.SEQ] = seq + 1  # odd: readers back off
        self.arena.payload[: len(payload)] = payload
        hdr[self.LEN] = len(payload)
        hdr[self.SEQ] = seq + 2  # even: consistent again
        return True

    def torn_write(self, payload: bytes) -> None:
        """Fault-injection twin of :meth:`write` (``shm.torn``): the
        writer dies mid-slab — seq goes odd, a PREFIX of the payload
        lands, and neither length nor the closing even stamp is ever
        written. Readers must back off (odd seq) and the NEXT live write
        must restamp; both paths are pinned by tests/test_proclanes.py."""
        if len(payload) > self.cap:
            return
        hdr = self.arena.hdr
        seq = int(hdr[self.SEQ])
        if seq % 2:
            seq += 1
        hdr[self.SEQ] = seq + 1  # odd: mid-write
        k = max(1, len(payload) // 2)
        self.arena.payload[:k] = payload[:k]
        # ...writer SIGKILLed here: no LEN store, no even restamp

    def reset(self) -> None:
        """Respawn path: empty the slab (back to the never-published
        state) so the parent cannot re-read a dead incarnation's
        snapshot once it has been folded into the retired accumulator."""
        hdr = self.arena.hdr
        hdr[self.LEN] = 0
        hdr[self.SEQ] = 0

    def read(self, retries: int = 8) -> bytes | None:
        """One consistent snapshot, or None if the slab is empty or the
        writer kept it torn for the whole (bounded) retry window."""
        hdr = self.arena.hdr
        for attempt in range(retries):
            seq0 = int(hdr[self.SEQ])
            if seq0 == 0:  # nothing published yet
                return None
            if seq0 % 2:  # writer mid-update: back off briefly, retry
                if attempt:
                    time.sleep(0.0002)
                continue
            n = int(hdr[self.LEN])
            if not 0 <= n <= self.cap:
                continue
            out = bytes(self.arena.payload[:n])
            if int(hdr[self.SEQ]) == seq0:
                return out
        return None

    def close(self, unlink: bool = False) -> None:
        self.arena.close(unlink=unlink)
