"""Process lanes: the GIL escape (ISSUE 15, ROADMAP item 2).

The threaded ShardLanes (engine/lanes.py) overlap only where stages
release the GIL — LANES_r07 measured the threaded multi-lane win at
~2.2x and called it the floor, with ``engine_lane_drain_emit`` the
largest remaining host term. This module makes each lane a worker
**process** on a true core:

  parent: watch ingest ──> router thread (one native batch parse,
          pre-partitioned lane runs) ──> per-lane shared-memory RawRing
          (raw bytes written once, never re-serialized) + descriptor pipe
  child i: full single-lane ClusterEngine over shard i — drain, device
          tick, emit, its own pump connection group — plus a node
          "topology tap" for the shards it does not own

Each child is *exactly* the single-lane engine (the per-key ordering
oracle's reference arm), so per-key patch order and patch bytes are the
single-lane engine's by construction; only the plumbing around it is
new. Cross-lane coupling is gone instead of shared: node events
broadcast to every lane — the owning lane ingests (rows, heartbeats,
emit), the others run the tap (``node_has`` membership + managed-ness
re-evaluation for their own pods), so ``SEL_ON_MANAGED_NODE`` bits stay
correct with no cross-process topology store; the pod-IP CIDR is
partitioned per lane (disjoint sub-ranges, no cross-process allocator
lock).

The robustness tier maps one-to-one (the ISSUE's bet):

- watchdog in-thread restarts become supervised process respawns with
  the same budget/ledger/degradation semantics (``Watchdog.charge``
  shares the budget window; exhaustion degrades /readyz exactly like a
  thread crash-loop);
- per-lane checkpoints reuse the ``member<i>.ckpt.json`` pattern:
  children checkpoint to ``lane<i>.ckpt.json`` and a respawn reconciles
  via the PR 7 RestoreSession against the respawn-triggered full
  re-list;
- the fault plane stays one-plane-per-engine on the PARENT (watch
  cuts/410 storms/blackouts/garbling inject where the bytes enter), and
  ``worker.kill=kwok-lane*`` now delivers REAL SIGKILLs to lane
  processes (FaultPlane.register_proc_target);
- the ``_emit_inflight`` crash-replay slot survives as a shared-memory
  slot (engine/shm.InflightSlot): the child parks rendered emit frames
  before the pump send; the parent replays them before the respawn, so
  an emit slice is never lost to a dying process.

Spawn-only, always: the parent engine is thread-rich by the time lanes
start, and a fork would duplicate locked mutexes into the child
(fork-after-threads deadlock — kwoklint's spawn-only rule pins the
whole tree). Default off: ``--lane-procs`` / ``laneProcs`` /
``KWOK_LANE_PROCS``; with it off the threaded path is byte-unchanged
and no shm arena, pipe, or process exists.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import os
import pickle
import queue
import signal
import threading
import time

from kwok_tpu.engine import shm as shm_mod
from kwok_tpu.engine.rowpool import shard_of
from kwok_tpu.telemetry.errors import (
    PROCESS_REGISTRY,
    swallowed,
    worker_crashed,
    worker_restarted,
)
from kwok_tpu.workers import spawn_worker

logger = logging.getLogger("kwok_tpu.proclanes")

_KINDS = ("nodes", "pods")

#: per-lane raw-handoff ring size (bytes); one parse window must fit
_RING_BYTES = int(os.environ.get("KWOK_TPU_SHM_RING_BYTES", str(4 << 20)))
#: per-lane emit crash-replay slot size (bytes)
_SLOT_BYTES = int(os.environ.get("KWOK_TPU_SHM_SLOT_BYTES", str(1 << 20)))
#: per-lane telemetry-snapshot slab size (bytes); a whole registry
#: snapshot is ~20KB JSON, so 1MB never truncates in practice
_METRICS_BYTES = int(
    os.environ.get("KWOK_TPU_SHM_METRICS_BYTES", str(1 << 20))
)
#: status-loop beats (50ms each) between telemetry-snapshot publishes
_METRICS_EVERY_BEATS = 20
#: seconds the router waits on a full ring before dropping the window
#: for that lane (a dead/stalled child; the respawn resync re-delivers);
#: env-tunable so the shm.stall chaos arm can exercise the drop+resync
#: path without 5s of wall clock per injected stall
_RING_STALL_S = float(os.environ.get("KWOK_TPU_RING_STALL_S", "5.0"))
#: supervisor poll cadence
_SUPER_POLL_S = 0.2
#: a live lane process whose status beat is older than this is wedged
#: (the beat rides a dedicated 50ms thread, so only a hard GIL seizure
#: or a stopped process stalls it this long) and is killed for respawn
_STALL_NS = int(float(
    os.environ.get("KWOK_TPU_LANE_STALL_S", "60")
) * 1e9)


# --------------------------------------------------------------- child side


def _desc_check(kind, off, ln, bounds, cap: int, published: int):
    """None when a RAWB descriptor is safe to dereference, else the
    reject reason (the `reason` label of kwok_shm_desc_rejects_total).
    Pure integer/bounds math over the descriptor fields plus the ring's
    capacity and published write cursor — nothing is read from shared
    memory until every check passes, so a garbled descriptor
    (shm.desc_garble, or a genuinely hostile pipe) can never turn into
    a wild read."""
    if kind not in _KINDS:
        return "kind"
    if not isinstance(off, int) or not isinstance(ln, int):
        return "type"
    if ln < 0 or ln > cap or off < 0:
        return "range"
    if off + ln > published:
        return "unpublished"
    if not isinstance(bounds, list) or not bounds or bounds[0] != 0:
        return "bounds"
    prev = 0
    for b in bounds[1:]:
        if not isinstance(b, int) or b < prev or b > ln:
            return "bounds"
        prev = b
    if prev != ln:
        return "bounds"
    return None


class _SlotGuardPump:
    """Wraps one pump connection group member in the child: every batch
    is parked in the lane's shared-memory InflightSlot before it goes on
    the wire and cleared once every frame has a real HTTP status. NOT a
    plain native pump, so the fused template emit falls back to
    render-then-send through this wrapper — a fused call can never
    tunnel past the slot (the same containment contract as FaultyPump /
    FencedPump)."""

    def __init__(self, slot: shm_mod.InflightSlot, inner, plane=None):
        self._slot = slot
        self._inner = inner
        # the lane child's own fault plane (ISSUE 17): shm.torn here
        # simulates the writer dying mid-arm — disarm fires, a prefix of
        # the payload lands, state never returns to 1, and the parent's
        # post-mortem peek() must park the slot as "empty"
        self._plane = plane

    def send(self, requests):
        try:
            payload = pickle.dumps(requests, protocol=4)
            plane = self._plane
            if plane is not None and plane.decide("shm.torn") is not None:
                plane.record("shm.torn")
                self._slot.torn_arm(payload)
            else:
                self._slot.arm(payload)
        except Exception:
            # the slot is belt-and-braces over checkpoint replay: losing
            # it must never block the send
            swallowed("proclanes.slot_arm")
        status = self._inner.send(requests)
        try:
            if (status != 0).all():
                self._slot.clear()
            # any 0 statuses: the engine's whole-frame resend re-enters
            # send() with the failed subset, re-arming the slot with
            # exactly the frames still owed
        except Exception:
            swallowed("proclanes.slot_clear")
        return status

    def close(self) -> None:
        self._inner.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)


def make_proc_lane_engine_class():
    """The child's engine class, built lazily so importing this module
    never pays the engine import chain (the parent imports proclanes
    inside ClusterEngine.__init__; the spawn pickle carries only a
    module path)."""
    from kwok_tpu.engine.engine import ClusterEngine

    class _ProcLaneEngine(ClusterEngine):
        """The single-lane engine plus the node topology tap: node
        events for shards this lane does not own update ``node_has``
        membership (and re-evaluate this lane's pods on that node)
        WITHOUT acquiring rows — the owning lane does the row work and
        the heartbeats, so no node is double-managed.

        Stream healing inverts across the process boundary: the child
        has no watch streams, so integrity doubt (corrupt routed bytes)
        and re-list rv rewinds (store restore) are published as counters
        in the lane's StatusBank row; the parent's coordinator turns the
        deltas into the real (rate-bounded) stream cuts + re-lists."""

        _lane_index = 0
        _lane_n = 1
        _proc_integ: dict | None = None

        def _integrity_resync(self, kind: str) -> None:
            d = self._proc_integ
            if d is not None:
                d[kind] = d.get(kind, 0) + 1
                return
            super()._integrity_resync(kind)

        def _node_owned(self, name: str) -> bool:
            return shard_of(name, self._lane_n) == self._lane_index

        def _node_upsert(self, node: dict) -> None:
            name = (node.get("metadata") or {}).get("name")
            if name and not self._node_owned(name):
                # membership is sticky until Deleted, like the engine's
                # nodesSets (no removal on Modified,
                # node_controller.go:256-268) — so only a NEW managed
                # node changes the tap
                if (
                    name not in self.node_has
                    and self._node_need_heartbeat(node)
                ):
                    self.node_has.add(name)
                    self._update_pods_on_node(name)
                return
            super()._node_upsert(node)

        def _node_deleted(self, node: dict) -> None:
            name = (node.get("metadata") or {}).get("name")
            if name and not self._node_owned(name):
                if name in self.node_has:
                    self.node_has.discard(name)
                    self._update_pods_on_node(name)
                return
            super()._node_deleted(node)

        def _resync(self, kind: str, objs: list) -> None:
            d = self._proc_integ
            if d is not None:
                # store-restore detection moved lane-side: the parent
                # has no rows, so the watch loop's per-object rewind
                # scan is vacuous there — this lane compares its own
                # tracked revisions against the routed snapshot instead
                for o in objs:
                    meta = o.get("metadata") or {}
                    try:
                        rv = int(meta.get("resourceVersion") or 0)
                    except (TypeError, ValueError):
                        rv = 0
                    if not rv:
                        continue
                    tracked = self._tracked_rv(kind, o)
                    if tracked and rv < tracked:
                        d["rewind"] = d.get("rewind", 0) + 1
                        break
            if kind == "nodes":
                # tap hygiene: tracked-but-unowned nodes that vanished
                # while a stream was down never get a DELETED broadcast —
                # prune them from the managed set here (the owning lane's
                # rows are pruned by the super() walk)
                seen = {
                    (o.get("metadata") or {}).get("name") for o in objs
                }
                for name in [
                    nm for nm in self.node_has
                    if nm not in seen and not self._node_owned(nm)
                ]:
                    self.node_has.discard(name)
                    self._update_pods_on_node(name)
            super()._resync(kind, objs)

    return _ProcLaneEngine


def _make_lane_engine(spec: dict):
    """Build the child's single-lane engine."""
    from kwok_tpu.edge.httpclient import HttpKubeClient

    index = spec["index"]
    n = spec["n"]
    cls = make_proc_lane_engine_class()

    kubeconfig = spec.get("kubeconfig") or ""
    if kubeconfig:
        client = HttpKubeClient.from_kubeconfig(kubeconfig, spec["master"])
    else:
        client = HttpKubeClient(spec["master"])
    # the child's shard-scoped audit interval: the parent's RESOLVED
    # interval rides the spawn spec; anything else (including an
    # inherited KWOK_TPU_AUDIT_INTERVAL) is forced off with -1 — the
    # parent's resolution is the single source of truth
    audit = float(spec.get("audit_interval") or 0.0)
    cfg = dataclasses.replace(
        spec["config"],
        lane_procs=False,
        drain_shards=1,      # the child IS one lane
        use_mesh=False,
        initial_capacity=spec["capacity"],
        profile_dir="",
        # per-lane span-ring dump (ISSUE 16): the child owns its tick, so
        # engine.stop() writes <parent dump>.lane<i>.json on STOP/SIGTERM;
        # timeline.py --lane-dump merges them wall-aligned as pid 2+i
        trace_dump=spec.get("trace_dump", ""),
        # per-lane fault plane (ISSUE 17): the parent derives each
        # child's spec (faults.child_spec_text — CHILD_KINDS only,
        # re-seeded per lane); the "off" literal still forces a no-plane
        # child even when KWOK_TPU_FAULTS rides the inherited environment
        faults=spec.get("faults") or "off",
        audit_interval=audit if audit > 0 else -1.0,
        ha_role="",
        shed_queue_depth=0,  # shedding is a router concern (parent-side)
    )
    e = cls(client, cfg)
    e._lane_index = index
    e._lane_n = n
    e._proc_integ = {"nodes": 0, "pods": 0, "rewind": 0}
    e._ckpt_name = f"lane{index}"
    # partition the pod-IP CIDR: disjoint per-lane sub-ranges, so the
    # allocator needs no cross-process lock and respawns re-derive the
    # same range (pinned IPs from re-lists still ride IPPool.use)
    e.ippool.partition_lanes(index, n)
    return e


def lane_proc_main(spec: dict, conn) -> None:
    """Child entry point (spawn target; must stay module-level so the
    spawn pickle is a path, not state). Runs the lane's whole single-lane
    engine; the main thread consumes the parent's descriptor pipe."""
    plat = os.environ.get("KWOK_TPU_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    ring = shm_mod.RawRing(spec["ring"])
    slot = shm_mod.InflightSlot(spec["slot"])
    bank = shm_mod.StatusBank(spec["bank"])
    mbank = (
        shm_mod.MetricsBank(spec["metrics"]) if spec.get("metrics") else None
    )
    row = bank.row(spec["index"])
    row[shm_mod.BANK_PID] = os.getpid()
    row[shm_mod.BANK_ALIVE_NS] = time.monotonic_ns()
    e = _make_lane_engine(spec)
    # the child's own fault plane (None unless the parent propagated a
    # spec): shm.torn and shm.stall inject HERE, on the surfaces this
    # process owns; wire/pump/clock faults were already wrapped around
    # the child's client/pumps/clock by the engine constructor
    plane = e._faults
    e._pump_wrap = lambda p: _SlotGuardPump(slot, p, plane)
    # descriptor-pipe hygiene (shm.desc_garble's landing zone): a
    # corrupt descriptor must be BOUNDS-REJECTED and counted, never
    # dereferenced into the ring. Labeled family: absent from the
    # exposition until the first reject (parity with the threaded
    # engine, which has no descriptor pipe at all).
    desc_rejects = e.telemetry.registry.counter(
        "kwok_shm_desc_rejects_total",
        "Ring descriptors rejected by a lane child's bounds validation "
        "before any shared-memory dereference (corrupt offset/length/"
        "bounds vector), by reason; each reject also raises an "
        "integrity-doubt upcall so the parent re-lists.",
        ("reason",),
    )

    def _desc_reject(kind, reason: str) -> None:
        desc_rejects.labels(reason=reason).inc()
        integ = e._proc_integ
        for k in (kind,) if kind in _KINDS else _KINDS:
            integ[k] = integ.get(k, 0) + 1
        logger.warning(
            "lane %d: rejected %s descriptor (%s)",
            spec["index"], kind, reason,
        )

    def _desc_ok(kind, off, ln, bounds) -> "str | None":
        return _desc_check(
            kind, off, ln, bounds, ring.cap,
            int(ring.arena.hdr[shm_mod.RawRing.W]),
        )

    e.start(spawn_watches=False)
    applied = 0
    stop_status = threading.Event()

    def publish_metrics() -> None:
        """Serialize the lane's WHOLE registry (plus this process's
        error/fault counters) into the seqlock slab the parent merges —
        the 12 StatusBank int64s stop being the only telemetry that
        crosses the process boundary (ISSUE 16)."""
        if mbank is None:
            return
        try:
            doc = {
                "engine": e.telemetry.registry.snapshot(),
                "process": PROCESS_REGISTRY.snapshot(),
            }
            payload = json.dumps(doc).encode()
            if plane is not None and plane.decide("shm.torn") is not None:
                # the writer "dies" mid-slab: odd seq, half a payload —
                # readers must back off and the next write must restamp
                plane.record("shm.torn")
                mbank.torn_write(payload)
                return
            mbank.write(payload)
        except Exception:
            swallowed("proclanes.metrics_publish")

    def status_loop() -> None:
        beats = 0
        while not stop_status.wait(0.05):
            row[shm_mod.BANK_ALIVE_NS] = time.monotonic_ns()
            row[shm_mod.BANK_READY] = int(e.ready)
            sp = e._startup_pending
            row[shm_mod.BANK_RESYNC] = (
                3 if sp is None
                else (0 if "nodes" in sp else 1) | (0 if "pods" in sp else 2)
            )
            row[shm_mod.BANK_NODES] = len(e.nodes.pool)
            row[shm_mod.BANK_PODS] = len(e.pods.pool)
            row[shm_mod.BANK_QDEPTH] = e._q.qsize()
            row[shm_mod.BANK_EVENTS] = applied
            integ = e._proc_integ
            row[shm_mod.BANK_INTEG_NODES] = integ["nodes"]
            row[shm_mod.BANK_INTEG_PODS] = integ["pods"]
            row[shm_mod.BANK_REWIND] = integ["rewind"]
            # drift upcall: the child's shard-scoped auditor degrades
            # the CHILD on an unrepaired-divergence streak; the parent
            # mirrors the bit into its own /readyz (single-process
            # parity — the operator-facing surface is the parent's)
            row[shm_mod.BANK_DRIFT] = int(
                "drift" in e._degradation.reasons
            )
            beats += 1
            if beats % _METRICS_EVERY_BEATS == 0:
                publish_metrics()

    status_thread = spawn_worker(status_loop, name="kwok-lane-status")

    def _on_sigterm(signum, frame):
        # graceful external stop: unwind through finally so engine.stop()
        # dumps the lane's span ring (the cross-process trace contract)
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _on_sigterm)
    rc = 0
    try:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                # parent died: stop cleanly (final checkpoint included)
                logger.warning("lane %d: parent pipe closed", spec["index"])
                break
            t = time.monotonic()
            op = msg[0]
            if op == "STOP":
                break
            if op == "RAWB":
                _op, kind, off, ln, bounds = msg
                bad = _desc_ok(kind, off, ln, bounds)
                if bad is not None:
                    # never dereference: the skipped bytes retire when
                    # the next good read sets the R cursor absolutely,
                    # and the integrity upcall makes the parent re-list
                    _desc_reject(kind, bad)
                    continue
                if plane is not None:
                    stall = plane.decide("shm.stall")
                    if stall is not None:
                        # wedge ring consumption: the parent's router
                        # fills the ring and takes the _RING_STALL_S
                        # drop+resync path (arg = seconds to stall)
                        plane.record("shm.stall")
                        time.sleep(stall.arg or (_RING_STALL_S + 1.0))
                blob = ring.read(off, ln)
                e._q.put((kind, "RAWB", (blob, bounds), t))
                applied += len(bounds) - 1
            elif op == "FAULTSOFF":
                # benchmark quiesce: the parent cleared its own rates
                # and broadcasts the same to every child plane (the
                # convergence/repair phases must run fault-free)
                if plane is not None:
                    plane.spec.rates.clear()
            elif op == "EV":
                _op, kind, type_, obj = msg
                e._q.put((kind, type_, obj, t))
                applied += 1
            elif op == "RESYNC":
                _op, kind, objs = msg
                e._q.put((kind, "RESYNC", objs, t))
            else:
                logger.warning("lane %d: unknown descriptor %r",
                               spec["index"], op)
    except SystemExit:
        logger.info("lane %d: SIGTERM, stopping", spec["index"])
    except BaseException:
        logger.exception("lane %d: reader failed", spec["index"])
        rc = 1
    finally:
        stop_status.set()
        try:
            e.stop()
        except Exception:
            logger.exception("lane %d: stop failed", spec["index"])
            rc = rc or 1
        # the final snapshot: a STOPped lane's last counters survive in
        # the slab for the parent's retired-lane fold. The status thread
        # is joined first — the slab is single-writer by contract.
        if status_thread is not None:
            status_thread.join(timeout=2.0)
        publish_metrics()
        try:
            conn.close()
        except Exception:
            swallowed("proclanes.child_conn_close")
        ring.close()
        slot.close()
        bank.close()
        if mbank is not None:
            mbank.close()
    os._exit(rc)  # skip atexit: jax/absl handlers hang a daemonized child


# -------------------------------------------------------------- parent side


def _garble_desc(plane, off: int, ln: int, bounds: list, cap: int):
    """One seeded descriptor corruption (shm.desc_garble): the three
    shapes a hostile pipe produces — a length past the ring, an offset
    past the published window, a bounds vector inconsistent with the
    length. Every shape MUST be caught by the child's _desc_ok gate
    before any shared-memory dereference."""
    rng, lock = plane._streams["shm.desc_garble"]
    with lock:
        shape = rng.randrange(3)
        jitter = rng.randrange(1, 1 << 20)
    if shape == 0:
        return off, cap + jitter, bounds
    if shape == 1:
        return off + cap + jitter, ln, bounds
    garbled = list(bounds)
    garbled[-1] = garbled[-1] + jitter
    return off, ln, garbled


class ProcLane:
    """Parent-side handle for one lane process: its shm ring + inflight
    slot, descriptor pipe, and the live Process object."""

    def __init__(self, index: int, ring: shm_mod.RawRing,
                 slot: shm_mod.InflightSlot,
                 mbank: "shm_mod.MetricsBank | None" = None):
        self.index = index
        self.ring = ring
        self.slot = slot
        self.mbank = mbank     # telemetry-snapshot slab (ISSUE 16)
        self.retired = None    # dead incarnations' folded final snapshots
        self.proc = None
        self.conn = None
        self.dead = False      # budget exhausted: no more respawns
        self.shedding = False  # router shedding past --shed-queue-depth
        self.restarts = 0

    @property
    def name(self) -> str:
        return f"kwok-lane{self.index}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.is_alive()

    def sigkill(self) -> bool:
        """The fault plane's worker.kill arm: a REAL SIGKILL."""
        p = self.proc
        if p is None or not p.is_alive() or p.pid is None:
            return False
        try:
            os.kill(p.pid, 9)
            return True
        except OSError:
            return False

    def sigstop(self) -> bool:
        """The fault plane's lane.sigstop arm: a REAL SIGSTOP — the
        wedged-but-alive shape. The child stays is_alive() with frozen
        status beats; recovery is the supervisor's stall-kill (SIGKILL
        is deliverable to a stopped process)."""
        p = self.proc
        if p is None or not p.is_alive() or p.pid is None:
            return False
        try:
            os.kill(p.pid, signal.SIGSTOP)
            return True
        except OSError:
            return False


class ProcLaneSet:
    """The parent coordinator for process lanes: router, supervisor,
    status scraping, and lifecycle. Duck-types the LaneSet surface the
    ingest path needs (``n``, ``route``, ``route_batch``)."""

    def __init__(self, parent, n: int):
        self.parent = parent
        self.n = int(n)
        master = getattr(parent.client, "server", "")
        if not (isinstance(master, str) and master.startswith("http")):
            raise ValueError(
                "process lanes need an HTTP --master (lane processes "
                "open their own client/pump connections); got "
                f"{type(parent.client).__name__}"
            )
        self._master = master
        # per-lane row budget: the LaneSet split (even share + 25% crc32
        # slack), floored like _MIN_LANE_ROWS
        self.capacity = max(
            1024,
            -(-int(parent.config.initial_capacity) * 5 // (4 * self.n)),
        )
        self._ctx = None      # spawn context, built in prepare()
        self.lanes: list[ProcLane] = []
        self.bank: shm_mod.StatusBank | None = None
        # router-side per-(lane, kind) raw-line buffers (router thread
        # only — no lock)
        self._buf: dict[tuple[int, str], list] = {}
        self.events_routed = 0
        # graceful degradation stays a ROUTER concern in both lane
        # topologies (children are forced to shed_queue_depth=0): the
        # child's ingest-queue depth rides its StatusBank row and the
        # parent sheds routed events past the threshold exactly like
        # LaneSet._shed — counted, degraded, cleared + resynced by the
        # coordinator once the backlog halves
        self._shed_depth = int(parent.config.shed_queue_depth)
        self._closing = False
        self._respawning = False
        # guards lane handle swaps (supervisor respawn vs close); leaf
        # lock, never held across blocking work (spawn/join/IO happen
        # outside it) — kwoklint table: _proc_lock @ 84
        self._proc_lock = threading.Lock()
        # serializes MetricsBank read/reset against the respawn fold so a
        # scrape can never see one lane's final counters BOTH live in the
        # slab and folded into the retired accumulator (a transient
        # double-count would break counter monotonicity); leaf lock,
        # shm reads + dict folds only — kwoklint table: _mbank_lock @ 84
        self._mbank_lock = threading.Lock()
        r = parent.telemetry.registry
        self._m_restarts = r.counter(
            "kwok_lane_proc_restarts_total",
            "Lane worker-process respawns by the supervisor (SIGKILL, "
            "crash, or chaos worker.kill), by shard.",
            ("shard",),
        )
        self._m_stall_kills = r.counter(
            "kwok_lane_stall_kills_total",
            "Wedged-but-alive lane children SIGKILLed by the supervisor "
            "because their 50ms StatusBank beat went older than "
            "KWOK_TPU_LANE_STALL_S (a stopped/GIL-seized process, not a "
            "crash — crashes ride kwok_lane_proc_restarts_total without "
            "this), by shard.",
            ("shard",),
        )
        self._m_handoff = r.histogram(
            "kwok_lane_handoff_seconds",
            "Router-side wall seconds per cross-process handoff: shared-"
            "memory ring write + descriptor send for one lane's slice of "
            "a parse window.",
        ).child
        self._m_arena = r.gauge(
            "kwok_shm_arena_bytes",
            "Bytes of shared memory mapped per arena pool (ring = raw "
            "event handoff, slot = emit crash-replay, status = lane "
            "status bank, metrics = per-lane telemetry-snapshot slabs). "
            "0 when process lanes are off.",
            ("pool",),
        )
        # the router IS the native pre-partitioned parse consumer in proc
        # mode, so it owns the per-shard routed-event counter the
        # threaded LaneSet exposes — pre-created per shard so the merged
        # exposition carries the family from the first scrape
        from kwok_tpu.telemetry.engine_metrics import _HELP as _ENGINE_HELP

        routed_fam = r.counter(
            "kwok_route_partition_events_total",
            _ENGINE_HELP["kwok_route_partition_events_total"],
            ("shard",),
        )
        self._m_routed = [
            routed_fam.labels(shard=str(i)) for i in range(self.n)
        ]

    # ------------------------------------------------------------ lifecycle

    def prepare(self, executor) -> None:
        """Create the shm arenas and spawn every lane process (spawn
        context only — the parent is already thread-rich, and a fork
        would clone held locks into the child)."""
        import multiprocessing as mp

        self._ctx = mp.get_context("spawn")
        tag = f"{os.getpid()}"
        self.bank = shm_mod.StatusBank(
            shm_mod.arena_name(f"bank-{tag}"), lanes=self.n, create=True
        )
        for i in range(self.n):
            ring = shm_mod.RawRing(
                shm_mod.arena_name(f"ring{i}-{tag}"), _RING_BYTES,
                create=True,
            )
            slot = shm_mod.InflightSlot(
                shm_mod.arena_name(f"slot{i}-{tag}"), _SLOT_BYTES,
                create=True,
            )
            mbank = shm_mod.MetricsBank(
                shm_mod.arena_name(f"metrics{i}-{tag}"), _METRICS_BYTES,
                create=True,
            )
            self.lanes.append(ProcLane(i, ring, slot, mbank))
        self._m_arena.labels(pool="ring").set(_RING_BYTES * self.n)
        self._m_arena.labels(pool="slot").set(_SLOT_BYTES * self.n)
        self._m_arena.labels(pool="status").set(
            self.n * shm_mod.BANK_FIELDS * 8
        )
        self._m_arena.labels(pool="metrics").set(_METRICS_BYTES * self.n)
        for lane in self.lanes:
            self._spawn_lane(lane)
        faults = self.parent._faults
        if faults is not None:
            for lane in self.lanes:
                faults.register_proc_target(
                    lane.name, lane.sigkill, lane.sigstop
                )

    def _lane_spec(self, lane: ProcLane) -> dict:
        from kwok_tpu.resilience.faults import child_spec_text

        trace_base = self.parent.config.trace_dump or os.environ.get(
            "KWOK_TPU_TRACE", ""
        )
        pf = self.parent._faults
        return {
            "index": lane.index,
            "n": self.n,
            "master": self._master,
            "kubeconfig": getattr(
                self.parent.client, "kubeconfig_path", ""
            ),
            "config": self.parent.config,
            "capacity": self.capacity,
            "ring": lane.ring.name,
            "slot": lane.slot.name,
            "bank": self.bank.name,
            "metrics": lane.mbank.name if lane.mbank is not None else "",
            # distinct per-lane path: parent and children each own a file
            # (a shared KWOK_TPU_TRACE would otherwise have every process
            # clobber the same dump at stop)
            "trace_dump": (
                f"{trace_base}.lane{lane.index}" if trace_base else ""
            ),
            # per-lane child fault plane (ISSUE 17): the parent's spec
            # filtered to the kinds the child's boundaries own, re-keyed
            # lane=<i> so every stream re-seeds as (seed, lane, kind);
            # "off" when the parent has no plane or nothing survives
            "faults": child_spec_text(
                pf.spec if pf is not None else None, lane.index
            ),
            # shard-scoped anti-entropy: the parent's RESOLVED interval
            # (0 keeps the child's auditor off via the -1 config force)
            "audit_interval": float(self.parent._audit_interval),
        }

    def _spawn_lane(self, lane: ProcLane) -> None:
        parent_conn, child_conn = self._ctx.Pipe(duplex=False)
        # parent KEEPS the write end; the child gets the read end
        proc = self._ctx.Process(
            target=lane_proc_main,
            args=(self._lane_spec(lane), parent_conn),
            name=lane.name,
            daemon=True,
        )
        proc.start()
        parent_conn.close()  # the child owns the read end now
        with self._proc_lock:
            lane.proc = proc
            lane.conn = child_conn

    def start_workers(self, threads: list) -> None:
        wd = self.parent._watchdog

        def spawn(target, name):
            if wd is not None:
                return wd.spawn(target, name=name)
            return spawn_worker(target, name=name)

        threads.append(spawn(self.route_loop, "kwok-route"))
        # "kwok-proc-super", NOT "kwok-lane-…": the supervisor is the
        # recovery mechanism itself — its name must never match the
        # chaos plane's supervised-prefix kill filter (worker.kill=
        # kwok-lane* would otherwise kill supervision with rotation
        # slot 0 and leave every later lane SIGKILL unrecovered). It IS
        # watchdog-supervised: an exception escaping a respawn (e.g.
        # proc.start() OSError under fd pressure) must restart the loop,
        # not silently end all lane recovery.
        threads.append(spawn(self.supervise_loop, "kwok-proc-super"))

    def close(self) -> None:
        """Graceful stop: STOP every child (they drain + write a final
        checkpoint), join, escalate to kill, unlink every arena."""
        with self._proc_lock:
            self._closing = True
        # a respawn racing shutdown (chaos SIGKILL just before stop())
        # must finish its handle swap before the arenas are unlinked —
        # a child spawned after the unlink would crash on attach and
        # never receive the STOP below. _respawn checks _closing and
        # flips _respawning under the same lock, so after this wait no
        # new spawn can start.
        deadline = time.monotonic() + 20.0
        while self._respawning and time.monotonic() < deadline:
            time.sleep(0.05)
        faults = self.parent._faults
        if faults is not None:
            for lane in self.lanes:
                faults.unregister_proc_target(lane.name)
        for lane in self.lanes:
            conn = lane.conn
            if conn is not None:
                try:
                    conn.send(("STOP",))
                except (OSError, ValueError, BrokenPipeError):
                    swallowed("proclanes.stop_send")
        deadline = time.monotonic() + 30.0
        for lane in self.lanes:
            p = lane.proc
            if p is None:
                continue
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                logger.warning("lane %d did not stop; killing", lane.index)
                p.kill()
                p.join(timeout=5)
        for lane in self.lanes:
            if lane.conn is not None:
                try:
                    lane.conn.close()
                except OSError:
                    swallowed("proclanes.conn_close")
                lane.conn = None
            lane.ring.close(unlink=True)
            lane.slot.close(unlink=True)
            if lane.mbank is not None:
                # the stopped child's final snapshot outlives the arena:
                # folded into the retired accumulator so post-stop reads
                # (tests, a last scrape) keep the full tally
                self._fold_lane_final(lane)
                lane.mbank.close(unlink=True)
                lane.mbank = None
        if self.bank is not None:
            self.bank.close(unlink=True)
            self.bank = None
        for pool in ("ring", "slot", "status", "metrics"):
            self._m_arena.labels(pool=pool).set(0)

    # --------------------------------------------------------------- router

    def route_loop(self) -> None:
        """The LaneSet router loop shape — drain the parent queue, batch-
        parse per half-tick window — with the handoff rewritten for the
        process boundary: per-lane raw slices into the shm ring, window
        flushes as one descriptor per (lane, kind)."""
        parent = self.parent
        q = parent._q
        tel = parent.telemetry
        window = max(0.002, parent.config.tick_interval / 2)
        raw_buf: dict = {}
        try:
            while True:
                try:
                    item = q.get(timeout=0.1)
                except queue.Empty:
                    if not parent._running:
                        return
                    continue
                if item is None:
                    if not parent._running:
                        return
                    continue
                lag = time.monotonic() - item[3]
                parent._drain_apply(item, raw_buf, self.route, self.n)
                window_end = time.monotonic() + window
                while True:
                    timeout = window_end - time.monotonic()
                    if timeout <= 0:
                        break
                    try:
                        item = q.get(timeout=timeout)
                    except queue.Empty:
                        break
                    if item is None:
                        if not parent._running:
                            break
                        continue
                    lag = max(lag, time.monotonic() - item[3])
                    parent._drain_apply(item, raw_buf, self.route, self.n)
                if raw_buf:
                    parent._drain_flush(raw_buf, self.route, self.n)
                self.flush_lanes()
                tel.observe_watch_lag(lag)
                tel.set_gauge("ingest_queue_depth", q.qsize())
                if not parent._running:
                    return
        finally:
            try:
                if raw_buf:
                    parent._drain_flush(raw_buf, self.route, self.n)
                self.flush_lanes()
            except Exception:
                logger.exception("final router flush failed")

    def route(self, kind: str, type_: str, obj) -> None:
        """Per-event route (the non-partitioned fallback path). Raw
        record bytes buffer per (lane, kind) and flush as one ring blob
        per window; dict events ship pickled over the pipe (rare: re-list
        snapshots and the plain-iterator client path)."""
        if type_ == "RESYNC":
            for lane in self.lanes:
                objs = obj if kind == "nodes" else [
                    o for o in obj
                    if shard_of(self._pod_key(o), self.n) == lane.index
                ]
                self._flush_buf(lane, kind)
                self._send(lane, ("RESYNC", kind, objs))
            self.events_routed += 1
            return
        if type_ == "REC":
            raw = obj.raw
            if kind == "nodes":
                for lane in self.lanes:
                    self._buf.setdefault((lane.index, kind), []).append(raw)
            else:
                key = self._rec_key(obj)
                if key is None:
                    return
                li = shard_of(key, self.n)
                self._buf.setdefault((li, kind), []).append(raw)
            self.events_routed += 1
            return
        if not isinstance(obj, dict):
            return
        if kind == "nodes":
            targets = self.lanes
        else:
            key = self._pod_key(obj)
            if not key[1]:
                return
            targets = [self.lanes[shard_of(key, self.n)]]
        for lane in targets:
            if self._shed_check(lane, 1):
                continue
            self._flush_buf(lane, kind)
            self._send(lane, ("EV", kind, type_, obj))
        self.events_routed += 1

    def route_batch(self, kind: str, batch) -> None:
        """Pre-partitioned handoff: the native parse already computed
        per-lane index runs; gather each lane's raw lines into ONE ring
        blob + descriptor. Node batches broadcast every routable record
        to every lane (the tap needs the full node stream)."""
        t0 = time.perf_counter()
        lines = batch.lines
        if kind == "nodes":
            ids = batch.lane_idx[: batch.route_info.routable].tolist()
            parts = [lines[i] for i in ids]
            for lane in self.lanes:
                self._flush_buf(lane, kind)
                self._ship(lane, kind, parts)
                self._m_routed[lane.index].inc(len(parts))
            self.events_routed += len(parts)
        else:
            lane_off = batch.lane_off
            lane_idx = batch.lane_idx
            routed = 0
            for li in range(len(lane_off) - 1):
                lo, hi = lane_off[li], lane_off[li + 1]
                if hi <= lo:
                    continue
                lane = self.lanes[li]
                parts = [lines[i] for i in lane_idx[lo:hi].tolist()]
                self._flush_buf(lane, kind)
                self._ship(lane, kind, parts)
                self._m_routed[li].inc(len(parts))
                routed += len(parts)
            self.events_routed += routed
        self.parent.telemetry.observe_route_batch(
            time.perf_counter() - t0
        )

    def flush_lanes(self) -> None:
        """Window end: ship every buffered (lane, kind) raw slice."""
        if not self._buf:
            return
        for (li, kind) in list(self._buf):
            self._flush_buf(self.lanes[li], kind)

    def _flush_buf(self, lane: ProcLane, kind: str) -> None:
        parts = self._buf.pop((lane.index, kind), None)
        if parts:
            self._ship(lane, kind, parts)

    def _ship(self, lane: ProcLane, kind: str, parts: list) -> None:
        """One (lane, kind) slice onto the lane's ring + pipe. Bytes are
        copied into shared memory exactly once; the descriptor carries
        only offsets. A slice bigger than the ring splits into chunks
        along record bounds (a reconnect flood's window is bounded in
        LINES, not bytes — one oversized window must never crash the
        router). A full ring paces briefly, then — if the child is dead
        or wedged past the stall bound — drops the slice (counted; the
        supervisor's respawn resync re-delivers)."""
        if self._shed_check(lane, len(parts)):
            return
        # chunk bound: HALF the ring, not the ring — try_write pads a
        # wrapping blob to the boundary, so a blob needs pad+n <= free
        # and one wider than cap/2 can be UNWRITABLE forever from an
        # unlucky cursor position even with the ring fully drained
        limit = lane.ring.cap // 2
        total = 0
        for p in parts:
            total += len(p)
        if total > limit:
            keep = []
            for p in parts:
                if len(p) > limit:
                    # a record larger than the guaranteed-writable bound:
                    # undeliverable over this ring
                    self.parent.telemetry.inc("dropped_jobs_total", 1)
                    logger.warning(
                        "lane %d: %s record of %dB exceeds the %dB ring "
                        "bound; dropped (resync re-delivers current "
                        "state)", lane.index, kind, len(p), limit,
                    )
                    self.parent._integrity_resync(kind)
                else:
                    keep.append(p)
            chunk: list = []
            size = 0
            for p in keep:
                if size + len(p) > limit:
                    self._ship(lane, kind, chunk)
                    chunk, size = [], 0
                chunk.append(p)
                size += len(p)
            if chunk:
                self._ship(lane, kind, chunk)
            return
        t0 = time.perf_counter()
        bounds = [0]
        for p in parts:
            bounds.append(bounds[-1] + len(p))
        blob = b"".join(parts)
        deadline = time.monotonic() + _RING_STALL_S
        off = lane.ring.try_write(blob)
        while off is None:
            if self._closing or not lane.alive() or (
                time.monotonic() >= deadline
            ):
                self.parent.telemetry.inc("dropped_jobs_total", len(parts))
                logger.warning(
                    "lane %d ring full (%s): dropped %d events",
                    lane.index, "dead child" if not lane.alive()
                    else "stalled child", len(parts),
                )
                if not self._closing:
                    # a dead child's respawn resyncs, but an alive-slow
                    # child never respawns — the drop itself must
                    # schedule the (rate-bounded) full re-list, or the
                    # dropped events are permanent divergence
                    self.parent._integrity_resync(kind)
                return
            time.sleep(0.001)
            off = lane.ring.try_write(blob)
        faults = self.parent._faults
        if faults is not None:
            if faults.decide("shm.desc_drop") is not None:
                # the descriptor dies between ring write and pipe send:
                # the blob's bytes retire implicitly (the reader's next
                # good read sets the R cursor absolutely), and the drop
                # must schedule the re-list or the events are permanent
                # divergence — same recovery as the ring-stall drop
                faults.record("shm.desc_drop")
                self.parent.telemetry.inc("dropped_jobs_total", len(parts))
                self.parent._integrity_resync(kind)
                return
            if faults.decide("shm.desc_garble") is not None:
                faults.record("shm.desc_garble")
                off, blob_len, bounds = _garble_desc(
                    faults, off, len(blob), bounds, lane.ring.cap
                )
                self._send(lane, ("RAWB", kind, off, blob_len, bounds))
                self._m_handoff.observe(time.perf_counter() - t0)
                return
        self._send(lane, ("RAWB", kind, off, len(blob), bounds))
        self._m_handoff.observe(time.perf_counter() - t0)

    def _lane_qdepth(self, lane: ProcLane) -> int:
        bank = self.bank
        rows = bank.rows if bank is not None else None
        if rows is None:
            return 0
        return int(rows[lane.index, shm_mod.BANK_QDEPTH])

    def _shed_check(self, lane: ProcLane, n: int) -> bool:
        """Parent-side twin of LaneSet._shed: sheds ``n`` routed events
        when the child's ingest queue (read from its StatusBank row;
        the 50ms refresh only delays the trip by one beat) is deeper
        than --shed-queue-depth — counted in kwok_dropped_jobs_total and
        surfaced as the lane<N>_queue degraded reason. The coordinator
        clears + resyncs once the backlog halves, so shedding trades
        freshness, not permanent state (the LaneSet contract)."""
        if not self._shed_depth or self._lane_qdepth(lane) <= (
            self._shed_depth
        ):
            return False
        self.parent.telemetry.inc("dropped_jobs_total", n)
        lane.shedding = True
        if self.parent._degradation.set(f"lane{lane.index}_queue"):
            logger.warning(
                "lane %d queue past %d: shedding routed events "
                "(engine degraded)", lane.index, self._shed_depth,
            )
        return True

    def _send(self, lane: ProcLane, msg) -> None:
        conn = lane.conn
        if conn is None:
            return
        try:
            conn.send(msg)
        except (OSError, ValueError, BrokenPipeError):
            # dead child mid-send: the supervisor owns recovery
            swallowed("proclanes.send_dead_lane")

    def quiesce_child_faults(self) -> None:
        """Broadcast FAULTSOFF to every lane child: zero their planes'
        rates over the descriptor pipe. The benchmark quiesce phase —
        the caller clears the PARENT's rates itself; convergence/repair
        oracles then run fault-free on both sides of the boundary."""
        for lane in self.lanes:
            self._send(lane, ("FAULTSOFF",))

    @staticmethod
    def _rec_key(rec):
        name = rec.name
        if not name:
            return None
        return (rec.namespace or "default", name)

    @staticmethod
    def _pod_key(obj: dict):
        meta = obj.get("metadata") or {}
        return (meta.get("namespace") or "default", meta.get("name") or "")

    # ----------------------------------------------------------- supervisor

    def supervise_loop(self) -> None:
        """Process-level watchdog: a lane process that exits without a
        STOP is a crash — charge the SAME restart budget the thread
        watchdog uses, replay its emit crash-replay slot, respawn it,
        and resync the streams so the re-list re-delivers whatever died
        with it. Budget exhaustion degrades, exactly like a thread
        crash-loop."""
        parent = self.parent
        while parent._running and not self._closing:
            time.sleep(_SUPER_POLL_S)
            for lane in self.lanes:
                if self._closing or not parent._running:
                    return
                p = lane.proc
                if p is None or lane.dead:
                    continue
                if p.is_alive():
                    # hung-child detection: the status loop beats the
                    # lane's BANK_ALIVE_NS every 50ms (CLOCK_MONOTONIC is
                    # system-wide, comparable across processes); a live
                    # process whose beat is older than the stall bound is
                    # wedged — SIGKILL it and let the dead-path below
                    # charge + respawn on the next poll. The stamp is
                    # zeroed at respawn, so a fresh child importing jax
                    # is never judged by its predecessor's clock.
                    bank = self.bank
                    rows = bank.rows if bank is not None else None
                    if rows is not None:
                        beat = int(rows[lane.index,
                                        shm_mod.BANK_ALIVE_NS])
                        if beat and (
                            time.monotonic_ns() - beat > _STALL_NS
                        ):
                            logger.warning(
                                "lane %d wedged (no status beat for "
                                "%.0fs); killing for respawn",
                                lane.index, _STALL_NS / 1e9,
                            )
                            if lane.sigkill():
                                # a stall-kill is NOT a crash: count it
                                # apart from the respawn counter, and
                                # degrade transiently (cleared at the
                                # respawn — the shard is dark until
                                # then) so /readyz tells the truth
                                self._m_stall_kills.labels(
                                    shard=str(lane.index)
                                ).inc()
                                parent._degradation.set(
                                    f"lane{lane.index}_stalled"
                                )
                    continue
                rc = p.exitcode
                logger.warning(
                    "lane %d process died (exit %s)", lane.index, rc
                )
                worker_crashed(lane.name)
                wd = parent._watchdog
                if wd is not None and not wd.charge(lane.name):
                    lane.dead = True
                    parent._worker_budget_exhausted(lane.name)
                    continue
                self._respawn(lane)

    def _respawn(self, lane: ProcLane) -> None:
        with self._proc_lock:
            if self._closing:
                return  # close() owns the endgame; don't race the unlink
            self._respawning = True
        try:
            self._do_respawn(lane)
        finally:
            self._respawning = False

    def _do_respawn(self, lane: ProcLane) -> None:
        # 1. replay the emit crash-replay slot BEFORE the new child can
        #    emit anything: at-least-once, ordered ahead of post-respawn
        #    traffic (echo drop / repair no-op absorb duplicates)
        payload = lane.slot.peek()
        if payload is not None:
            try:
                self._replay_frames(pickle.loads(payload))
                lane.slot.clear()
            except Exception:
                logger.exception(
                    "lane %d: inflight replay failed (checkpoint replay "
                    "still covers the slice)", lane.index,
                )
        # 2. unread ring bytes died with the child's descriptors; the
        #    dead child's status stamp must not feed the stall detector
        lane.ring.reset()
        if self.bank is not None:
            self.bank.rows[lane.index, shm_mod.BANK_ALIVE_NS] = 0
        # 2b. fold the dead incarnation's last telemetry snapshot into
        #     the retired accumulator (and empty the slab) so the merged
        #     counters stay monotonic while the fresh child restarts at 0
        self._fold_lane_final(lane)
        old_conn = lane.conn
        if old_conn is not None:
            try:
                old_conn.close()
            except OSError:
                swallowed("proclanes.respawn_conn_close")
        # 3. respawn + account
        self._spawn_lane(lane)
        lane.restarts += 1
        self._m_restarts.labels(shard=str(lane.index)).inc()
        # a stall-killed lane is back: the transient degraded reason
        # (set by the supervisor's wedged-child branch) lifts here
        self.parent._degradation.clear(f"lane{lane.index}_stalled")
        worker_restarted(lane.name)
        logger.warning("lane %d respawned (pid %s)", lane.index,
                       lane.proc.pid)
        # 4. the data half: only a full list+RESYNC provably re-delivers
        #    what the dead process took with it (the PR 6/7 contract)
        self.parent.resync_streams()

    def _replay_frames(self, requests: list) -> None:
        """Send a dead lane's parked emit frames from the parent: plain
        HTTP, one connection, sequential (the batch is small — one emit
        window). Status codes are advisory: 4xx here means the echo
        already landed or the object moved on, which the repair path
        owns either way."""
        if not requests:
            return
        from urllib.parse import urlsplit

        u = urlsplit(self._master)
        if u.scheme == "https":
            conn = http.client.HTTPSConnection(
                u.hostname, u.port or 443, timeout=10
            )
        else:
            conn = http.client.HTTPConnection(
                u.hostname, u.port or 80, timeout=10
            )
        try:
            for r in requests:
                method, path, body = r[0], r[1], r[2]
                ct = r[3] if len(r) > 3 else "application/json"
                if isinstance(path, (bytes, bytearray)):
                    path = path.decode()
                conn.request(
                    method, path, body=bytes(body),
                    headers={"Content-Type": ct or "application/json"},
                )
                conn.getresponse().read()
        finally:
            conn.close()

    # ---------------------------------------------------------- coordinator

    def coordinator_loop(self) -> None:
        """Runs as the engine's kwok-tick thread: no device state at the
        parent — the status scrape (gauges + the startup gate) plus the
        child->parent healing upcalls, at the tick cadence."""
        parent = self.parent
        interval = max(0.02, parent.config.tick_interval)
        tel = parent.telemetry
        seen_integ = {("nodes", i): 0 for i in range(self.n)}
        seen_integ.update({("pods", i): 0 for i in range(self.n)})
        seen_rewind = [0] * self.n
        seen_rewind_gen = [0] * self.n
        while parent._running:
            time.sleep(interval)
            bank = self.bank
            if bank is None:
                continue
            rows = bank.rows
            tel.set_gauge("nodes_managed", int(rows[:, shm_mod.BANK_NODES].sum()))
            tel.set_gauge("pods_managed", int(rows[:, shm_mod.BANK_PODS].sum()))
            tel.set_gauge(
                "ingest_queue_depth",
                max(parent._q.qsize(),
                    int(rows[:, shm_mod.BANK_QDEPTH].max())),
            )
            if parent._startup_pending is not None:
                for lane in self.lanes:
                    mask = int(rows[lane.index, shm_mod.BANK_RESYNC])
                    if mask & 1:
                        parent._mark_resync("nodes", lane.index)
                    if mask & 2:
                        parent._mark_resync("pods", lane.index)
                parent._ckpt_gate(dispatched=True, staged=False)
            # healing upcalls: counter deltas -> the real (rate-bounded)
            # stream machinery on the parent, which owns the watches
            for lane in self.lanes:
                i = lane.index
                if lane.restarts != seen_rewind_gen[i]:
                    # a respawned child's counters restart at zero
                    seen_rewind_gen[i] = lane.restarts
                    seen_integ[("nodes", i)] = 0
                    seen_integ[("pods", i)] = 0
                    seen_rewind[i] = 0
                for kind, field in (
                    ("nodes", shm_mod.BANK_INTEG_NODES),
                    ("pods", shm_mod.BANK_INTEG_PODS),
                ):
                    v = int(rows[i, field])
                    if v > seen_integ[(kind, i)]:
                        seen_integ[(kind, i)] = v
                        parent._integrity_resync(kind)
                v = int(rows[i, shm_mod.BANK_REWIND])
                if v > seen_rewind[i]:
                    seen_rewind[i] = v
                    now = time.monotonic()
                    if now - parent._rv_rewind_at >= 5.0:
                        parent._rv_rewind_at = now
                        parent._inc("rv_rewinds_total")
                        logger.warning(
                            "lane %d reported a re-list rv rewind "
                            "(store restore signature); resyncing all "
                            "streams", i,
                        )
                        parent.resync_streams()
            # drift mirror (ISSUE 17): any child whose shard-scoped
            # auditor holds an unrepaired-divergence streak publishes
            # BANK_DRIFT=1; the parent's /readyz degrades on "drift"
            # exactly as the single-process auditor would, and clears
            # once every child's streak has healed
            if any(
                int(rows[lane.index, shm_mod.BANK_DRIFT])
                for lane in self.lanes
            ):
                if parent._degradation.set("drift"):
                    logger.warning(
                        "lane auditor reported an unrepaired-divergence "
                        "streak; engine degraded (drift)"
                    )
            elif parent._degradation.clear("drift"):
                logger.info("lane drift repaired; degraded reason cleared")
            if self._shed_depth:
                # shed-clear, the LaneSet drain_loop contract: backlog
                # halved -> clear the degraded reason + resync (shed
                # events are GONE; only the full re-list re-delivers
                # them), rate-limited so a re-list burst re-tripping
                # shedding can't hammer the apiserver with LISTs
                from kwok_tpu.engine.lanes import _SHED_RESYNC_MIN_S

                for lane in self.lanes:
                    if not lane.shedding or self._lane_qdepth(
                        lane
                    ) * 2 > self._shed_depth:
                        continue
                    now = time.monotonic()
                    if now - parent._shed_resync_at < _SHED_RESYNC_MIN_S:
                        continue
                    parent._shed_resync_at = now
                    lane.shedding = False
                    if parent._degradation.clear(
                        f"lane{lane.index}_queue"
                    ):
                        logger.info(
                            "lane %d drained below shed threshold; "
                            "degraded reason cleared; resyncing streams "
                            "to re-deliver shed events", lane.index,
                        )
                        parent.resync_streams()

    # ------------------------------------------------------------- readouts

    def _lane_doc(self, lane: ProcLane) -> "dict | None":
        """One consistent telemetry snapshot off a lane's seqlock slab
        (None before the child's first publish)."""
        if lane.mbank is None:
            return None
        raw = lane.mbank.read()
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def _fold_lane_final(self, lane: ProcLane) -> None:
        """Fold a dying/stopped incarnation's last snapshot into the
        lane's retired accumulator and empty the slab — under
        ``_mbank_lock`` so a concurrent scrape can never count the same
        final snapshot twice (or see it vanish mid-fold)."""
        from kwok_tpu.telemetry.registry import fold_snapshot

        with self._mbank_lock:
            doc = self._lane_doc(lane)
            if doc is None:
                return
            if lane.mbank is not None:
                lane.mbank.reset()
            acc = lane.retired or {}
            for part in ("engine", "process"):
                snap = doc.get(part)
                if snap:
                    acc[part] = fold_snapshot(acc.get(part), snap)
            lane.retired = acc

    def merged_metrics_text(self) -> str:
        """The proc-lane `/metrics` body: the parent registry plus every
        lane's shm snapshot merged into ONE scratch registry (one TYPE
        declaration per family — the strict exposition oracle's
        contract), lane stage/queue families label-split per shard, and
        retired incarnations keeping aggregate counters monotonic."""
        from kwok_tpu.telemetry.engine_metrics import merge_proc_lane_metrics

        live: dict = {}
        retired: dict = {}
        with self._mbank_lock:
            for lane in self.lanes:
                doc = self._lane_doc(lane)
                if doc and doc.get("engine"):
                    live[lane.index] = doc["engine"]
                if lane.retired and lane.retired.get("engine"):
                    retired[lane.index] = lane.retired["engine"]
        depths: dict = {}
        rows = self.bank.rows if self.bank is not None else None
        if rows is not None:
            for lane in self.lanes:
                depths[lane.index] = int(
                    rows[lane.index, shm_mod.BANK_QDEPTH]
                )
        reg = merge_proc_lane_metrics(
            self.parent.telemetry.registry.snapshot(),
            live, retired, self.n, queue_depths=depths,
        )
        return reg.render()

    def merged_process_text(self) -> str:
        """The process-global error/fault block with every lane's share
        aggregated in (kwok_swallowed_errors_total, kwok_wire_rejects_
        total, kwok_faults_injected_total, worker crash/restart ledgers)
        — one registry render, so each family keeps a single TYPE line."""
        from kwok_tpu.telemetry.registry import (
            family_from_doc,
            merge_child,
            registry_from_snapshot,
        )

        reg = registry_from_snapshot(PROCESS_REGISTRY.snapshot())
        with self._mbank_lock:
            docs = []
            for lane in self.lanes:
                doc = self._lane_doc(lane)
                if doc and doc.get("process"):
                    docs.append(doc["process"])
                if lane.retired and lane.retired.get("process"):
                    docs.append(lane.retired["process"])
        for snap in docs:
            for name, doc in sorted(snap.items()):
                fam = family_from_doc(reg, name, doc)
                for values, v in doc.get("children", ()):
                    merge_child(fam, values, v)
        text = reg.render()
        return "" if not text.strip() else text

    def status(self) -> list[dict]:
        """Per-lane status rows (tests, tooling, the proc-check gate)."""
        out = []
        rows = self.bank.rows if self.bank is not None else None
        for lane in self.lanes:
            r = rows[lane.index] if rows is not None else None
            out.append({
                "index": lane.index,
                "alive": lane.alive(),
                "pid": lane.proc.pid if lane.proc is not None else None,
                "restarts": lane.restarts,
                "ready": bool(r is not None and r[shm_mod.BANK_READY]),
                "nodes": int(r[shm_mod.BANK_NODES]) if r is not None else 0,
                "pods": int(r[shm_mod.BANK_PODS]) if r is not None else 0,
            })
        return out
