"""FederatedEngine: N simulated clusters, one device mesh (BASELINE config 5).

The reference is a single Go process bound to a single apiserver; its only
scale-out story is "run more kwok processes". Here the multi-cluster case is
a first-class device-level construct: N member clusters — each with its own
apiserver, watch streams, IP pool, and patch executor — share ONE stacked
row-state tensor of shape [N * R] sharded over the TPU mesh, ticked by ONE
jitted shard_map'd kernel per resource kind. With N == mesh size each
cluster's rows land whole on one core ("8 kwok apiservers sharded
1-per-TPU-core"); otherwise the flat row axis still shards evenly and
correctness is unchanged (rows are independent).

Host side, each member is a full ClusterEngine minus its tick thread
(start(run_tick_loop=False)): ingest queues and patch egress stay
per-cluster (per-apiserver HTTP fan-out, like the reference's per-process
parallelTasks pools), while state mutation and rule evaluation are batched
across clusters in the shared tick.

All members must share one lifecycle rule set (the compiled rule table is
baked into the jitted kernel). Heterogeneous-rule federations would need one
kernel per rule-set group — out of scope, as is cross-cluster scheduling
(federated *scheduling* is the real scheduler's job; we simulate the
kubelets under it).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import queue
import threading
import time

import numpy as np

from kwok_tpu.edge.kubeclient import KubeClient
from kwok_tpu.edge.render import now_rfc3339
from kwok_tpu.engine.engine import ClusterEngine, EngineConfig
from kwok_tpu.models.defaults import SEL_HEARTBEAT
from kwok_tpu.ops.state import RowState, new_row_state
from kwok_tpu.ops.tick import (
    REBASE_AFTER,
    MultiTickKernel,
    rebase_times,
    to_host,
    unpack_wire,
)
from kwok_tpu.parallel import make_mesh

logger = logging.getLogger("kwok_tpu.federation")


def _pad_cluster_capacity(r: int, n_clusters: int, n_devices: int) -> int:
    """Smallest R' >= r such that n_clusters * R' shards evenly."""
    step = n_devices // math.gcd(n_clusters, n_devices)
    return ((r + step - 1) // step) * step


class FederatedEngine:
    """Drive N member clusters from one stacked, mesh-sharded tick."""

    def __init__(
        self,
        clients: list[KubeClient],
        config: EngineConfig,
        mesh=None,
    ) -> None:
        if not clients:
            raise ValueError("federation needs at least one cluster")
        self.mesh = mesh if mesh is not None else make_mesh()
        n = len(clients)
        d = int(self.mesh.devices.size)
        self.cluster_capacity = _pad_cluster_capacity(
            max(int(config.initial_capacity), 1), n, d
        )

        self.engines: list[ClusterEngine] = []
        for client in clients:
            cfg = dataclasses.replace(
                config, initial_capacity=self.cluster_capacity, use_mesh=False
            )
            self.engines.append(ClusterEngine(client, cfg))

        e0 = self.engines[0]
        # ONE fused kernel for both kinds across the whole stacked state
        # (rule tables are e0's — all members share them): one dispatch and
        # one packed-wire D2H per federated tick (ops/tick.MultiTickKernel).
        hb_bit = e0.node_bits[SEL_HEARTBEAT]
        steps = max(1, int(getattr(config, "tick_substeps", 1)))
        self._fused = MultiTickKernel(
            [
                (e0.nodes.table, config.heartbeat_interval, (), hb_bit),
                (e0.pods.table, config.heartbeat_interval, (), -1),
            ],
            mesh=self.mesh,
            pack=True,
            steps=steps,
            dt=config.tick_interval / steps,
        )

        # Shared engine epoch so one `now` is correct for every member.
        self._epoch = time.time()
        for e in self.engines:
            e._epoch = self._epoch

        cap = self.cluster_capacity * n
        self._stacked: dict[str, RowState] = {
            "nodes": self._fused.place(new_row_state(cap)),
            "pods": self._fused.place(new_row_state(cap)),
        }

        self.config = config
        self._running = False
        self._thread: threading.Thread | None = None
        # monotonic wake-up for the idle tick loop (see ClusterEngine):
        # 0 = tick immediately, None = nothing scheduled on device
        self._idle_wake: float | None = 0.0

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._running = True
        for e in self.engines:
            e.start(run_tick_loop=False)
        self._thread = threading.Thread(
            target=self._tick_loop, name="kwok-fed-tick", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        # join the shared tick first so it cannot submit patch jobs to
        # members whose executors are already shut down
        if self._thread is not None:
            self._thread.join(timeout=5)
        for e in self.engines:
            e.stop()

    # ------------------------------------------------------------- tick loop

    _IDLE_MAX = 60.0

    def _tick_loop(self) -> None:
        interval = self.config.tick_interval
        while self._running:
            deadline = time.monotonic() + interval
            if all(e._q.empty() for e in self.engines) and not any(
                k.buffer.pending
                for e in self.engines
                for k in (e.nodes, e.pods)
            ):
                # idle: sleep toward the device-reported next deadline
                # (ops/tick.next_due); arriving events shorten the drain
                wake = self._idle_wake
                if wake is None:
                    deadline = time.monotonic() + self._IDLE_MAX
                elif wake > deadline:
                    deadline = min(wake, time.monotonic() + self._IDLE_MAX)
            self._drain_ingest(deadline)
            try:
                self.tick_once()
            except Exception:
                logger.exception("federated tick failed")

    def _drain_ingest(self, deadline: float) -> None:
        """Round-robin the members' ingest queues until the tick is due.
        An arriving event during an extended idle sleep pulls the deadline
        back to one normal interval; consecutive empty polls back off
        exponentially so idling costs ~no wakeups."""
        lag: dict[int, float] = {}
        interval = self.config.tick_interval
        idle_sleep = 0.002
        got_event = False
        try:
            while self._running:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                drained_any = False
                for i, e in enumerate(self.engines):
                    while True:
                        try:
                            item = e._q.get_nowait()
                        except queue.Empty:
                            break
                        if item is None:
                            continue
                        drained_any = True
                        lag[i] = max(
                            lag.get(i, 0.0), time.monotonic() - item[3]
                        )
                        e._ingest_safe(*item[:3])
                if drained_any:
                    idle_sleep = 0.002
                    if not got_event:
                        got_event = True
                        deadline = min(
                            deadline, time.monotonic() + interval
                        )
                else:
                    time.sleep(min(remaining, idle_sleep))
                    idle_sleep = min(idle_sleep * 2, 0.1)
        finally:
            # slowest enqueue->processing delay this tick; 0 on a quiet tick
            for i, e in enumerate(self.engines):
                with e._metrics_lock:
                    e.metrics["watch_lag_seconds"] = lag.get(i, 0.0)
                    e.metrics["ingest_queue_depth"] = e._q.qsize()

    # ------------------------------------------------------------------ tick

    def tick_once(self) -> None:
        self._maybe_regrow()
        t0 = time.perf_counter()
        now = time.time() - self._epoch
        if now >= REBASE_AFTER:
            # shared-epoch rebase (see ClusterEngine.tick_once): shift the
            # stacked time fields and every member's epoch together
            self._epoch += now
            for e in self.engines:
                e._epoch = self._epoch
            for kind in ("nodes", "pods"):
                self._stacked[kind] = rebase_times(self._stacked[kind], now)
            now = 0.0
        now_str = now_rfc3339()
        r = self.cluster_capacity
        any_rows = False
        for kind in ("nodes", "pods"):
            state = self._stacked[kind]
            for c, e in enumerate(self.engines):
                k = e.nodes if kind == "nodes" else e.pods
                if k.buffer.pending:
                    state = k.buffer.flush(state, offset=c * r)
                    any_rows = True
                elif len(k.pool):
                    any_rows = True
            self._stacked[kind] = state
        if any_rows:
            # with substeps, anchor the LAST scan step at wall-now
            now_base = now - (self._fused.steps - 1) * self._fused.dt
            (nout, pout), wire = self._fused(
                (self._stacked["nodes"], self._stacked["pods"]), now_base
            )
            self._stacked["nodes"] = nout.state
            self._stacked["pods"] = pout.state
            cap = r * len(self.engines)
            counters, masks_fn, dues = unpack_wire(np.asarray(wire), [cap, cap])
            nd = float(dues.min())
            self._idle_wake = (
                None if nd == float("inf")
                else time.monotonic() + max(0.0, nd - now)
            )
            masks = masks_fn() if counters.any() else None
            for i, (kind, out) in enumerate((("nodes", nout), ("pods", pout))):
                if not (int(counters[i]) or int(counters[2 + i])):
                    continue
                dirty, deleted, hb = masks[i]
                phase = np.asarray(out.state.phase)
                cond = np.asarray(out.state.cond_bits)
                for c, e in enumerate(self.engines):
                    k = e.nodes if kind == "nodes" else e.pods
                    lo, hi = c * r, (c + 1) * r
                    d_c, del_c, hb_c = dirty[lo:hi], deleted[lo:hi], hb[lo:hi]
                    trans_c = int(
                        np.count_nonzero(d_c) + np.count_nonzero(del_c)
                    )
                    if trans_c:
                        e._inc("transitions_total", trans_c)
                    if trans_c or hb_c.any():
                        k.phase_h = phase[lo:hi].copy()
                        k.cond_h = cond[lo:hi].copy()
                        e._emit(kind, k, d_c, del_c, hb_c, now_str)
        else:
            self._idle_wake = None  # empty federation: sleep until events
        elapsed = time.perf_counter() - t0
        for e in self.engines:
            with e._metrics_lock:
                e.metrics["ticks_total"] += 1
                e.metrics["tick_seconds_sum"] += elapsed
                e.metrics["tick_seconds_last"] = elapsed
                e.metrics["nodes_managed"] = len(e.nodes.pool)
                e.metrics["pods_managed"] = len(e.pods.pool)

    # ------------------------------------------------------------------ grow

    def _maybe_regrow(self) -> None:
        """If any member's pool grew (ClusterEngine._grow during ingest),
        rebuild the stacked state at the new common per-cluster capacity."""
        want = max(k.capacity for e in self.engines for k in (e.nodes, e.pods))
        if want <= self.cluster_capacity:
            return
        n = len(self.engines)
        d = int(self.mesh.devices.size)
        new_r = _pad_cluster_capacity(want, n, d)
        old_r = self.cluster_capacity
        logger.info("federation regrow: %d -> %d rows/cluster", old_r, new_r)
        for e in self.engines:
            for k in (e.nodes, e.pods):
                if k.capacity < new_r:
                    k.grow(new_r)
        for kind in ("nodes", "pods"):
            host = to_host(self._stacked[kind])
            stacked = new_row_state(new_r * n)
            for c in range(n):
                for f in RowState._fields:
                    getattr(stacked, f)[c * new_r : c * new_r + old_r] = getattr(
                        host, f
                    )[c * old_r : (c + 1) * old_r]
            self._stacked[kind] = self._fused.place(stacked)
        self.cluster_capacity = new_r

    # --------------------------------------------------------------- metrics

    @property
    def metrics(self) -> dict:
        """Aggregated counters across members (gauges are summed too —
        nodes/pods managed are totals across the federation)."""
        agg: dict[str, float] = {}
        for e in self.engines:
            with e._metrics_lock:
                for name, v in e.metrics.items():
                    if name == "watch_lag_seconds":
                        # worst-case lag, not a sum over members
                        agg[name] = max(agg.get(name, 0.0), v)
                    else:
                        agg[name] = agg.get(name, 0) + v
        if self.engines:
            n = len(self.engines)
            # every member records the same shared-tick values; un-sum them
            for name in ("ticks_total", "tick_seconds_sum", "tick_seconds_last"):
                agg[name] = agg[name] / n
        return agg
