"""FederatedEngine: N simulated clusters, one device mesh (BASELINE config 5).

The reference is a single Go process bound to a single apiserver; its only
scale-out story is "run more kwok processes". Here the multi-cluster case is
a first-class device-level construct: N member clusters — each with its own
apiserver, watch streams, IP pool, and patch executor — share ONE stacked
row-state tensor of shape [N * R] sharded over the TPU mesh, ticked by ONE
jitted shard_map'd kernel per resource kind. With N == mesh size each
cluster's rows land whole on one core ("8 kwok apiservers sharded
1-per-TPU-core"); otherwise the flat row axis still shards evenly and
correctness is unchanged (rows are independent).

Host side, each member is a full ClusterEngine minus its tick thread
(start(run_tick_loop=False)): ingest queues and patch egress stay
per-cluster (per-apiserver HTTP fan-out, like the reference's per-process
parallelTasks pools), while state mutation and rule evaluation are batched
across clusters in the shared tick.

Members MAY run different lifecycle rule sets (`member_configs`): the
compiled rule table is baked into each jitted kernel, so members are
grouped by (rule tables, heartbeat interval) and each group gets its own
stacked state + fused kernel — one dispatch per GROUP per tick, which
degenerates to the single-dispatch fast path when all members share rules
(the common case, and the only case round 1 supported). Out of scope:
cross-cluster scheduling (federated *scheduling* is the real scheduler's
job; we simulate the kubelets under it).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import queue
import threading
import time

import numpy as np

from kwok_tpu.edge.kubeclient import KubeClient
from kwok_tpu.edge.render import now_rfc3339
from kwok_tpu.engine.engine import ClusterEngine, EngineConfig
from kwok_tpu.models.defaults import SEL_HEARTBEAT
from kwok_tpu.ops.state import RowState, new_row_state
from kwok_tpu.ops.tick import (
    REBASE_AFTER,
    MultiTickKernel,
    rebase_times,
    to_host,
    unpack_wire,
)
from kwok_tpu.parallel import make_mesh
from kwok_tpu.telemetry import (
    EngineTelemetry,
    MetricsRegistry,
    Tracer,
    merge_chrome_traces,
)

logger = logging.getLogger("kwok_tpu.federation")


def _pad_cluster_capacity(r: int, n_clusters: int, n_devices: int) -> int:
    """Smallest R' >= r such that n_clusters * R' shards evenly."""
    step = n_devices // math.gcd(n_clusters, n_devices)
    return ((r + step - 1) // step) * step


def _table_bytes(tab) -> bytes:
    """Canonical bytes of a CompiledRules table (grouping key). The phase
    vocabulary is part of the key: Stage docs can extend the space past the
    canonical prefix (compiler.compile_rules), and two numerically identical
    tables whose extra ids name DIFFERENT phases must not share a kernel —
    the rendered phase strings would be wrong for one member."""
    return b"|".join(
        [
            np.ascontiguousarray(getattr(tab, f)).tobytes()
            for f in (
                "from_mask", "deletion", "selector_bit", "delay_kind",
                "delay_a", "delay_b", "to_phase", "cond_assign",
                "cond_value", "is_delete", "weight",
            )
        ]
        + [
            "\x1f".join(tab.space.phases).encode(),
            "\x1f".join(tab.space.conditions).encode(),
        ]
    )


@dataclasses.dataclass
class _FedPending:
    """A dispatched-but-unconsumed group tick in the pipelined loop."""

    group: "_Group"
    wire: object  # device array; self-contained (pack_rows)
    r: int  # rows per cluster AT DISPATCH (regrow may change it)
    cap: int  # stacked capacity at dispatch
    seqs: list  # per-member release seq at dispatch (stale-mask filter)
    now: float  # engine time of the dispatch
    mono: float  # monotonic clock at dispatch (idle-wake anchor)
    flush_s: float


class _Group:
    """Members sharing one compiled rule set: one stacked state and one
    fused kernel (the round-1 whole-federation layout, now per group)."""

    def __init__(self, engines, cfg, mesh):
        self.engines = engines  # ClusterEngines, federation order preserved
        self.r = 0  # rows per cluster; set by alloc
        # fused-kernel launch counter: the registry child (set by
        # FederatedEngine right after group construction) is the single
        # source of truth; `dispatches` below is the legacy read view
        self.dispatch_counter = None
        # monotonic device-timer deadline from this group's newest consumed
        # tick (None = nothing scheduled); the loop gate takes the min
        self.wake: float | None = 0.0
        e0 = engines[0]
        hb_bit = e0.node_bits[SEL_HEARTBEAT]
        steps = max(1, int(getattr(cfg, "tick_substeps", 1)))
        self.fused = MultiTickKernel(
            [
                (e0.nodes.table, cfg.heartbeat_interval, (), hb_bit),
                (e0.pods.table, cfg.heartbeat_interval, (), -1),
            ],
            mesh=mesh,
            pack=True,
            pack_rows=True,  # self-contained wire: pipelined consume
            steps=steps,
            dt=cfg.tick_interval / steps,
        )
        self.stacked: dict[str, RowState] = {}

    @property
    def dispatches(self) -> int:
        """Fused-kernel launches so far (legacy view of the counter)."""
        return self.dispatch_counter.value if self.dispatch_counter else 0

    def alloc(self, r: int) -> None:
        self.r = r
        cap = r * len(self.engines)
        self.stacked = {
            "nodes": self.fused.place(new_row_state(cap)),
            "pods": self.fused.place(new_row_state(cap)),
        }


class FederatedEngine:
    """Drive N member clusters from one stacked, mesh-sharded tick per
    rule-set group (a single group — and a single dispatch — when all
    members share rules)."""

    def __init__(
        self,
        clients: list[KubeClient],
        config: EngineConfig,
        mesh=None,
        member_configs: list[EngineConfig] | None = None,
    ) -> None:
        if not clients:
            raise ValueError("federation needs at least one cluster")
        if member_configs is not None and len(member_configs) != len(clients):
            raise ValueError(
                f"member_configs has {len(member_configs)} entries "
                f"for {len(clients)} clusters"
            )
        self.mesh = mesh if mesh is not None else make_mesh()
        d = int(self.mesh.devices.size)
        cfgs = member_configs if member_configs is not None else [config] * len(clients)
        # the stacked tick holds every member's rows in one [n_members, cap]
        # array, so capacity must be uniform — honor heterogeneous
        # member_configs by sizing for the largest request (a member asking
        # for more capacity gets it; nobody is silently undersized)
        base_capacity = max(
            1,
            int(config.initial_capacity),
            *(int(c.initial_capacity) for c in cfgs),
        )

        # ONE registry for the whole federation: every member registers the
        # same families and writes its own shard-labeled children, so
        # /metrics exports per-shard series (shard="0".."N-1") instead of
        # whichever member's scalar was written last. The fed loop itself
        # records its spans in its own tracer; /debug/trace merges all.
        self.registry = MetricsRegistry()
        self.tracer = Tracer()
        # members are forced single-lane (drain_shards=1): the federated
        # loop drives their ingest queues and emit paths directly, and the
        # per-member `shard` telemetry label would collide with the lane
        # label a sharded member's ShardLanes register. Host-lane sharding
        # composes with federation ABOVE this class, not inside a member.
        self.engines = [
            ClusterEngine(
                client,
                dataclasses.replace(
                    cfg, initial_capacity=base_capacity, use_mesh=False,
                    drain_shards=1,
                ),
                telemetry=EngineTelemetry(
                    registry=self.registry, shard=str(i)
                ),
            )
            for i, (client, cfg) in enumerate(zip(clients, cfgs))
        ]
        # Member identity for crash-durable restarts + failover: each
        # member checkpoints to its own <dir>/member<i>.ckpt.json, and
        # its watch ("ingest pump") threads carry a -m<i> suffix so the
        # shared watchdog's budget accounting and the member-restart
        # counter can attribute a crash to its member.
        for i, e in enumerate(self.engines):
            e._ckpt_name = f"member{i}"
            e._worker_suffix = f"-m{i}"

        # Group members by compiled rule set + heartbeat cadence: the rule
        # table is baked into the jitted kernel, so each distinct set needs
        # its own kernel; identical sets share one (one dispatch per group).
        by_key: dict[tuple, list[int]] = {}
        for i, (e, cfg) in enumerate(zip(self.engines, cfgs)):
            key = (
                _table_bytes(e.nodes.table),
                _table_bytes(e.pods.table),
                # everything _Group bakes into the jitted kernel must be in
                # the key, or differing members would silently coalesce —
                # including the heartbeat SELECTOR BIT: rule sets differing
                # only in selector names compile to identical numeric
                # tables but different bit assignments
                int(e.node_bits[SEL_HEARTBEAT]),
                float(cfg.heartbeat_interval),
                float(cfg.tick_interval),
                int(getattr(cfg, "tick_substeps", 1)),
            )
            by_key.setdefault(key, []).append(i)
        self.groups: list[_Group] = []
        for members in by_key.values():
            g = _Group(
                [self.engines[i] for i in members], cfgs[members[0]], self.mesh
            )
            g.alloc(_pad_cluster_capacity(base_capacity, len(members), d))
            self.groups.append(g)
        for g in self.groups:
            for e in g.engines:
                for k in (e.nodes, e.pods):
                    if k.capacity < g.r:
                        k.grow(g.r)

        # Shared engine epoch so one `now` is correct for every member.
        self._epoch = time.time()
        for e in self.engines:
            e._epoch = self._epoch

        # per-group kernel-launch counters (labeled series), plus
        # cross-shard aggregate gauges refreshed on every /metrics render
        disp_fam = self.registry.counter(
            "kwok_group_dispatches_total",
            "Fused-kernel launches per rule-set group",
            ("group",),
        )
        for i, g in enumerate(self.groups):
            g.dispatch_counter = disp_fam.labels(group=str(i))
        self._agg_lag = self.registry.gauge(
            "kwok_fed_watch_lag_seconds_max",
            "Worst per-shard watch lag in the last drain window",
        )
        self._agg_depth = self.registry.gauge(
            "kwok_fed_ingest_queue_depth",
            "Watch events waiting to be ingested, summed across shards",
        )
        self._agg_nodes = self.registry.gauge(
            "kwok_fed_nodes_managed", "Nodes managed across all shards"
        )
        self._agg_pods = self.registry.gauge(
            "kwok_fed_pods_managed", "Pods tracked across all shards"
        )

        # Member failover (ISSUE 7): ONE shared watchdog supervises every
        # member's ingest-pump (watch) threads; a crashed worker restarts
        # in place on its own thread, counted per member.
        self._member_restarts = self.registry.counter(
            "kwok_fed_member_restarts_total",
            "Supervised federation-member ingest workers restarted in "
            "place after a crash (the member re-lists and refines its "
            "slice of the stacked state from its checkpoint)",
            ("member",),
        )
        self._watchdog = None

        self.config = config
        self._running = False
        self.ready = False  # /readyz gate; flips once members catch up
        # post-refine forced-tick budget (see _ckpt_service)
        self._ckpt_force_ticks = 0
        self._thread: threading.Thread | None = None
        # monotonic wake-up for the idle tick loop (see ClusterEngine):
        # 0 = tick immediately, None = nothing scheduled on device
        self._idle_wake: float | None = 0.0

    @property
    def cluster_capacity(self) -> int:
        """Rows per member cluster (max across groups; groups pad
        independently so their stacks shard evenly)."""
        return max(g.r for g in self.groups)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        from kwok_tpu.resilience.watchdog import Watchdog

        self._running = True
        # ONE watchdog across members, installed BEFORE they start so
        # ClusterEngine.start() adopts it instead of building its own:
        # a member watch worker killed by a chaos pill restarts in place,
        # re-lists (the fresh loop's construction), and re-fills its
        # slice of the stacked group state from its checkpoint.
        self._watchdog = Watchdog(
            budget=self.config.worker_restart_budget,
            window=self.config.worker_restart_window,
            on_exhausted=self._member_budget_exhausted,
            on_restart=self._member_worker_restarted,
        )
        for e in self.engines:
            e._watchdog = self._watchdog
            e.start(run_tick_loop=False)
        # pre-compile both ingest-scatter widths against the STACKED state
        # shapes (member engines skip their own warm-up under
        # run_tick_loop=False): the first federated ingest wave through a
        # tunneled device must not block on jit compilation mid-burst
        self._warm_scatters()
        self._warm_ticks()
        from kwok_tpu.workers import spawn_worker

        self._thread = spawn_worker(self._tick_loop, name="kwok-fed-tick")
        # ready flips on the federated loop once every member's startup
        # catch-up gate (first full re-list + checkpoint reconcile)
        # completes — the same contract as the solo engine.

    @property
    def degraded(self) -> bool:
        """Any member degraded degrades the federation's /readyz (the
        members share one process; a load balancer cannot route around
        half of it)."""
        return any(e.degraded for e in self.engines)

    @property
    def startup_resync_pending(self) -> bool:
        return self._running and any(
            e._startup_pending is not None for e in self.engines
        )

    def _member_of_worker(self, name: str) -> "int | None":
        i = name.rfind("-m")
        if i < 0:
            return None
        try:
            idx = int(name[i + 2:])
        except ValueError:
            return None
        return idx if 0 <= idx < len(self.engines) else None

    def _member_budget_exhausted(self, name: str) -> None:
        i = self._member_of_worker(name)
        if i is None:
            return
        e = self.engines[i]
        if e._degradation.set("worker_restart_budget"):
            logger.error(
                "federation member %d degraded: worker %s out of "
                "restart budget", i, name,
            )

    def _member_worker_restarted(self, name: str) -> None:
        """Watchdog callback, on the restarted worker's own thread: a
        dead member ingest pump is back — account it and re-arm the
        member's checkpoint refill so rows its re-list re-initializes
        resume their timers (the federated loop applies the refine into
        the member's slice of the stacked group state). The re-list
        itself is the restarted loop's own construction."""
        i = self._member_of_worker(name)
        if i is None:
            return
        self._member_restarts.labels(member=str(i)).inc()
        e = self.engines[i]
        if not e._running:
            return
        logger.warning(
            "federation member %d: ingest worker %s restarted; "
            "re-listing and re-filling its slice", i, name,
        )
        # the restarted loop re-lists its own kind BY CONSTRUCTION (the
        # fresh loop has no resume revision) — cutting the member's
        # healthy other-kind stream too would be pure cost, exactly like
        # the standalone kwok-watch branch in _worker_restarted_resync.
        # The checkpoint refill re-arms so rows the re-list
        # re-initializes resume their timers.
        e._rearm_restore()

    def _warm_scatters(self) -> None:
        import numpy as np

        from kwok_tpu.ops.updates import (
            BATCH,
            BATCH_LARGE,
            InitBatch,
            UpdateBatch,
            init_rows,
            update_rows,
        )

        for g in self.groups:
            for kind in ("nodes", "pods"):
                state = g.stacked[kind]
                cap = state.capacity
                for width in (BATCH, BATCH_LARGE):
                    idx = np.full(width, cap, np.int32)  # every lane padded
                    state = init_rows(state, InitBatch(
                        idx=idx,
                        active=np.zeros(width, bool),
                        phase=np.zeros(width, np.int32),
                        cond_bits=np.zeros(width, np.uint32),
                        sel_bits=np.zeros(width, np.uint32),
                        has_deletion=np.zeros(width, bool),
                    ))
                    state = update_rows(state, UpdateBatch(
                        idx=idx,
                        sel_bits=np.zeros(width, np.uint32),
                        has_deletion=np.zeros(width, bool),
                    ))
                g.stacked[kind] = state

    def _warm_ticks(self) -> None:
        """Compile every group's fused kernel + packed wire at startup
        with one all-inactive dispatch (see ClusterEngine._warm_tick:
        first-dispatch XLA compilation otherwise lands mid-load inside
        the serial tick lane). Homogeneous groups share one compile via
        the jit cache; heterogeneous rule sets each pay their own here."""
        import numpy as np

        for g in self.groups:
            (nout, pout), wire = g.fused(
                (g.stacked["nodes"], g.stacked["pods"]), 0.0
            )
            g.stacked["nodes"] = nout.state
            g.stacked["pods"] = pout.state
            np.asarray(wire)

    def stop(self) -> None:
        self._running = False
        self.ready = False
        if self._watchdog is not None:
            self._watchdog.close()  # shutdown crashes must not restart
        # join the shared tick first so it cannot submit patch jobs to
        # members whose executors are already shut down
        if self._thread is not None:
            self._thread.join(timeout=5)
        for e in self.engines:
            e.stop()
        import json as _json
        import os as _os

        trace_path = self.config.trace_dump or _os.environ.get(
            "KWOK_TPU_TRACE", ""
        )
        if trace_path:
            # members skip their own dump (run_tick_loop=False); the
            # federation writes ONE merged document
            try:
                with open(trace_path, "w") as f:
                    _json.dump(self.trace_chrome(), f)
                logger.info("federated span trace written to %s", trace_path)
            except Exception:
                logger.exception("federated span trace dump failed")

    # ------------------------------------------------------------- tick loop

    _IDLE_MAX = 60.0

    def _tick_loop(self) -> None:
        """Pipelined federated loop, mirroring ClusterEngine._tick_loop:
        every iteration drains member queues, consumes in-flight group
        wires that have landed, and dispatches the next tick of every
        group — so the device round trip overlaps drain + emit instead of
        serializing in front of them. Per-group consume order is FIFO."""
        from collections import deque

        interval = self.config.tick_interval
        depth = max(1, int(getattr(self.config, "pipeline_depth", 8)))
        pending: "deque" = deque()
        from kwok_tpu import profiling

        profiling.maybe_start()
        try:
            while self._running:
                deadline = time.monotonic() + interval
                if (
                    not pending
                    and all(e._q.empty() for e in self.engines)
                    and not any(
                        k.buffer.pending
                        for e in self.engines
                        for k in (e.nodes, e.pods)
                    )
                ):
                    # idle: sleep toward the device-reported deadline
                    # (ops/tick.next_due); events shorten the drain
                    wake = self._idle_wake
                    if wake is None:
                        deadline = time.monotonic() + self._IDLE_MAX
                    elif wake > deadline:
                        deadline = min(
                            wake, time.monotonic() + self._IDLE_MAX
                        )
                got_event = self._drain_ingest(deadline, pending)
                did_dispatch = False
                try:
                    while pending and (
                        len(pending) >= depth * max(1, len(self.groups))
                        or ClusterEngine._wire_ready(pending[0])
                    ):
                        self._consume_one(pending)
                    # dispatch only when something calls for a tick (see
                    # the solo loop's gate: an always-in-flight pipeline
                    # would otherwise never idle)
                    wake = self._idle_wake
                    if (
                        got_event
                        or any(
                            k.buffer.pending
                            for e in self.engines
                            for k in (e.nodes, e.pods)
                        )
                        or (wake is not None
                            and time.monotonic() >= wake)
                    ):
                        did_dispatch = True
                        self._tick_dispatch_all(pending)
                except Exception:
                    logger.exception("federated tick failed")
                    self._idle_wake = time.monotonic() + interval
                # crash-durable restarts: per-member reconcile +
                # checkpoint gathers against each member's slice of its
                # group's stacked state; also flips federation readiness
                # once every member caught up
                try:
                    self._ckpt_service(did_dispatch)
                except Exception:
                    logger.exception("federated checkpoint service failed")
        finally:
            # stopping: flush in-flight group wires so computed patches
            # are not dropped (stop() joins us before member teardown)
            while pending:
                try:
                    self._consume_one(pending)
                except Exception:
                    logger.exception("final federated consume failed")
            for g in self.groups:
                for c, e in enumerate(g.engines):
                    if e._ckpt is not None:
                        try:
                            e._ckpt.final(
                                self._member_snapshot(g, c, e)
                            )
                        except Exception:
                            logger.exception(
                                "final member checkpoint failed"
                            )

    def _drain_ingest(self, deadline: float, pending=None) -> bool:
        """Round-robin the members' ingest queues until the tick is due;
        returns whether any event was drained. An arriving event during an
        extended idle sleep pulls the deadline back to one normal
        interval; consecutive empty polls back off exponentially so idling
        costs ~no wakeups — capped at 5ms while group wires are in flight
        so a wire landing mid-drain is consumed promptly."""
        lag: dict[int, float] = {}
        drain: dict[int, float] = {}
        bufs: dict[int, dict] = {}
        interval = self.config.tick_interval
        idle_sleep = 0.002
        got_event = False
        try:
            while self._running:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return got_event
                drained_any = False
                for i, e in enumerate(self.engines):
                    while True:
                        try:
                            item = e._q.get_nowait()
                        except queue.Empty:
                            break
                        if item is None:
                            continue
                        drained_any = True
                        lag[i] = max(
                            lag.get(i, 0.0), time.monotonic() - item[3]
                        )
                        _t = time.perf_counter()
                        e._drain_apply(item, bufs.setdefault(i, {}))
                        drain[i] = drain.get(i, 0.0) + (
                            time.perf_counter() - _t
                        )
                if drained_any:
                    idle_sleep = 0.002
                    if not got_event:
                        got_event = True
                        deadline = min(
                            deadline, time.monotonic() + interval
                        )
                else:
                    if pending and ClusterEngine._wire_ready(pending[0]):
                        try:
                            self._consume_one(pending)
                        except Exception:
                            logger.exception("mid-drain consume failed")
                        continue
                    cap = 0.005 if pending else 0.1
                    time.sleep(min(remaining, idle_sleep))
                    idle_sleep = min(idle_sleep * 2, cap)
        finally:
            for i, e in enumerate(self.engines):
                if i in bufs and bufs[i]:
                    _t = time.perf_counter()
                    e._drain_flush(bufs[i])
                    drain[i] = drain.get(i, 0.0) + (
                        time.perf_counter() - _t
                    )
            # slowest enqueue->processing delay this tick; 0 on a quiet
            # tick. Each member writes its OWN shard-labeled children —
            # the old flat dict let whichever shard drained last overwrite
            # watch_lag_seconds/ingest_queue_depth for the whole federation
            for i, e in enumerate(self.engines):
                tel = e.telemetry
                lag_i = lag.get(i, 0.0)
                if i in lag:
                    tel.observe_watch_lag(lag_i)
                else:
                    tel.set_gauge("watch_lag_seconds", 0.0)
                tel.set_gauge("ingest_queue_depth", e._q.qsize())
                drain_i = drain.get(i, 0.0)
                if drain_i:
                    tel.observe_stage("drain", drain_i)
        return got_event

    # --------------------------------------- crash-durable restarts (ckpt)

    def _ckpt_service(self, dispatched: bool) -> None:
        """Per-member reconcile + checkpoint gathers, on the federated
        loop (the only thread that touches member pools and the stacked
        group states). Mirrors ClusterEngine._ckpt_service with each
        member refining/gathering its own [c*r, (c+1)*r) slice."""
        from kwok_tpu.ops.updates import refine_flush

        now = time.time() - self._epoch
        for g in self.groups:
            for c, e in enumerate(g.engines):
                r = e._restore
                if r is not None:
                    if r.expired() or (
                        not r.gate_ready and not r.remaining
                    ):
                        s = r.finish()
                        e._close_restore(r)
                        logger.info(
                            "member checkpoint refine closed: %d "
                            "refined, %d stale", s["refined"], s["stale"],
                        )
                    else:
                        for kind in ("nodes", "pods"):
                            if not r.kinds.get(kind):
                                continue
                            k = e.nodes if kind == "nodes" else e.pods
                            staged = (
                                k.buffer.staged_rows()
                                if k.buffer.pending else frozenset()
                            )
                            cur_fire = np.asarray(
                                g.stacked[kind].fire_at
                            )
                            idx, fire, hb, gen = r.match_kind(
                                kind, k.pool, staged, now,
                                phase_h=k.phase_h, fire=cur_fire,
                                offset=c * g.r,
                            )
                            if idx.size:
                                g.stacked[kind] = refine_flush(
                                    g.stacked[kind], idx, fire, hb, gen,
                                    offset=c * g.r,
                                )
                    # tick until the pipeline flushes every pre-refine
                    # wire — their consumes re-arm the stale fresh-arm
                    # wake (see ClusterEngine._ckpt_service)
                    self._ckpt_force_ticks = (
                        max(1, int(getattr(
                            self.config, "pipeline_depth", 8
                        ))) + 2
                    ) * max(1, len(self.groups))
                e._ckpt_gate(
                    dispatched,
                    staged=bool(
                        e.nodes.buffer.pending or e.pods.buffer.pending
                    ),
                )
                ck = e._ckpt
                if ck is not None and ck.due():
                    ck.submit(self._member_snapshot(g, c, e))
        if self._ckpt_force_ticks > 0:
            self._ckpt_force_ticks -= 1
            self._idle_wake = time.monotonic()
            for g in self.groups:
                if g.wake is not None:
                    g.wake = min(g.wake, self._idle_wake)
        if not self.ready and self._running and all(
            e._startup_pending is None for e in self.engines
        ):
            self.ready = True

    def _member_snapshot(self, g: _Group, c: int, e: ClusterEngine) -> dict:
        """Gather one member's checkpoint rows from its slice of the
        group's stacked state."""
        from kwok_tpu.ops.tick import gather_deadlines
        from kwok_tpu.resilience import checkpoint as ckpt_mod

        now = time.time() - self._epoch
        kinds: dict = {}
        for kind in ("nodes", "pods"):
            state = g.stacked.get(kind)
            if state is None:
                kinds[kind] = {}
                continue
            fire, hb, gen = gather_deadlines(state)
            k = e.nodes if kind == "nodes" else e.pods
            staged = (
                k.buffer.staged_rows() if k.buffer.pending else frozenset()
            )
            kinds[kind] = ckpt_mod.gather_rows(
                kind, k.pool, k.phase_h, fire, hb, gen, staged, now,
                offset=c * g.r,
            )
        return {"kinds": kinds}

    # ------------------------------------------------------------------ tick

    def tick_once(self) -> None:
        """One synchronous federated step: dispatch every group, then
        consume every wire — the pipelined loop calls the halves with up
        to pipeline_depth * groups wires in flight."""
        from collections import deque

        pending: "deque" = deque()
        self._tick_dispatch_all(pending)
        while pending:
            self._consume_one(pending)

    def _tick_dispatch_all(self, pending) -> None:
        """Dispatch one tick of every group, appending _FedPending records
        whose wires materialize asynchronously."""
        self._maybe_regrow()
        t0 = time.perf_counter()
        now = time.time() - self._epoch
        if now >= REBASE_AFTER:
            # shared-epoch rebase (see ClusterEngine): shift every group's
            # stacked time fields and every member's epoch together
            self._epoch += now
            for e in self.engines:
                e._epoch = self._epoch
                e._inc("epoch_rebases_total")
            for g in self.groups:
                for kind in ("nodes", "pods"):
                    g.stacked[kind] = rebase_times(g.stacked[kind], now)
            logger.info("federated epoch rebase at engine time %.1fs", now)
            now = 0.0
        any_dispatch = False
        flush_s = 0.0
        for g in self.groups:
            p = self._tick_group_dispatch(g, now)
            if p is not None:
                pending.append(p)
                any_dispatch = True
                flush_s += p.flush_s
            else:
                # empty group: clear its wake so a stale deadline cannot
                # keep the gate firing (its in-flight wires, if any, still
                # refresh the wake at consume)
                g.wake = None
        if not any_dispatch:
            wakes = [g.wake for g in self.groups if g.wake is not None]
            self._idle_wake = min(wakes) if wakes else None
        t_end = time.perf_counter()
        host_s = t_end - t0
        if any_dispatch:
            self.tracer.span("tick.dispatch", t0, t_end, "dispatch")
        for e in self.engines:
            tel = e.telemetry
            tel.inc("ticks_total")
            tel.observe_stage("flush", flush_s)
            tel.tick_hist.observe(host_s)
            tel.set_gauge("nodes_managed", len(e.nodes.pool))
            tel.set_gauge("pods_managed", len(e.pods.pool))

    def _tick_group_dispatch(self, g: _Group, now: float):
        """Flush members' staged writes into the group's stacked state and
        dispatch its fused kernel. Returns a _FedPending (wire in flight)
        or None when the group is empty."""
        from kwok_tpu.ops.tick import prefetch

        r = g.r
        t0 = time.perf_counter()
        any_rows = False
        for kind in ("nodes", "pods"):
            state = g.stacked[kind]
            for c, e in enumerate(g.engines):
                k = e.nodes if kind == "nodes" else e.pods
                if k.buffer.pending:
                    state = k.buffer.flush(state, offset=c * r)
                    any_rows = True
                elif len(k.pool):
                    any_rows = True
            g.stacked[kind] = state
        t_flush = time.perf_counter()
        if not any_rows:
            return None  # empty group: nothing on device
        # with substeps, anchor the LAST scan step at wall-now
        now_base = now - (g.fused.steps - 1) * g.fused.dt
        g.dispatch_counter.inc()
        (nout, pout), wire = g.fused(
            (g.stacked["nodes"], g.stacked["pods"]), now_base
        )
        g.stacked["nodes"] = nout.state
        g.stacked["pods"] = pout.state
        prefetch(wire)  # self-contained pack_rows wire (see ClusterEngine)
        return _FedPending(
            group=g,
            wire=wire,
            r=r,
            cap=r * len(g.engines),
            seqs=[e._release_seq for e in g.engines],
            now=now,
            mono=time.monotonic(),
            flush_s=t_flush - t0,
        )

    def _consume_one(self, pending) -> None:
        """Consume the oldest in-flight group wire: refresh fired rows'
        mirrors per member (skipping rows released since that dispatch)
        and emit patches. FIFO preserves per-object patch order."""
        p = pending.popleft()
        g = p.group
        t0 = time.perf_counter()
        counters, masks_fn, dues, rows_fn = unpack_wire(
            np.asarray(p.wire), [p.cap, p.cap], rows=True
        )
        t_wire = time.perf_counter()
        nd = float(dues.min())
        wake = (
            None if nd == float("inf")
            else p.mono + max(0.0, nd - p.now)
        )
        # Per-group wake, newest consume wins (the solo engine's overwrite
        # semantics, per group); the loop's gate reads the min across
        # groups. A plain min-merge on one shared field can only ever
        # decrease — it would pin the gate at its 0.0 start value and keep
        # an idle federation dispatching through the device forever.
        g.wake = wake
        wakes = [q.wake for q in self.groups if q.wake is not None]
        self._idle_wake = min(wakes) if wakes else None
        emit_s = 0.0
        if counters.any():
            now_str = now_rfc3339()
            masks = masks_fn()
            rows = None  # decoded lazily: heartbeat-only wires never need it
            r = p.r
            for i, kind in enumerate(("nodes", "pods")):
                if not (int(counters[i]) or int(counters[2 + i])):
                    continue
                dirty, deleted, hb = masks[i]
                for c, e in enumerate(g.engines):
                    k = e.nodes if kind == "nodes" else e.pods
                    lo, hi = c * r, (c + 1) * r
                    d_c, del_c, hb_c = (
                        dirty[lo:hi], deleted[lo:hi], hb[lo:hi]
                    )
                    # rows released since this dispatch: the mask bits
                    # describe the old occupant (see ClusterEngine)
                    seq = p.seqs[c]
                    stale = [
                        li for li, s in k.released_at.items()
                        if s > seq and li < r
                    ]
                    if stale:
                        d_c[stale] = False
                        del_c[stale] = False
                        hb_c[stale] = False
                    trans_c = int(
                        np.count_nonzero(d_c) + np.count_nonzero(del_c)
                    )
                    if trans_c:
                        e.telemetry.inc_kind(
                            "transitions_total", kind, trans_c
                        )
                        idxs = np.nonzero(d_c | del_c)[0]
                        if rows is None:
                            rows = rows_fn()
                        ph, cb = rows[i]
                        # fired rows only: freshly acquired rows keep
                        # their ingest-time mirror values
                        k.phase_h[idxs] = ph[lo:hi][idxs]
                        k.cond_h[idxs] = cb[lo:hi][idxs]
                    if trans_c or hb_c.any():
                        _t = time.perf_counter()
                        e._emit(kind, k, d_c, del_c, hb_c, now_str)
                        _t1 = time.perf_counter()
                        emit_s += _t1 - _t
                        e.telemetry.observe_stage("emit", _t1 - _t)
                        self.tracer.span(
                            "tick.emit", _t, _t1, "emit",
                            {"kind": kind, "shard": c},
                        )
        # prune each member's release log against its oldest still-in-
        # flight dispatch (members belong to exactly one group)
        next_p = next(
            (q for q in pending if q.group is g), None
        )
        for c, e in enumerate(g.engines):
            e._prune_released(
                next_p.seqs[c] if next_p is not None else e._release_seq
            )
        t_end = time.perf_counter()
        elapsed = t_end - t0
        self.tracer.span(
            "tick.consume", t0, t_end, "consume",
            {"wire_wait_us": round((t_wire - t0) * 1e6, 1)},
        )
        for e in g.engines:
            tel = e.telemetry
            tel.observe_tick(elapsed)
            tel.observe_stage("kernel", t_wire - t0)

    # ------------------------------------------------------------------ grow

    def _maybe_regrow(self) -> None:
        """If any member's pool grew (ClusterEngine._grow during ingest),
        rebuild that member's GROUP at the new common per-cluster capacity
        (other groups keep their size — heterogeneous federations don't pay
        for one member's growth)."""
        d = int(self.mesh.devices.size)
        for g in self.groups:
            want = max(k.capacity for e in g.engines for k in (e.nodes, e.pods))
            if want <= g.r:
                continue
            n = len(g.engines)
            new_r = _pad_cluster_capacity(want, n, d)
            old_r = g.r
            logger.info(
                "federation regrow (%d-member group): %d -> %d rows/cluster",
                n, old_r, new_r,
            )
            for e in g.engines:
                for k in (e.nodes, e.pods):
                    if k.capacity < new_r:
                        k.grow(new_r)
            for kind in ("nodes", "pods"):
                host = to_host(g.stacked[kind])
                stacked = new_row_state(new_r * n)
                for c in range(n):
                    for f in RowState._fields:
                        getattr(stacked, f)[
                            c * new_r : c * new_r + old_r
                        ] = getattr(host, f)[c * old_r : (c + 1) * old_r]
                g.stacked[kind] = g.fused.place(stacked)
            g.r = new_r

    # --------------------------------------------------------------- metrics

    @property
    def metrics(self) -> dict:
        """Aggregated counters across members (gauges are summed too —
        nodes/pods managed are totals across the federation). The labeled
        per-shard series live in ``self.registry``; this flat view keeps
        the legacy surface (tests, cost model) working."""
        agg: dict[str, float] = {}
        for e in self.engines:
            for name, v in e.telemetry.legacy_dict().items():
                if name == "watch_lag_seconds":
                    # worst-case lag, not a sum over members
                    agg[name] = max(agg.get(name, 0.0), v)
                else:
                    agg[name] = agg.get(name, 0) + v
        if self.engines:
            n = len(self.engines)
            # every member records the same shared-tick values; un-sum
            # them (emit/drain are per-member work and stay summed)
            for name in ("ticks_total", "tick_seconds_sum",
                         "tick_seconds_last", "epoch_rebases_total",
                         "tick_flush_seconds_sum",
                         "tick_kernel_seconds_sum"):
                agg[name] = agg[name] / n
        # per-rule-set-group kernel launches: a heterogeneous federation
        # shows one live counter per group, a homogeneous one exactly one
        for i, g in enumerate(self.groups):
            agg[f"group{i}_dispatches_total"] = g.dispatch_counter.value
        return agg

    def metrics_text(self) -> str:
        """Prometheus exposition of the shared registry: per-shard labeled
        series plus the cross-shard aggregates (refreshed here so a scrape
        always sees a consistent view)."""
        lags, depths, nn, pp = [], [], 0, 0
        for e in self.engines:
            d = e.telemetry.legacy_dict()
            lags.append(d["watch_lag_seconds"])
            depths.append(d["ingest_queue_depth"])
            nn += d["nodes_managed"]
            pp += d["pods_managed"]
        self._agg_lag.set(max(lags) if lags else 0.0)
        self._agg_depth.set(sum(depths))
        self._agg_nodes.set(nn)
        self._agg_pods.set(pp)
        return self.registry.render()

    def trace_chrome(self) -> dict:
        """Chrome trace-event doc merging the fed loop's spans with every
        member's (pump/patch/event spans land member-side)."""
        return merge_chrome_traces(
            [self.tracer] + [e.tracer for e in self.engines],
            labels=["federation"]
            + [f"shard{i}" for i in range(len(self.engines))],
        )
