"""Host controller: wires watch-ingest -> device tick -> patch-egress.

The replacement for pkg/kwok/controllers' Controller/NodeController/
PodController goroutine machinery: one ingest queue, a tick thread owning
device dispatch, and a bounded-parallelism patch executor (the analogue of
the reference's 16-way parallelTasks pools, controller.go:118-136). With
``EngineConfig.drain_shards > 1`` the host pipeline hash-partitions into
ShardLanes (engine/lanes.py): per-lane drain workers, staged buffers, emit
workers, and pump connection groups, coordinated by a tick thread that
shrinks to kernel dispatch + per-shard wire handoff.
"""

from kwok_tpu.engine.engine import ClusterEngine, EngineConfig

__all__ = [
    "ClusterEngine", "EngineConfig", "FederatedEngine", "LaneSet",
    "ShardLane",
]


def __getattr__(name):
    # lazy: federation pulls in the mesh/shard_map machinery, and the lane
    # module pulls the sharded pipeline — single-cluster single-lane
    # consumers (the synchronous test rigs) never need either
    if name == "FederatedEngine":
        from kwok_tpu.engine.federation import FederatedEngine

        return FederatedEngine
    if name in ("LaneSet", "ShardLane"):
        from kwok_tpu.engine import lanes

        return getattr(lanes, name)
    raise AttributeError(name)
