"""Host controller: wires watch-ingest -> device tick -> patch-egress.

The replacement for pkg/kwok/controllers' Controller/NodeController/
PodController goroutine machinery: one ingest queue, one tick thread owning
all state mutation (SURVEY.md section 5.2: "host ingest queue needs one
lock"), and a bounded-parallelism patch executor (the analogue of the
reference's 16-way parallelTasks pools, controller.go:118-136).
"""

from kwok_tpu.engine.engine import ClusterEngine, EngineConfig

__all__ = ["ClusterEngine", "EngineConfig", "FederatedEngine"]


def __getattr__(name):
    # lazy: federation pulls in the mesh/shard_map machinery, which
    # single-cluster consumers (the common case) never need
    if name == "FederatedEngine":
        from kwok_tpu.engine.federation import FederatedEngine

        return FederatedEngine
    raise AttributeError(name)
