"""Host-side row bookkeeping: name <-> row index, metadata, free list.

Dynamic strings never reach the device (SURVEY.md "Hard parts"): objects are
interned to row indices at ingest; freed rows are recycled like the
reference's ipPool (pkg/kwok/controllers/utils.go:52-117).
"""

from __future__ import annotations

import zlib
from typing import Any


def shard_of(key: Any, n: int) -> int:
    """Stable key -> shard index for the hash-partitioned host lanes.

    Deliberately NOT Python's ``hash()``: str hashing is salted per process
    (PYTHONHASHSEED), and the lane layout should be reproducible across
    runs so soak artifacts and trace dumps from different rounds line up.
    Keys are the row-pool keys: node name (str) or (namespace, name) for
    pods — crc32 over the joined utf-8 bytes."""
    if n <= 1:
        return 0
    if isinstance(key, tuple):
        data = "\x1f".join(str(p) for p in key).encode()
    else:
        data = str(key).encode()
    return zlib.crc32(data) % n


# RowPool.eflags bits — the native emit path's per-row classification,
# staged at upsert so emit never walks the meta dicts (ISSUE 14).
EF_RENDER = 1  # row has a renderable object (raw line or parsed dict)
EF_RGATES = 2  # spec carries readinessGates -> slow path
EF_SCALAR = 4  # server-side status is scalar-replace only (fp seeding)


class RowPool:
    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._by_key: dict[Any, int] = {}
        self._key_by_idx: list[Any] = [None] * capacity
        self.meta: list[dict | None] = [None] * capacity
        self._free: list[int] = []
        self._high = 0  # rows [0, high) have been used at least once
        # Columnar emit inputs (ISSUE 14): pre-encoded per-row byte slabs
        # the native emit splice gathers WITHOUT touching `meta` — staged
        # by the engine at upsert time (gated on its native-emit flag) and
        # cleared with the row. `path_b` holds the URL-quoted object path
        # minus any server base prefix and minus the "/status" suffix, so
        # status patches and deletes share it.
        self.path_b: list[bytes | None] = [None] * capacity
        self.host_b: list[bytes | None] = [None] * capacity
        self.ip_b: list[bytes | None] = [None] * capacity
        self.start_b: list[bytes | None] = [None] * capacity
        self.ctr_b: list[bytes | None] = [None] * capacity
        self.ictr_b: list[bytes | None] = [None] * capacity
        self.eflags: list[int] = [0] * capacity
        # server-side .status.phase as a compiled phase id (-1 unknown):
        # the emit path's no-op-merge pre-check (phase already reached)
        self.srv_phase: list[int] = [-1] * capacity

    def __len__(self) -> int:
        return len(self._by_key)

    def lookup(self, key: Any) -> int | None:
        return self._by_key.get(key)

    @property
    def full(self) -> bool:
        return not self._free and self._high >= self.capacity

    def acquire(self, key: Any) -> int:
        existing = self._by_key.get(key)
        if existing is not None:
            return existing
        if self._free:
            idx = self._free.pop()
        else:
            if self._high >= self.capacity:
                raise IndexError("row pool full; grow first")
            idx = self._high
            self._high += 1
        self._by_key[key] = idx
        self._key_by_idx[idx] = key
        self.meta[idx] = {}
        return idx

    def release(self, key: Any) -> int | None:
        idx = self._by_key.pop(key, None)
        if idx is None:
            return None
        self._key_by_idx[idx] = None
        self.meta[idx] = None
        # emit columns die with the row: a recycled index must never
        # splice the previous occupant's bytes (EF_RENDER=0 alone gates
        # the fast path; the rest is hygiene)
        self.eflags[idx] = 0
        self.srv_phase[idx] = -1
        self.path_b[idx] = None
        self.host_b[idx] = None
        self.ip_b[idx] = None
        self.start_b[idx] = None
        self.ctr_b[idx] = None
        self.ictr_b[idx] = None
        self._free.append(idx)
        return idx

    def key_of(self, idx: int) -> Any:
        return self._key_by_idx[idx]

    def grow(self, new_capacity: int) -> None:
        if new_capacity <= self.capacity:
            return
        extra = new_capacity - self.capacity
        self._key_by_idx.extend([None] * extra)
        self.meta.extend([None] * extra)
        for col in (self.path_b, self.host_b, self.ip_b, self.start_b,
                    self.ctr_b, self.ictr_b):
            col.extend([None] * extra)
        self.eflags.extend([0] * extra)
        self.srv_phase.extend([-1] * extra)
        self.capacity = new_capacity

    def keys(self):
        return self._by_key.keys()

    def items(self):
        return self._by_key.items()
