"""Minimal built-in kubectl (air-gapped fallback for the kubectl verb).

kwokctl's `kubectl` verb is a passthrough to a real kubectl binary, found
on PATH or downloaded on first use (reference: pkg/kwokctl/cmd/kubectl.go;
pkg/kwokctl/runtime/cluster.go kubectlPath download-or-find). In
zero-egress environments (this build's CI, the all-in-one image) neither
exists, so the base runtime falls back to this shim: enough of kubectl's
surface for the reference's e2e assertions (get / apply / delete /
get --raw) against any apiserver this framework speaks to.

Deliberately NOT a full kubectl: printers are table/wide/json/yaml/name,
no server-side apply, no openapi validation, no exec/attach/port-forward
(the reference snapshot's fake pods have no streaming endpoints either).
`logs` is wired and surfaces the kwok reality: the apiserver's log proxy
dials the fake node's kubelet and fails, so users get real kubectl's
`Error from server: ... connection refused` dialect. `get -w`
streams row-per-event like real kubectl (bounded by --request-timeout),
`-l` label selectors scope lists and watches server-side, `describe
nodes|pods` renders the sectioned report (conditions, capacity, system
info, containers, events), and `wait --for=condition=...|delete` covers
the polling loops the reference's e2e scripts hand-roll
(test/kwok/kwok.test.sh:40-56).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from urllib.parse import quote as _q

from kwok_tpu.edge.httpclient import HttpKubeClient
from kwok_tpu.telemetry.errors import swallowed
from kwok_tpu.edge.merge import strategic_merge
from kwok_tpu.edge.render import parse_rfc3339

# canonical kind -> (aliases, namespaced)
_KINDS: dict[str, tuple[tuple[str, ...], bool]] = {
    "nodes": (("node", "no"), False),
    "pods": (("pod", "po"), True),
    "roles": (("role",), True),
    "rolebindings": (("rolebinding",), True),
    "clusterroles": (("clusterrole",), False),
    "clusterrolebindings": (("clusterrolebinding",), False),
    "events": (("event", "ev"), True),
}
_ALIASES = {
    alias: kind
    for kind, (aliases, _) in _KINDS.items()
    for alias in (kind, *aliases)
}


def _resolve_kind(word: str) -> str:
    kind = _ALIASES.get(word.lower())
    if kind is None:
        raise SystemExit(f'error: the server doesn\'t have a resource type "{word}"')
    return kind


def _is_namespaced(kind: str) -> bool:
    return _KINDS[kind][1]


def _age(obj: dict) -> str:
    ts = (obj.get("metadata") or {}).get("creationTimestamp")
    if not ts:
        return "<unknown>"
    try:
        secs = max(0, int(time.time() - parse_rfc3339(ts)))
    except (ValueError, TypeError):
        return "<unknown>"
    for div, unit in ((86400, "d"), (3600, "h"), (60, "m")):
        if secs >= div:
            return f"{secs // div}{unit}"
    return f"{secs}s"


def _node_roles(o: dict) -> str:
    roles = sorted(
        k.split("/", 1)[1]
        for k in (o.get("metadata") or {}).get("labels") or {}
        if k.startswith("node-role.kubernetes.io/")
    )
    return ",".join(r for r in roles if r) or "<none>"


def _node_row(o: dict) -> list[str]:
    conds = {
        c.get("type"): c.get("status")
        for c in (o.get("status") or {}).get("conditions") or []
    }
    status = "Ready" if conds.get("Ready") == "True" else "NotReady"
    return [o["metadata"]["name"], status, _age(o)]


def _node_row_wide(o: dict) -> list[str]:
    st = o.get("status") or {}
    info = st.get("nodeInfo") or {}
    addrs = {
        a.get("type"): a.get("address") for a in st.get("addresses") or []
    }
    return [
        *_node_row(o),
        _node_roles(o),
        info.get("kubeletVersion") or "<unknown>",
        addrs.get("InternalIP") or "<none>",
        addrs.get("ExternalIP") or "<none>",
        info.get("osImage") or "<unknown>",
        info.get("kernelVersion") or "<unknown>",
        info.get("containerRuntimeVersion") or "<unknown>",
    ]


def _pod_row(o: dict) -> list[str]:
    st = o.get("status") or {}
    cs = st.get("containerStatuses") or []
    total = len(cs) or len((o.get("spec") or {}).get("containers") or [])
    ready = sum(1 for c in cs if c.get("ready"))
    phase = st.get("phase") or "Unknown"
    if (o.get("metadata") or {}).get("deletionTimestamp"):
        phase = "Terminating"
    return [o["metadata"]["name"], f"{ready}/{total}", phase, _age(o)]


def _pod_row_wide(o: dict) -> list[str]:
    st = o.get("status") or {}
    gates = (o.get("spec") or {}).get("readinessGates") or []
    if gates:
        conds = {
            c.get("type"): c.get("status")
            for c in st.get("conditions") or []
        }
        gates_ok = sum(
            1 for g in gates if conds.get(g.get("conditionType")) == "True"
        )
        gates_cell = f"{gates_ok}/{len(gates)}"
    else:
        gates_cell = "<none>"
    return [
        *_pod_row(o),
        st.get("podIP") or "<none>",
        (o.get("spec") or {}).get("nodeName") or "<none>",
        st.get("nominatedNodeName") or "<none>",
        gates_cell,
    ]


def _event_row(o: dict) -> list[str]:
    obj = o.get("involvedObject") or o.get("regarding") or {}
    target = f"{(obj.get('kind') or '').lower()}/{obj.get('name') or ''}".strip("/")
    # LAST SEEN means the last occurrence: lastTimestamp (core/v1),
    # series.lastObservedTime / eventTime (events.k8s.io), then creation
    last = (
        o.get("lastTimestamp")
        or (o.get("series") or {}).get("lastObservedTime")
        or o.get("eventTime")
    )
    ts_holder = {"metadata": {"creationTimestamp": last}} if last else o
    return [
        _age(ts_holder),
        o.get("type") or "Normal",
        o.get("reason") or "",
        target,
        (o.get("message") or o.get("note") or "").replace("\n", " "),
    ]


def _print_table(kind: str, objs: list[dict], *, all_namespaces: bool,
                 no_headers: bool, out=None, wide: bool = False) -> None:
    out = out if out is not None else sys.stdout
    if kind == "nodes" and wide:
        headers = ["NAME", "STATUS", "AGE", "ROLES", "VERSION",
                   "INTERNAL-IP", "EXTERNAL-IP", "OS-IMAGE",
                   "KERNEL-VERSION", "CONTAINER-RUNTIME"]
        row = _node_row_wide
    elif kind == "nodes":
        headers, row = ["NAME", "STATUS", "AGE"], _node_row
    elif kind == "pods" and wide:
        headers = ["NAME", "READY", "STATUS", "AGE", "IP", "NODE",
                   "NOMINATED NODE", "READINESS GATES"]
        row = _pod_row_wide
    elif kind == "pods":
        headers, row = ["NAME", "READY", "STATUS", "AGE"], _pod_row
    elif kind == "events":
        headers = ["LAST SEEN", "TYPE", "REASON", "OBJECT", "MESSAGE"]
        row = _event_row
    else:
        headers, row = ["NAME", "AGE"], lambda o: [o["metadata"]["name"], _age(o)]
    if all_namespaces and _is_namespaced(kind):
        headers = ["NAMESPACE", *headers]
        inner = row
        row = lambda o: [(o["metadata"].get("namespace") or ""), *inner(o)]  # noqa: E731
    rows = [row(o) for o in objs]
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    lines = [] if no_headers else [headers]
    lines += rows
    for cells in lines:
        print(
            "   ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip(),
            file=out,
        )


def _singular(kind: str) -> str:
    return _KINDS[kind][0][0]


def _load_docs(path: str) -> list[dict]:
    import yaml

    text = sys.stdin.read() if path == "-" else open(path).read()
    return [d for d in yaml.safe_load_all(text) if d]


_KIND_TO_PLURAL = {
    "Node": "nodes",
    "Pod": "pods",
    "Role": "roles",
    "RoleBinding": "rolebindings",
    "ClusterRole": "clusterroles",
    "ClusterRoleBinding": "clusterrolebindings",
    "Event": "events",
}


def _doc_target(doc: dict) -> tuple[str, str | None, str]:
    kind = _KIND_TO_PLURAL.get(doc.get("kind") or "")
    if kind is None:
        raise SystemExit(f"error: unsupported kind in document: {doc.get('kind')}")
    meta = doc.get("metadata") or {}
    ns = meta.get("namespace") or ("default" if _is_namespaced(kind) else None)
    name = meta.get("name")
    if not name:
        raise SystemExit("error: document has no metadata.name")
    return kind, ns, name


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="kubectl", add_help=True)
    p.add_argument("--kubeconfig", default=None)
    p.add_argument("-s", "--server", default=None)
    sub = p.add_subparsers(dest="verb", required=True)

    g = sub.add_parser("get")
    g.add_argument("args", nargs="*", help="KIND[,KIND...] [NAME]")
    g.add_argument("--raw", default=None, help="raw URI GET")
    g.add_argument("-n", "--namespace", default=None)
    g.add_argument("-A", "--all-namespaces", action="store_true")
    g.add_argument("-o", "--output", default="",
                   help='"", json, yaml, name, wide, or jsonpath={...}')
    g.add_argument("-l", "--selector", default=None,
                   help="label selector, e.g. a=b,c!=d")
    g.add_argument("--no-headers", action="store_true")
    g.add_argument("-w", "--watch", action="store_true",
                   help="after listing, stream a row per watch event")
    g.add_argument("--watch-only", action="store_true",
                   help="stream events without the initial list")
    g.add_argument("--request-timeout", default="0",
                   help='bound the watch, e.g. "5s" (0 = no bound)')

    ds = sub.add_parser("describe")
    ds.add_argument("args", nargs="+", help="KIND [NAME...] | KIND/NAME")
    ds.add_argument("-n", "--namespace", default=None)

    w = sub.add_parser("wait")
    w.add_argument("args", nargs="+", help="KIND/NAME | KIND NAME...")
    w.add_argument("--for", dest="for_", required=True,
                   help="condition=NAME[=VALUE] | delete")
    w.add_argument("-n", "--namespace", default=None)
    w.add_argument("--timeout", default="30s")

    a = sub.add_parser("apply")
    a.add_argument("-f", "--filename", required=True)
    c = sub.add_parser("create")
    c.add_argument("-f", "--filename", required=True)

    d = sub.add_parser("delete")
    d.add_argument("args", nargs="*", help="KIND NAME | -f FILE")
    d.add_argument("-f", "--filename", default=None)
    d.add_argument("-n", "--namespace", default=None)
    # None = omit DeleteOptions.gracePeriodSeconds (server-side default,
    # like real kubectl); 0 = force delete
    d.add_argument("--grace-period", type=int, default=None)

    lg = sub.add_parser("logs")
    lg.add_argument("pod", help="POD name")
    lg.add_argument("-n", "--namespace", default=None)
    lg.add_argument("-c", "--container", default=None)

    v = sub.add_parser("version")
    v.add_argument("--client", action="store_true")

    args = p.parse_args(argv)

    if args.verb == "version":
        print("kwok-tpu built-in kubectl (air-gapped fallback shim)")
        return 0

    client = HttpKubeClient.from_kubeconfig(args.kubeconfig, master=args.server)
    try:
        return _run(args, client)
    finally:
        client.close()


def _parse_duration(s: str) -> float:
    """kubectl-style duration via the shared Go-duration parser
    (config/stages.parse_duration: "30s", "1m30s", "300ms", "0.5s", bare
    seconds). Invalid input is a clean usage error, not a traceback
    (advisor r4)."""
    from kwok_tpu.config.stages import parse_duration

    try:
        return parse_duration(s or "0")
    except ValueError:
        raise SystemExit(f'error: invalid duration "{s}"') from None


def _jsonpath_eval(obj, path: str) -> list:
    """Evaluate a dotted jsonpath expression (the subset the reference's
    e2e scripts use: `.a.b`, `.items[*].x`, `.items.*.x`, `.items[2].x`)
    against obj, returning the matched values in document order."""
    values = [obj]
    for raw in path.strip().lstrip(".").replace("[", ".[").split("."):
        tok = raw.strip()
        if not tok:
            continue
        out = []
        for v in values:
            if tok in ("*", "[*]"):
                if isinstance(v, list):
                    out.extend(v)
                elif isinstance(v, dict):
                    out.extend(v.values())
            elif tok.startswith("[") and tok.endswith("]"):
                idx = tok[1:-1].strip()
                if idx == "*":
                    if isinstance(v, list):
                        out.extend(v)
                elif isinstance(v, list):
                    try:
                        out.append(v[int(idx)])
                    except (ValueError, IndexError):
                        pass
            elif isinstance(v, dict) and tok in v:
                out.append(v[tok])
        values = out
    return values


def _print_jsonpath(doc, template: str) -> None:
    """kubectl-style jsonpath printer for the common template shapes:
    `{.expr}` segments evaluate (lists join with spaces), `{"literal"}`
    segments emit verbatim (so `{"\\n"}` works), text outside braces
    passes through."""
    import re as _re

    out: list[str] = []
    pos = 0
    for m in _re.finditer(r"\{([^{}]*)\}", template):
        out.append(template[pos:m.start()])
        inner = m.group(1).strip()
        if len(inner) >= 2 and inner[0] == inner[-1] == '"':
            out.append(inner[1:-1].encode().decode("unicode_escape"))
        else:
            vals = _jsonpath_eval(doc, inner)
            out.append(" ".join(
                v if isinstance(v, str)
                else json.dumps(v, separators=(",", ":"))
                for v in vals
            ))
        pos = m.end()
    out.append(template[pos:])
    sys.stdout.write("".join(out))
    sys.stdout.flush()


def _no_resources_msg(kind: str, ns: str | None,
                      all_namespaces: bool = False) -> str:
    """Real kubectl's empty-result dialect: namespace-qualified (with the
    period) for namespaced kinds, bare otherwise."""
    if _is_namespaced(kind) and not all_namespaces and ns:
        return f"No resources found in {ns} namespace."
    return "No resources found"


def _emit_machine_doc(obj: dict, fmt: str,
                      explicit_start: bool = True) -> None:
    if fmt == "yaml":
        import yaml

        # explicit_start separates successive documents like real
        # kubectl's yaml stream; a single merged List omits it
        yaml.safe_dump(obj, sys.stdout, default_flow_style=False,
                       sort_keys=True, explicit_start=explicit_start)
    else:
        json.dump(obj, sys.stdout, indent=2)
        print()


def _emit_watch_row(kind, obj, args) -> None:
    if args.output in ("json", "yaml"):
        _emit_machine_doc(obj, args.output)
    elif args.output == "name":
        print(f"{_singular(kind)}/{obj['metadata']['name']}")
    else:
        # real kubectl appends one UNPADDED-consistent row per event; it
        # prints headers once (unless --no-headers/--watch-only)
        _print_table(
            kind, [obj], all_namespaces=args.all_namespaces,
            no_headers=True, wide=args.output == "wide",
        )
    sys.stdout.flush()


class _WatchFailed:
    """Error sentinel the `get -w` reader thread pushes onto the event
    queue when the watch cannot be (re-)established."""

    def __init__(self, cause: Exception) -> None:
        self.cause = cause


def _get_watch(args, client, kind, ns, name, start_rv=None) -> int:
    """`get -w`: stream a row per ADDED/MODIFIED/DELETED event until
    interrupted or --request-timeout elapses (real kubectl's bound). A
    reader thread feeds a queue so the deadline fires even on a QUIET
    stream (a blocking read would hold the process past the bound).
    `start_rv` is the initial list's resourceVersion: the watch resumes
    from it so events landing between list and watch registration are
    replayed, not dropped (real kubectl threads it the same way);
    re-watches resume from the last event seen."""
    import queue as _queue
    import threading

    bound = _parse_duration(args.request_timeout)
    deadline = time.monotonic() + bound if bound > 0 else None
    field_selector = f"metadata.name={name}" if name else None
    q: "_queue.Queue" = _queue.Queue()
    stop = threading.Event()
    rv_box = [start_rv]

    def reader():
        from kwok_tpu.edge.kubeclient import (
            TooLargeResourceVersion,
            WatchExpired,
        )

        while not stop.is_set():
            try:
                w = client.watch(kind, field_selector=field_selector,
                                 label_selector=args.selector,
                                 allow_bookmarks=False,
                                 resource_version=rv_box[0])
            except (WatchExpired, TooLargeResourceVersion):
                rv_box[0] = None  # compacted/reset: rejoin live
                continue
            except Exception as e:
                # server unreachable/dead: surface the failure instead of
                # dying silently and leaving the main loop blocked on an
                # empty queue (advisor r4; real kubectl reports watch
                # errors and exits nonzero)
                if not stop.is_set():
                    q.put(_WatchFailed(e))
                return
            handles.append(w)
            try:
                for ev in w:
                    rv = (ev.object.get("metadata") or {}).get(
                        "resourceVersion"
                    )
                    if rv:
                        rv_box[0] = rv
                    q.put(ev)
                    if stop.is_set():
                        return
                if getattr(w, "expired", False):
                    rv_box[0] = None
            except Exception:
                if stop.is_set():
                    return
            finally:
                w.stop()
            if stop.wait(0.2):  # stream ended; re-watch like real kubectl
                return

    handles: list = []
    t = threading.Thread(target=reader, daemon=True)
    t.start()
    try:
        while True:
            remaining = (
                None if deadline is None else deadline - time.monotonic()
            )
            if remaining is not None and remaining <= 0:
                return 0
            try:
                ev = q.get(timeout=remaining)
            except _queue.Empty:
                return 0
            if isinstance(ev, _WatchFailed):
                print(f"error: watch failed: {ev.cause}", file=sys.stderr)
                return 1
            obj = ev.object
            if name and (obj.get("metadata") or {}).get("name") != name:
                continue
            if (
                _is_namespaced(kind)
                and not args.all_namespaces
                and ((obj.get("metadata") or {}).get("namespace")
                     or "default") != ns
            ):
                continue
            _emit_watch_row(kind, obj, args)
    except KeyboardInterrupt:
        return 0
    finally:
        stop.set()
        for w in handles:
            try:
                w.stop()
            except Exception:
                swallowed("kubectl.watch_stop")


def _kv_block(d: dict | None) -> str:
    if not d:
        return "<none>"
    return ",".join(f"{k}={v}" for k, v in sorted(d.items()))


def _events_for(events: list[dict], kind: str, ns: str | None,
                name: str) -> list[dict]:
    """Events whose involvedObject matches, from a PRE-FETCHED list
    (client-side filter: the mock servers store events but do not index
    them; one fetch serves every described object)."""
    want_kind = {"nodes": "Node", "pods": "Pod"}.get(kind, "")
    out = []
    for ev in events:
        obj = ev.get("involvedObject") or ev.get("regarding") or {}
        if (obj.get("kind") or "") != want_kind:
            continue
        if (obj.get("name") or "") != name:
            continue
        if ns and (obj.get("namespace") or "default") != ns:
            continue
        out.append(ev)
    return out


def _events_section(events: list[dict]) -> list[str]:
    if not events:
        return ["Events:              <none>"]
    lines = ["Events:",
             "  Type     Reason     Age    From     Message",
             "  ----     ------     ----   ----     -------"]
    for ev in events:
        lines.append(
            "  {:<8} {:<10} {:<6} {:<8} {}".format(
                ev.get("type") or "Normal",
                ev.get("reason") or "",
                _age({"metadata": {"creationTimestamp":
                                   ev.get("lastTimestamp")
                                   or ev.get("eventTime")}}),
                ((ev.get("source") or {}).get("component")
                 or ev.get("reportingController") or ""),
                (ev.get("message") or ev.get("note") or "").replace(
                    "\n", " "),
            ).rstrip()
        )
    return lines


def _describe_node(events: list[dict], o: dict) -> str:
    meta = o.get("metadata") or {}
    st = o.get("status") or {}
    info = st.get("nodeInfo") or {}
    taints = (o.get("spec") or {}).get("taints") or []
    taints_cell = ",".join(
        f"{t.get('key')}:{t.get('effect')}" for t in taints
    ) or "<none>"
    lines = [
        f"Name:               {meta.get('name')}",
        f"Roles:              {_node_roles(o)}",
        f"Labels:             {_kv_block(meta.get('labels'))}",
        f"Annotations:        {_kv_block(meta.get('annotations'))}",
        f"CreationTimestamp:  {meta.get('creationTimestamp') or '<unknown>'}",
        f"Taints:             {taints_cell}",
        f"Unschedulable:      "
        f"{str(bool((o.get('spec') or {}).get('unschedulable'))).lower()}",
    ]
    conds = st.get("conditions") or []
    if conds:
        lines.append("Conditions:")
        rows = [["Type", "Status", "LastHeartbeatTime",
                 "LastTransitionTime", "Reason", "Message"],
                ["----", "------", "-----------------",
                 "------------------", "------", "-------"]]
        for c in conds:
            rows.append([
                c.get("type") or "", c.get("status") or "",
                c.get("lastHeartbeatTime") or "",
                c.get("lastTransitionTime") or "",
                c.get("reason") or "", c.get("message") or "",
            ])
        widths = [max(len(r[i]) for r in rows) for i in range(6)]
        for r in rows:
            lines.append(
                "  " + "  ".join(
                    c.ljust(w) for c, w in zip(r, widths)
                ).rstrip()
            )
    addrs = st.get("addresses") or []
    if addrs:
        lines.append("Addresses:")
        for a in addrs:
            lines.append(f"  {a.get('type')}:  {a.get('address')}")
    for section, key in (("Capacity", "capacity"),
                         ("Allocatable", "allocatable")):
        vals = st.get(key) or {}
        if vals:
            lines.append(f"{section}:")
            width = max(len(k) for k in vals) + 1
            for k in sorted(vals):
                lines.append(f"  {k + ':':<{width}}  {vals[k]}")
    if info:
        lines.append("System Info:")
        for label, key in (
            ("Machine ID", "machineID"),
            ("Kernel Version", "kernelVersion"),
            ("OS Image", "osImage"),
            ("Operating System", "operatingSystem"),
            ("Architecture", "architecture"),
            ("Container Runtime Version", "containerRuntimeVersion"),
            ("Kubelet Version", "kubeletVersion"),
        ):
            if info.get(key):
                lines.append(f"  {label + ':':<27} {info[key]}")
    lines += _events_section(
        _events_for(events, "nodes", None, meta.get("name") or "")
    )
    return "\n".join(lines)


def _describe_pod(events: list[dict], o: dict) -> str:
    meta = o.get("metadata") or {}
    spec = o.get("spec") or {}
    st = o.get("status") or {}
    ns = meta.get("namespace") or "default"
    node_cell = spec.get("nodeName") or "<none>"
    if st.get("hostIP"):
        node_cell = f"{node_cell}/{st['hostIP']}"
    phase = st.get("phase") or "Unknown"
    if meta.get("deletionTimestamp"):
        phase = "Terminating"
    lines = [
        f"Name:         {meta.get('name')}",
        f"Namespace:    {ns}",
        f"Node:         {node_cell}",
        f"Start Time:   {st.get('startTime') or '<unknown>'}",
        f"Labels:       {_kv_block(meta.get('labels'))}",
        f"Annotations:  {_kv_block(meta.get('annotations'))}",
        f"Status:       {phase}",
        f"IP:           {st.get('podIP') or '<none>'}",
    ]
    statuses = {
        c.get("name"): c for c in st.get("containerStatuses") or []
    }
    containers = spec.get("containers") or []
    if containers:
        lines.append("Containers:")
        for c in containers:
            cs = statuses.get(c.get("name")) or {}
            state = cs.get("state") or {}
            state_name = next(iter(state), "waiting").capitalize()
            lines.append(f"  {c.get('name')}:")
            lines.append(f"    Image:   {c.get('image') or '<none>'}")
            lines.append(f"    State:   {state_name}")
            started = (state.get("running") or {}).get("startedAt")
            if started:
                lines.append(f"      Started:  {started}")
            lines.append(
                f"    Ready:   {str(bool(cs.get('ready'))).capitalize()}"
            )
    conds = st.get("conditions") or []
    if conds:
        lines.append("Conditions:")
        width = max(len(c.get("type") or "") for c in conds) + 2
        lines.append(f"  {'Type':<{width}}Status")
        for c in conds:
            lines.append(
                f"  {(c.get('type') or ''):<{width}}{c.get('status') or ''}"
            )
    lines += _events_section(
        _events_for(events, "pods", ns, meta.get("name") or "")
    )
    return "\n".join(lines)


def _describe(args, client) -> int:
    """`kubectl describe nodes|pods [NAME...]` — the sectioned report the
    reference's e2e scripts grep (conditions + events), dialect-pinned by
    goldens + hack/diff-kubectl.sh."""
    targets: list[tuple[str, str | None, str | None]] = []
    if "/" in args.args[0]:
        for a in args.args:
            kindw, _, nm = a.partition("/")
            kind = _resolve_kind(kindw)
            ns = args.namespace or ("default" if _is_namespaced(kind) else None)
            targets.append((kind, ns, nm))
    else:
        kind = _resolve_kind(args.args[0])
        ns = args.namespace or ("default" if _is_namespaced(kind) else None)
        names = args.args[1:] or [None]
        targets = [(kind, ns, nm) for nm in names]
    render = {"nodes": _describe_node, "pods": _describe_pod}
    # ONE events fetch serves every described object (describe-all over
    # hundreds of pods must not re-list the events store per pod)
    try:
        all_events = client.list("events")
    except Exception as e:
        # real kubectl degrades the same way (describe without events);
        # say so instead of silently showing "<none>"
        print(f"warning: could not list events: {e}", file=sys.stderr)
        all_events = []
    blocks: list[str] = []
    rc = 0
    for kind, ns, nm in targets:
        fn = render.get(kind)
        if fn is None:
            raise SystemExit(
                f"error: describe is not supported for {kind} "
                "(nodes and pods only)"
            )
        if nm is None:
            objs = client.list(kind)
            if _is_namespaced(kind):
                objs = [
                    o for o in objs
                    if ((o.get("metadata") or {}).get("namespace")
                        or "default") == ns
                ]
        else:
            obj = client.get(kind, ns, nm)
            if obj is None:
                print(
                    f'Error from server (NotFound): {_singular(kind)} '
                    f'"{nm}" not found',
                    file=sys.stderr,
                )
                rc = 1
                continue
            objs = [obj]
        for o in objs:
            blocks.append(fn(all_events, o))
    if blocks:
        print("\n\n\n".join(blocks))
    elif rc == 0:
        kind0, ns0, _nm = targets[0]
        print(_no_resources_msg(kind0, ns0), file=sys.stderr)
    return rc


def _condition_met(obj: dict, cond: str, want: str) -> bool:
    for c in (obj.get("status") or {}).get("conditions") or []:
        if (c.get("type") or "").lower() == cond.lower():
            return (c.get("status") or "") == want
    return False


def _wait(args, client: HttpKubeClient) -> int:
    """`kubectl wait --for=condition=NAME[=VALUE] | --for=delete`: the
    polling loop the reference's e2e scripts hand-roll
    (test/kwok/kwok.test.sh:40-56 retry-until-Ready)."""
    spec = args.for_
    if spec == "delete":
        mode, cond, want = "delete", "", ""
    elif spec.startswith("condition="):
        mode = "condition"
        rest = spec[len("condition="):]
        cond, _, want = rest.partition("=")
        want = want or "True"
    else:
        raise SystemExit(
            f'error: unrecognized condition: "{spec}" (supported: '
            f"condition=NAME[=VALUE], delete)"
        )
    # targets: "kind/name" forms, or "KIND NAME [NAME...]"
    targets: list[tuple[str, str | None, str]] = []
    if "/" in args.args[0]:
        for a in args.args:
            kindw, _, nm = a.partition("/")
            kind = _resolve_kind(kindw)
            ns = args.namespace or ("default" if _is_namespaced(kind) else None)
            targets.append((kind, ns, nm))
    else:
        kind = _resolve_kind(args.args[0])
        ns = args.namespace or ("default" if _is_namespaced(kind) else None)
        targets = [(kind, ns, nm) for nm in args.args[1:]]
    if not targets:
        raise SystemExit("error: resource name is required")
    deadline = time.monotonic() + _parse_duration(args.timeout)
    pending = dict.fromkeys(range(len(targets)))
    rc = 0
    while pending:
        for i in list(pending):
            kind, ns, nm = targets[i]
            obj = client.get(kind, ns, nm)
            ok = (
                obj is None
                if mode == "delete"
                else obj is not None and _condition_met(obj, cond, want)
            )
            if ok:
                print(
                    f"{_singular(kind)}/{nm} "
                    + ("deleted" if mode == "delete" else "condition met")
                )
                del pending[i]
        if not pending:
            return rc
        if time.monotonic() >= deadline:
            for i in pending:
                kind, ns, nm = targets[i]
                print(
                    f"error: timed out waiting for the condition on "
                    f"{_singular(kind)}/{nm}",
                    file=sys.stderr,
                )
            return 1
        time.sleep(0.2)
    return rc


def _logs(args, client: HttpKubeClient) -> int:
    """`kubectl logs POD [-c C]` — on a kwok cluster the apiserver's log
    proxy dials the fake node's kubelet and fails; real kubectl surfaces
    that Status message as `Error from server: ...` and exits 1. The shim
    reproduces exactly that (and passes real logs through, should the
    server actually serve some)."""
    import urllib.error

    ns = args.namespace or "default"
    path = f"/api/v1/namespaces/{_q(ns)}/pods/{_q(args.pod)}/log"
    if args.container:
        path += f"?container={_q(args.container)}"
    try:
        with client._request("GET", client.server + path) as r:
            sys.stdout.write(r.read().decode())
        return 0
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")
        try:
            doc = json.loads(body)
        except ValueError:
            doc = None
        if not isinstance(doc, dict):
            doc = {}
        msg = doc.get("message") or body
        r = doc.get("reason")
        # real kubectl parenthesizes Status.reason whenever present; the
        # kwok dial-failure 500 carries none, yielding the bare form
        reason = f" ({r})" if r else ""
        print(f"Error from server{reason}: {msg}", file=sys.stderr)
        return 1


def _run(args, client: HttpKubeClient) -> int:
    if args.verb == "wait":
        return _wait(args, client)
    if args.verb == "describe":
        return _describe(args, client)
    if args.verb == "logs":
        return _logs(args, client)
    if args.verb == "get":
        if args.raw:
            # client._request applies the TLS context, CA, client cert and
            # bearer token from the kubeconfig (a bare urlopen would fail
            # against self-signed secure clusters)
            with client._request("GET", client.server + args.raw) as r:
                sys.stdout.write(r.read().decode())
            return 0
        if not args.args:
            raise SystemExit("error: you must specify the type of resource to get")
        kinds = [_resolve_kind(k) for k in args.args[0].split(",")]
        name = args.args[1] if len(args.args) > 1 else None
        if name and len(kinds) > 1:
            raise SystemExit("error: a resource name cannot combine with "
                             "multiple resource types")
        if name and args.selector:
            # real kubectl's exact refusal
            raise SystemExit("error: name cannot be provided when a "
                             "selector is specified")
        jsonpath = None
        if args.output.startswith("jsonpath="):
            jsonpath = args.output[len("jsonpath="):]
        elif args.output not in ("", "json", "yaml", "name", "wide"):
            raise SystemExit(
                "error: unable to match a printer suitable for the "
                f'output format "{args.output}"'
            )
        if jsonpath is not None and (args.watch or args.watch_only):
            raise SystemExit(
                "error: jsonpath output is not supported with --watch "
                "in this kubectl shim"
            )
        watching = args.watch or args.watch_only
        if watching and len(kinds) > 1:
            # real kubectl: watch is only supported on individual
            # resources and resource collections
            raise SystemExit("error: you may only specify a single "
                             "resource type when using --watch")
        per_kind: list[tuple[str, list[dict]]] = []
        start_rv = None
        if watching:
            # ONE raw list captures items + the List resourceVersion; the
            # watch then resumes from that exact revision, so events
            # landing between list and watch registration replay instead
            # of dropping (real kubectl threads the rv the same way)
            kind = kinds[0]
            ns = args.namespace or ("default" if _is_namespaced(kind) else None)
            query = (
                {"labelSelector": args.selector} if args.selector else None
            )
            doc = client._json("GET", client._url(kind, query=query)) or {}
            start_rv = (doc.get("metadata") or {}).get("resourceVersion")
            objs = doc.get("items") or []
            if name:
                objs = [
                    o for o in objs
                    if (o.get("metadata") or {}).get("name") == name
                ]
            if _is_namespaced(kind) and not args.all_namespaces:
                objs = [
                    o for o in objs
                    if (o["metadata"].get("namespace") or "default") == ns
                ]
            if name and not objs:
                # fail fast like real kubectl (and our non-watch branch)
                # instead of silently waiting for events on a name that
                # does not exist (advisor r4)
                print(
                    f'Error from server (NotFound): '
                    f'{_singular(kind)} "{name}" not found',
                    file=sys.stderr,
                )
                return 1
            per_kind = [(kind, objs)] if objs else []
        else:
            for kind in kinds:
                ns = args.namespace or (
                    "default" if _is_namespaced(kind) else None
                )
                if name:
                    obj = client.get(kind, ns, name)
                    if obj is None:
                        print(
                            f'Error from server (NotFound): '
                            f'{_singular(kind)} "{name}" not found',
                            file=sys.stderr,
                        )
                        return 1
                    objs = [obj]
                else:
                    objs = client.list(kind, label_selector=args.selector)
                    if _is_namespaced(kind) and not args.all_namespaces:
                        objs = [
                            o for o in objs
                            if (o["metadata"].get("namespace") or "default")
                            == ns
                        ]
                if objs:
                    per_kind.append((kind, objs))
        if args.watch_only:
            pass  # stream only; no initial listing
        elif jsonpath is not None:
            items = [o for _, objs in per_kind for o in objs]
            doc = items[0] if name else {
                "kind": "List", "apiVersion": "v1", "items": items
            }
            _print_jsonpath(doc, jsonpath)
        elif args.output in ("json", "yaml") and not watching:
            # one parseable document even across comma-separated kinds
            # (real kubectl merges everything into a single v1 List)
            items = [o for _, objs in per_kind for o in objs]
            doc = items[0] if name else {
                "kind": "List", "apiVersion": "v1", "items": items
            }
            _emit_machine_doc(doc, args.output, explicit_start=False)
        elif args.output in ("json", "yaml"):
            # -o json/yaml -w streams one document per object/event
            for _, objs in per_kind:
                for o in objs:
                    _emit_machine_doc(o, args.output)
        elif args.output == "name":
            for kind, objs in per_kind:
                for o in objs:
                    print(f"{_singular(kind)}/{o['metadata']['name']}")
        else:
            for kind, objs in per_kind:
                _print_table(
                    kind, objs,
                    all_namespaces=args.all_namespaces,
                    no_headers=args.no_headers,
                    wide=args.output == "wide",
                )
        if watching:
            sys.stdout.flush()
            kind = kinds[0]
            ns = args.namespace or ("default" if _is_namespaced(kind) else None)
            return _get_watch(args, client, kind, ns, name, start_rv)
        if not per_kind and jsonpath is None and args.output not in (
            "json", "yaml", "name"
        ):
            # real kubectl stays silent on empty results under machine
            # outputs (scripts capture both streams)
            ns0 = args.namespace or (
                "default" if _is_namespaced(kinds[0]) else None
            )
            print(
                _no_resources_msg(kinds[0], ns0, args.all_namespaces),
                file=sys.stderr,
            )
        return 0

    if args.verb in ("apply", "create"):
        # real kubectl processes EVERY document and aggregates the exit
        # code rather than aborting at the first failure
        rc = 0
        for doc in _load_docs(args.filename):
            kind, ns, name = _doc_target(doc)
            existing = client.get(kind, ns, name)
            if existing is None:
                client.create(kind, doc, namespace=ns)
                print(f"{_singular(kind)}/{name} created")
            elif args.verb == "create":
                print(
                    f'Error from server (AlreadyExists): {_singular(kind)} '
                    f'"{name}" already exists',
                    file=sys.stderr,
                )
                rc = 1
            else:
                # kubectl apply updates the client-owned sections; the mock
                # servers' merge-patch on metadata+spec models that (status
                # stays the kubelet's/engine's). "unchanged" means the
                # strategic-merge RESULT equals the live object (real
                # kubectl's last-applied diff): a doc whose nested maps are
                # a subset of the live ones is a merge no-op even though
                # its top-level values differ shallowly.
                # the patch must APPLY the same merge the detection
                # predicted: the servers replace top-level section keys
                # wholesale, so send each doc key's MERGED value (keeps
                # sibling keys inside nested maps; a nested null deletes
                # its key instead of storing a literal None)
                patch: dict = {}
                for section in ("metadata", "spec"):
                    sec_patch = doc.get(section)
                    if not sec_patch:
                        continue
                    cur = existing.get(section) or {}
                    merged = strategic_merge(cur, sec_patch)
                    if merged != cur:
                        patch[section] = {
                            k: (merged[k] if k in merged else None)
                            for k in sec_patch
                        }
                if patch:
                    client.patch_meta(kind, ns, name, patch)
                    print(f"{_singular(kind)}/{name} configured")
                else:
                    print(f"{_singular(kind)}/{name} unchanged")
        return rc

    if args.verb == "delete":
        targets: list[tuple[str, str | None, str]] = []
        if args.filename:
            targets = [_doc_target(d) for d in _load_docs(args.filename)]
        elif len(args.args) >= 2:
            kind = _resolve_kind(args.args[0])
            ns = args.namespace or ("default" if _is_namespaced(kind) else None)
            targets = [(kind, ns, n) for n in args.args[1:]]
        else:
            raise SystemExit("error: specify KIND NAME or -f FILE")
        rc = 0
        for kind, ns, name in targets:
            if client.get(kind, ns, name) is None:
                print(
                    f'Error from server (NotFound): {_singular(kind)} '
                    f'"{name}" not found',
                    file=sys.stderr,
                )
                rc = 1
                continue
            client.delete(kind, ns, name, grace_seconds=args.grace_period)
            print(f'{_singular(kind)} "{name}" deleted')
        return rc

    raise SystemExit(f"error: unknown verb {args.verb}")


if __name__ == "__main__":
    raise SystemExit(main())
