"""Force JAX onto the host-CPU platform with N virtual devices.

Single home for the guard used by tests/conftest.py and
__graft_entry__.dryrun_multichip: multi-chip TPU hardware is unavailable, so
sharding correctness runs on XLA's host platform with virtual devices (same
program, same collectives). The axon TPU plugin registers itself with a
priority that outranks env-level platform selection, so the env vars alone
are not enough — ``jax.config.update("jax_platforms", "cpu")`` wins over the
plugin's registration.

Import-light on purpose: importing this module pulls in nothing; jax is only
imported inside the function, and the env vars are set before that import so
they apply regardless of import order elsewhere.
"""

from __future__ import annotations

import os
import re


def force_cpu_devices(n_devices: int = 8) -> None:
    """Must run before any JAX backend initialisation (first ``jax.devices()``
    or trace). Rewrites any pre-existing device-count pin in XLA_FLAGS rather
    than silently keeping a stale value."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    pin = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", pin, flags
        )
    else:
        flags = (flags + " " + pin).strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
    if jax.device_count() < n_devices:
        raise RuntimeError(
            f"JAX backend already initialised with {jax.device_count()} "
            f"devices; force_cpu_devices({n_devices}) must run first"
        )
