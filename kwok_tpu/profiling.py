"""In-process sampling profiler for multi-threaded attribution.

Set ``KWOK_TPU_SAMPLE_PROF=<path.json>`` and the engine starts a daemon
thread that snapshots every Python thread's stack (``sys._current_frames``)
on a fixed cadence and dumps per-thread flat/cumulative hot-function counts
as JSON at engine stop.

Why not cProfile: on CPython 3.12 ``cProfile`` registers a process-wide
``sys.monitoring`` tool, so only ONE thread can be deterministically
profiled per process — useless for an engine whose CPU is spread across a
tick thread, watch threads, and a patch executor.  Sampling sees them all
at once, costs ~nothing at the default 2 ms cadence, and the counts are
directly proportional to wall time spent per frame.

Crash-proofing: ``maybe_start`` registers an ``atexit`` hook and (from the
main thread) chains onto SIGTERM, so a killed or crashed engine that never
reaches ``stop()`` still leaves its sample data on disk. The dump also
carries an ``overruns`` count — sampling intervals missed because one
snapshot took longer than the cadence — so a report whose wall-clock
coverage is thinner than ``samples * interval_s`` says so itself.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import sys
import threading
import time

ENV = "KWOK_TPU_SAMPLE_PROF"


class Sampler:
    def __init__(self, out_path: str, interval_s: float = 0.002) -> None:
        self.out_path = out_path
        self.interval_s = interval_s
        # per thread-name: leaf frame counts (self time) and
        # anywhere-on-stack counts (cumulative time)
        self.leaf: dict[str, collections.Counter] = collections.defaultdict(
            collections.Counter
        )
        self.cum: dict[str, collections.Counter] = collections.defaultdict(
            collections.Counter
        )
        self.samples = 0
        # intervals missed because a snapshot ran longer than the cadence
        # (GIL stalls, huge stacks): coverage = samples / (samples+overruns)
        self.overruns = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "Sampler":
        from kwok_tpu.workers import spawn_worker

        self._thread = spawn_worker(self._run, name="kwok-sampler")
        return self

    def _run(self) -> None:
        names = {}  # thread ident -> name (refreshed per sample)
        while not self._stop.is_set():
            t0 = time.perf_counter()
            for th in threading.enumerate():
                names[th.ident] = th.name
            me = threading.get_ident()
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue
                name = names.get(ident, str(ident))
                leaf = True
                seen = set()
                while frame is not None:
                    code = frame.f_code
                    key = (
                        f"{os.path.basename(code.co_filename)}:"
                        f"{frame.f_lineno}:{code.co_name}"
                        if leaf
                        else f"{os.path.basename(code.co_filename)}:"
                        f"{code.co_firstlineno}:{code.co_name}"
                    )
                    if leaf:
                        self.leaf[name][key] += 1
                        leaf = False
                    if key not in seen:  # recursion: count once per sample
                        seen.add(key)
                        self.cum[name][key] += 1
                    frame = frame.f_back
            self.samples += 1
            took = time.perf_counter() - t0
            if took > self.interval_s:
                self.overruns += int(took / self.interval_s)
            self._stop.wait(self.interval_s)

    def stop_and_dump(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
        out = {
            "samples": self.samples,
            "interval_s": self.interval_s,
            "overruns": self.overruns,
            "threads": {},
        }
        for name in sorted(self.leaf):
            out["threads"][name] = {
                "self": dict(self.leaf[name].most_common(40)),
                "cumulative": dict(self.cum[name].most_common(60)),
            }
        tmp = self.out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
        os.replace(tmp, self.out_path)


_sampler: Sampler | None = None
_lock = threading.Lock()
_hooks_installed = False


def _install_dump_hooks() -> None:
    """atexit always; SIGTERM only when callable from the main thread and
    only by CHAINING the existing handler (the CLI installs its own
    graceful-stop handler — both must run). Idempotent."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True
    atexit.register(maybe_dump)
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def on_term(signum, frame):
            maybe_dump()
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, on_term)
    except ValueError:
        pass  # not the main thread: atexit alone still covers clean exits


def maybe_start() -> None:
    """Idempotent: starts the process-wide sampler if ENV is set."""
    global _sampler
    path = os.environ.get(ENV, "")
    if not path:
        return
    with _lock:
        if _sampler is None:
            _sampler = Sampler(path).start()
            _install_dump_hooks()


def maybe_dump() -> None:
    global _sampler
    with _lock:
        s, _sampler = _sampler, None
    if s is not None:
        s.stop_and_dump()
