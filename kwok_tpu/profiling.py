"""In-process sampling profiler for multi-threaded attribution.

Set ``KWOK_TPU_SAMPLE_PROF=<path.json>`` and the engine starts a daemon
thread that snapshots every Python thread's stack (``sys._current_frames``)
on a fixed cadence and dumps per-thread flat/cumulative hot-function counts
as JSON at engine stop.

Why not cProfile: on CPython 3.12 ``cProfile`` registers a process-wide
``sys.monitoring`` tool, so only ONE thread can be deterministically
profiled per process — useless for an engine whose CPU is spread across a
tick thread, watch threads, and a patch executor.  Sampling sees them all
at once, costs ~nothing at the default 2 ms cadence, and the counts are
directly proportional to wall time spent per frame.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading

ENV = "KWOK_TPU_SAMPLE_PROF"


class Sampler:
    def __init__(self, out_path: str, interval_s: float = 0.002) -> None:
        self.out_path = out_path
        self.interval_s = interval_s
        # per thread-name: leaf frame counts (self time) and
        # anywhere-on-stack counts (cumulative time)
        self.leaf: dict[str, collections.Counter] = collections.defaultdict(
            collections.Counter
        )
        self.cum: dict[str, collections.Counter] = collections.defaultdict(
            collections.Counter
        )
        self.samples = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "Sampler":
        t = threading.Thread(target=self._run, name="kwok-sampler", daemon=True)
        t.start()
        self._thread = t
        return self

    def _run(self) -> None:
        names = {}  # thread ident -> name (refreshed per sample)
        while not self._stop.is_set():
            for th in threading.enumerate():
                names[th.ident] = th.name
            me = threading.get_ident()
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue
                name = names.get(ident, str(ident))
                leaf = True
                seen = set()
                while frame is not None:
                    code = frame.f_code
                    key = (
                        f"{os.path.basename(code.co_filename)}:"
                        f"{frame.f_lineno}:{code.co_name}"
                        if leaf
                        else f"{os.path.basename(code.co_filename)}:"
                        f"{code.co_firstlineno}:{code.co_name}"
                    )
                    if leaf:
                        self.leaf[name][key] += 1
                        leaf = False
                    if key not in seen:  # recursion: count once per sample
                        seen.add(key)
                        self.cum[name][key] += 1
                    frame = frame.f_back
            self.samples += 1
            self._stop.wait(self.interval_s)

    def stop_and_dump(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        out = {
            "samples": self.samples,
            "interval_s": self.interval_s,
            "threads": {},
        }
        for name in sorted(self.leaf):
            out["threads"][name] = {
                "self": dict(self.leaf[name].most_common(40)),
                "cumulative": dict(self.cum[name].most_common(60)),
            }
        tmp = self.out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
        os.replace(tmp, self.out_path)


_sampler: Sampler | None = None
_lock = threading.Lock()


def maybe_start() -> None:
    """Idempotent: starts the process-wide sampler if ENV is set."""
    global _sampler
    path = os.environ.get(ENV, "")
    if not path:
        return
    with _lock:
        if _sampler is None:
            _sampler = Sampler(path).start()


def maybe_dump() -> None:
    global _sampler
    with _lock:
        s, _sampler = _sampler, None
    if s is not None:
        s.stop_and_dump()
