"""Mesh construction + sharding specs for the row axis."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS_AXIS = "rows"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over all (or the first n) local devices.

    Cluster-state rows are independent, so a flat data axis is the right
    topology; on a multi-host pod slice the axis simply spans hosts and the
    only collective (counter psum) rides ICI.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (ROWS_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(ROWS_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, mesh: Mesh) -> int:
    d = mesh.devices.size
    return ((n + d - 1) // d) * d
