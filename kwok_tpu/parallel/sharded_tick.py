"""shard_map'd tick over a jax.sharding.Mesh.

Rows shard across the mesh's data axis; the rule table replicates (it is a
few hundred bytes). Inside the shard the body is identical to the
single-device kernel (kwok_tpu.ops.tick.tick_body); the only collective is a
psum of the transition counter so every host sees the global rate — the
replacement for the reference's per-batch elapsed logging
(node_controller.go:193-196).

Per-shard RNG: the key is folded with the shard index so delay samples are
independent across shards yet reproducible.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from kwok_tpu.models.compiler import CompiledRules
from kwok_tpu.ops.state import RowState, TickOutputs
from kwok_tpu.ops.tick import _rule_arrays, tick_body
from kwok_tpu.parallel.mesh import ROWS_AXIS, make_mesh, row_sharding


class ShardedTickKernel:
    """Tick for one resource kind, row-sharded over a device mesh.

    Capacity must be a multiple of the mesh size (use
    kwok_tpu.parallel.mesh.pad_to_multiple; inactive padding rows are free —
    they match no rules).
    """

    def __init__(
        self,
        table: CompiledRules,
        mesh=None,
        hb_interval: float = 30.0,
        hb_phases: tuple[str, ...] = (),
        hb_sel_bit: int = -1,
    ) -> None:
        self.table = table
        self.mesh = mesh if mesh is not None else make_mesh()
        self.hb_interval = float(hb_interval)
        mask = 0
        for p in hb_phases:
            mask |= 1 << table.space.phase_id(p)
        self.hb_phase_mask = mask
        self.hb_sel_bit = int(hb_sel_bit)
        self._rules = _rule_arrays(table)

        state_spec = RowState(*([P(ROWS_AXIS)] * len(RowState._fields)))
        out_spec = TickOutputs(
            state=state_spec,
            dirty=P(ROWS_AXIS),
            deleted=P(ROWS_AXIS),
            hb_fired=P(ROWS_AXIS),
            transitions=P(),
            heartbeats=P(),
        )

        def shard_fn(state: RowState, now: jnp.ndarray, key: jax.Array) -> TickOutputs:
            idx = jax.lax.axis_index(ROWS_AXIS)
            local_key = jax.random.fold_in(key, idx)
            out = tick_body(
                state, now, local_key, self._rules, self.hb_interval,
                self.hb_phase_mask, self.hb_sel_bit,
            )
            return out._replace(
                transitions=jax.lax.psum(out.transitions, ROWS_AXIS),
                heartbeats=jax.lax.psum(out.heartbeats, ROWS_AXIS),
            )

        sharded = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(state_spec, P(), P()),
            out_specs=out_spec,
        )
        self._tick = jax.jit(sharded, donate_argnums=(0,))
        self._key = jax.random.PRNGKey(0)
        self._step = 0

    def place(self, state: RowState) -> RowState:
        """Device-put a host state with row sharding."""
        sh = row_sharding(self.mesh)
        return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), state)

    def __call__(self, state: RowState, now: float) -> TickOutputs:
        self._step += 1
        key = jax.random.fold_in(self._key, self._step)
        return self._tick(state, jnp.float32(now), key)
