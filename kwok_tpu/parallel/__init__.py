"""Device-mesh parallelism for the tick engine.

The scale axis of this domain is OBJECT COUNT (SURVEY.md section 5.7): the
honest analogue of data parallelism is sharding the row axis of the cluster
state across TPU cores. There is no TP/PP/EP analogue — rows are independent
except for the host-resolved pod->node managed-set lookup, which is encoded
into per-row selector bits at ingest, so the sharded tick needs no
cross-device gathers; only the transition counters are psum'd over ICI.
"""

from kwok_tpu.parallel.mesh import make_mesh, row_sharding
from kwok_tpu.parallel.sharded_tick import ShardedTickKernel

__all__ = ["make_mesh", "row_sharding", "ShardedTickKernel"]
