"""Versioned config types + multi-doc YAML load/save + env overrides.

Mirrors pkg/apis/v1alpha1/kwok_configuration_types.go:30-81 and the loader in
pkg/config/config.go (Load: multi-doc YAML -> TypeMeta dispatch :67-84; Save
writes ---separated docs :138-192). Field names keep the reference's JSON
wire names so existing kwok.yaml files load unchanged.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Iterable

import yaml

GROUP_VERSION = "kwok.x-k8s.io/v1alpha1"
ENV_PREFIX = "KWOK_"


@dataclasses.dataclass
class KwokConfigurationOptions:
    """The kwok engine's options (kwok_configuration_types.go:30-81).
    Wire names in comments; defaults from the +default markers."""

    cidr: str = "10.0.0.1/24"
    nodeIP: str = "196.168.0.1"
    manageAllNodes: bool = False
    manageNodesWithAnnotationSelector: str = ""
    manageNodesWithLabelSelector: str = ""
    disregardStatusWithAnnotationSelector: str = ""
    disregardStatusWithLabelSelector: str = ""
    serverAddress: str = ""
    enableCNI: bool = False
    # TPU-native extensions (not in the reference):
    tickInterval: float = 0.05
    tickSubsteps: int = 1
    heartbeatInterval: float = 30.0
    parallelism: int = 16
    initialCapacity: int = 4096
    useMesh: bool = False
    # Host-lane sharding of the drain+emit pipeline: number of
    # hash-partitioned ShardLanes. 0 = auto (auto_drain_shards: cpu_count
    # capped by maxDrainShards); 1 = the classic single-lane engine.
    drainShards: int = 0
    # Cap on the AUTO lane count (0 = DEFAULT_MAX_DRAIN_SHARDS). With the
    # router's per-event Python term gone (native pre-partitioned
    # routing) lanes keep paying past 8 cores; this bounds fan-out on
    # very wide hosts without touching explicit drainShards values.
    maxDrainShards: int = 0
    # Process lanes (engine/proclanes.py): run each ShardLane as a
    # spawned worker PROCESS over shared-memory arenas instead of a
    # thread — the GIL escape. Default off: the threaded path is
    # byte-unchanged and no shm/process exists. Env: KWOK_LANE_PROCS
    # (the generic apply_env_overrides pass). Requires an HTTP master;
    # refused with useMesh, haRole, and federation.
    laneProcs: bool = False
    # Resilience (kwok_tpu/resilience/, docs/resilience.md):
    # deterministic fault-injection spec ("" = off; KWOK_TPU_FAULTS is
    # the engine-level fallback), lane-queue shed threshold (0 = never
    # shed), and the lane-worker restart budget per window.
    faults: str = ""
    shedQueueDepth: int = 0
    workerRestartBudget: int = 5
    workerRestartWindow: float = 30.0
    # Crash-durable restarts (resilience/checkpoint.py): directory for
    # the periodic atomic-rename checkpoint of device-resident timer
    # state ("" = disabled — no thread, no gathers; KWOK_TPU_CHECKPOINT_DIR
    # is the engine-level fallback), its cadence in seconds, and the
    # SIGTERM graceful-drain bound (flush in-flight emits + write a final
    # checkpoint within this many seconds, else force-exit nonzero; a
    # second SIGTERM force-exits immediately).
    checkpointDir: str = ""
    checkpointInterval: float = 2.0
    drainDeadline: float = 30.0
    # Anti-entropy auditor (resilience/antientropy.py): cadence in
    # seconds of the background apiserver-vs-rows drift pass (budgeted
    # LIST pages; detects + repairs silent divergence). 0 = off (the
    # default; KWOK_TPU_AUDIT_INTERVAL is the engine-level fallback).
    auditInterval: float = 0.0
    # Warm-standby HA (resilience/ha.py, docs/resilience.md): "" = off
    # (no elector, no fence — the zero-cost default). "primary" serves
    # while renewing the coordination.k8s.io Lease; "standby" observes
    # warm and takes over on lease expiry. Identity defaults to
    # hostname-pid; it doubles as the checkpoint file name so the
    # standby can tail the holder's stream. Env: KWOK_HA_ROLE,
    # KWOK_HA_IDENTITY, KWOK_LEASE_NAME, KWOK_LEASE_NAMESPACE,
    # KWOK_LEASE_DURATION, KWOK_LEASE_RENEW_INTERVAL (the generic
    # apply_env_overrides pass).
    haRole: str = ""
    haIdentity: str = ""
    leaseName: str = "kwok-tpu-engine"
    leaseNamespace: str = "kube-system"
    leaseDuration: float = 2.0
    leaseRenewInterval: float = 0.0


@dataclasses.dataclass
class KwokConfiguration:
    options: KwokConfigurationOptions = dataclasses.field(
        default_factory=KwokConfigurationOptions
    )

    KIND = "KwokConfiguration"

    def to_doc(self) -> dict:
        return {
            "apiVersion": GROUP_VERSION,
            "kind": self.KIND,
            "options": _prune(dataclasses.asdict(self.options)),
        }


def _prune(d: dict) -> dict:
    return {k: v for k, v in d.items() if v not in ("", None)}


# The auto lane-count ceiling. Historically 8: with the router hashing and
# dispatching every event in Python, lanes beyond ~8 bought nothing (the
# serial router was the wall — COSTMODEL_r06). Native pre-partitioned
# routing removed that term, so auto now follows the core count up to this
# cap (benchmarks/cost_model.py re-fit; override per deployment with
# --max-drain-shards / maxDrainShards / KWOK_MAX_DRAIN_SHARDS — the env
# form reaches the CLI through the generic apply_env_overrides pass over
# KwokConfigurationOptions, not through this module).
DEFAULT_MAX_DRAIN_SHARDS = 32


def auto_drain_shards(cores: int, max_shards: int = 0) -> int:
    """THE auto drain-shard policy — the single source the engine, the
    CLI, and the cost model all share (a drifted copy here once meant the
    model predicted a lane count the engine would never run)."""
    cap = max_shards if max_shards > 0 else DEFAULT_MAX_DRAIN_SHARDS
    return max(1, min(cap, int(cores)))


def resolve_drain_shards(value: int, max_shards: int = 0) -> int:
    """0/negative = auto: auto_drain_shards over this host's cpu_count."""
    v = int(value)
    if v > 0:
        return v
    return auto_drain_shards(os.cpu_count() or 1, max_shards)


def parse_bool(value: Any) -> bool:
    """The one truthy-string parser shared by every flag/env surface."""
    if value is None or isinstance(value, bool):
        return bool(value)
    return str(value).lower() in ("1", "true", "yes", "on")


def _coerce(value: str, target: Any) -> Any:
    if isinstance(target, bool):
        return parse_bool(value)
    if isinstance(target, int) and not isinstance(target, bool):
        return int(value)
    if isinstance(target, float):
        return float(value)
    return value


def apply_env_overrides(options: Any, environ=os.environ, prefix: str = ENV_PREFIX):
    """KWOK_<UPPER_SNAKE(field)> env vars override file values
    (vars.go GetEnvWithPrefix pattern)."""
    for f in dataclasses.fields(options):
        env_name = prefix + _upper_snake(f.name)
        if env_name in environ:
            setattr(
                options, f.name, _coerce(environ[env_name], getattr(options, f.name))
            )
    return options


def _upper_snake(camel: str) -> str:
    out = []
    for i, ch in enumerate(camel):
        if ch.isupper() and i > 0 and not camel[i - 1].isupper():
            out.append("_")
        out.append(ch.upper())
    return "".join(out)


def _options_from_doc(doc: dict) -> KwokConfigurationOptions:
    opts = KwokConfigurationOptions()
    for k, v in (doc.get("options") or {}).items():
        if hasattr(opts, k):
            setattr(opts, k, v)
    return opts


def load_documents(path: str) -> list[Any]:
    """Load a multi-doc YAML config file into typed objects.

    Unknown kinds are returned as raw dicts; docs without a GVK are treated
    as legacy KwokConfiguration options (compatibility.go:85)."""
    from kwok_tpu.config.ctl import KwokctlConfiguration
    from kwok_tpu.config.stages import Stage

    out: list[Any] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for doc in yaml.safe_load_all(f):
            if not doc:
                continue
            kind = doc.get("kind")
            if kind == KwokConfiguration.KIND:
                out.append(KwokConfiguration(options=_options_from_doc(doc)))
            elif kind == KwokctlConfiguration.KIND:
                out.append(KwokctlConfiguration.from_doc(doc))
            elif kind == Stage.KIND:
                out.append(Stage.from_doc(doc))
            elif kind is None and "apiVersion" not in doc:
                # legacy untyped options blob
                out.append(
                    KwokConfiguration(options=_options_from_doc({"options": doc}))
                )
            else:
                out.append(doc)
    return out


def save_documents(path: str, docs: Iterable[Any]) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    rendered = []
    for d in docs:
        doc = d.to_doc() if hasattr(d, "to_doc") else d
        rendered.append(yaml.safe_dump(doc, sort_keys=False))
    with open(path, "w") as f:
        f.write("---\n".join(rendered))


def first_of(docs: list[Any], cls) -> Any | None:
    for d in docs:
        if isinstance(d, cls):
            return d
    return None
