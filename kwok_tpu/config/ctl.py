"""KwokctlConfiguration: the orchestrator's config type.

Mirrors pkg/apis/v1alpha1/kwokctl_configuration_types.go:34-363 (options,
Component/Port/Env/Volume) with the same JSON wire names, so saved cluster
kwok.yaml files stay compatible with the reference's format. Defaulting logic
lives in kwok_tpu.kwokctl.vars (the analogue of pkg/config/vars.go).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from kwok_tpu.config.types import GROUP_VERSION, _prune


@dataclasses.dataclass
class Port:
    port: int = 0
    hostPort: int = 0
    name: str = ""
    protocol: str = "TCP"


@dataclasses.dataclass
class Env:
    name: str = ""
    value: str = ""


@dataclasses.dataclass
class Volume:
    name: str = ""
    readOnly: bool = False
    hostPath: str = ""
    mountPath: str = ""


@dataclasses.dataclass
class Component:
    """Declarative process/container spec (kwokctl_configuration_types.go:263).

    Links encode the start-order dependency graph consumed by
    kwok_tpu.kwokctl.components.group_by_links.
    """

    name: str = ""
    links: list[str] = dataclasses.field(default_factory=list)
    binary: str = ""
    image: str = ""
    command: list[str] = dataclasses.field(default_factory=list)
    args: list[str] = dataclasses.field(default_factory=list)
    workDir: str = ""
    ports: list[Port] = dataclasses.field(default_factory=list)
    envs: list[Env] = dataclasses.field(default_factory=list)
    volumes: list[Volume] = dataclasses.field(default_factory=list)
    version: str = ""

    def to_doc(self) -> dict:
        d = dataclasses.asdict(self)
        d["ports"] = [_prune(p) for p in d["ports"]]
        d["envs"] = [_prune(e) for e in d["envs"]]
        d["volumes"] = [_prune(v) for v in d["volumes"]]
        return {k: v for k, v in d.items() if v not in ("", None, [], {})}

    @classmethod
    def from_doc(cls, doc: dict) -> "Component":
        c = cls()
        for k, v in doc.items():
            if k == "ports":
                c.ports = [_sub(Port, p) for p in v or []]
            elif k == "envs":
                c.envs = [_sub(Env, e) for e in v or []]
            elif k == "volumes":
                c.volumes = [_sub(Volume, x) for x in v or []]
            elif hasattr(c, k):
                setattr(c, k, v)
        return c


def _sub(cls, doc: dict):
    obj = cls()
    for k, v in (doc or {}).items():
        if hasattr(obj, k):
            setattr(obj, k, v)
    return obj


@dataclasses.dataclass
class KwokctlConfigurationOptions:
    """kwokctl_configuration_types.go:35-261 — wire names preserved."""

    runtime: str = ""
    mode: str = ""
    kubeApiserverPort: int = 0
    prometheusPort: int = 0
    kwokVersion: str = ""
    kubeVersion: str = ""
    etcdVersion: str = ""
    prometheusVersion: str = ""
    securePort: bool | None = None
    quietPull: bool = False
    disableKubeScheduler: bool = False
    disableKubeControllerManager: bool = False
    kubeFeatureGates: str = ""
    kubeRuntimeConfig: str = ""
    kubeAuditPolicy: str = ""
    kubeAuthorization: bool = False
    binSuffix: str = ""
    kubeBinaryPrefix: str = ""
    kubeApiserverBinary: str = ""
    kubeControllerManagerBinary: str = ""
    kubeSchedulerBinary: str = ""
    kubectlBinary: str = ""
    etcdBinaryPrefix: str = ""
    etcdBinary: str = ""
    etcdBinaryTar: str = ""
    kwokBinaryPrefix: str = ""
    kwokControllerBinary: str = ""
    prometheusBinaryPrefix: str = ""
    prometheusBinary: str = ""
    prometheusBinaryTar: str = ""
    etcdPeerPort: int = 0
    etcdPort: int = 0
    kubeControllerManagerPort: int = 0
    kubeSchedulerPort: int = 0
    kwokControllerPort: int = 0
    cacheDir: str = ""
    # image-mode options (compose/kind runtimes; types.go image fields)
    kubeImagePrefix: str = ""
    etcdImagePrefix: str = ""
    kwokImagePrefix: str = ""
    prometheusImagePrefix: str = ""
    kindNodeImagePrefix: str = ""
    etcdImage: str = ""
    kubeApiserverImage: str = ""
    kubeControllerManagerImage: str = ""
    kubeSchedulerImage: str = ""
    kwokControllerImage: str = ""
    prometheusImage: str = ""
    kindNodeImage: str = ""
    dockerComposeVersion: str = ""
    dockerComposeBinaryPrefix: str = ""
    dockerComposeBinary: str = ""
    kindVersion: str = ""
    kindBinaryPrefix: str = ""
    kindBinary: str = ""
    # TPU-native engine knobs passed through to the kwok component
    # (not in the reference):
    tickInterval: float = 0.05
    useMesh: bool = False
    # apiserver bind address; 0.0.0.0 makes a containerized cluster
    # reachable through published ports (images/cluster)
    bindAddress: str = "127.0.0.1"


@dataclasses.dataclass
class KwokctlConfiguration:
    options: KwokctlConfigurationOptions = dataclasses.field(
        default_factory=KwokctlConfigurationOptions
    )
    components: list[Component] = dataclasses.field(default_factory=list)
    name: str = ""

    KIND = "KwokctlConfiguration"

    def to_doc(self) -> dict:
        doc: dict[str, Any] = {
            "apiVersion": GROUP_VERSION,
            "kind": self.KIND,
        }
        if self.name:
            doc["metadata"] = {"name": self.name}
        doc["options"] = _prune(dataclasses.asdict(self.options))
        if self.components:
            doc["components"] = [c.to_doc() for c in self.components]
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "KwokctlConfiguration":
        opts = KwokctlConfigurationOptions()
        for k, v in (doc.get("options") or {}).items():
            if hasattr(opts, k):
                setattr(opts, k, v)
        comps = [Component.from_doc(c) for c in doc.get("components") or []]
        name = ((doc.get("metadata") or {}).get("name")) or ""
        return cls(options=opts, components=comps, name=name)
