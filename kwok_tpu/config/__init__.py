"""Config system: YAML config file + KWOK_* env + CLI flags, three-layer
precedence (file < env < flags), mirroring pkg/config
(config.go:67-84, vars.go:100-445, flags.go:34-63).

Wire format: multi-doc YAML with apiVersion kwok.x-k8s.io/v1alpha1 and kinds
KwokConfiguration / KwokctlConfiguration / Stage; documents without a GVK are
treated as a legacy KwokConfiguration options blob (compatibility.go:85).
"""

from kwok_tpu.config.types import (
    GROUP_VERSION,
    KwokConfiguration,
    KwokConfigurationOptions,
    first_of,
    load_documents,
    save_documents,
)
from kwok_tpu.config.ctl import (
    Component,
    Env,
    KwokctlConfiguration,
    KwokctlConfigurationOptions,
    Port,
    Volume,
)
from kwok_tpu.config.stages import Stage, stages_to_rules

__all__ = [
    "GROUP_VERSION",
    "Component",
    "Env",
    "KwokConfiguration",
    "KwokConfigurationOptions",
    "KwokctlConfiguration",
    "KwokctlConfigurationOptions",
    "Port",
    "Stage",
    "Volume",
    "first_of",
    "stages_to_rules",
    "load_documents",
    "save_documents",
]
