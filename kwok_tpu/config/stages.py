"""Stage: the YAML lifecycle-rule API (selector + delay + next).

This snapshot of the reference predates the Stage CRD (SURVEY.md "Snapshot
vintage"); its lifecycle is three hard-coded templates. Per the survey's
guidance, the framework's native rule API is designed as the generalization
those templates are a degenerate case of, with a Stage-shaped YAML surface:

    apiVersion: kwok.x-k8s.io/v1alpha1
    kind: Stage
    metadata: {name: pod-complete}
    spec:
      resourceRef: {apiGroup: v1, kind: Pod}
      selector:
        matchPhases: [Running]          # phase names (our state machine)
        matchDeletion: absent           # absent | present | any
        matchSelector: managed          # host-computed selector bit name
      delay:
        duration: 5s                    # constant; or
        exponential: {mean: 30s, cap: 5m}
        uniform: {min: 1s, max: 10s}
      next:
        phase: Succeeded
        conditions: {Ready: false, ContainersReady: false}
        delete: false
      weight: 3   # optional; absent/0 = deterministic first-match, > 0 =
                  # weighted-random among matching weighted stages
                  # (LifecycleRule.weight has the full semantics)

Stages for a resource REPLACE the default rule set for that resource.
"""

from __future__ import annotations

import dataclasses
import re

from kwok_tpu.models.defaults import (
    SEL_HEARTBEAT,
    SEL_MANAGED,
    SEL_ON_MANAGED_NODE,
)
from kwok_tpu.models.lifecycle import (
    DELETION_ABSENT,
    DELETION_ANY,
    DELETION_PRESENT,
    Delay,
    LifecycleRule,
    ResourceKind,
    StatusEffect,
)

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h)")
_UNIT = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}
_DELETION = {
    "absent": DELETION_ABSENT,
    "present": DELETION_PRESENT,
    "any": DELETION_ANY,
}
_KIND_TO_RESOURCE = {"Pod": ResourceKind.POD, "Node": ResourceKind.NODE}
# Selector bits the engine actually sets at ingest, per resource kind
# (kwok_tpu/engine/engine.py:156-157); anything else would compile to a
# bit that never fires, so reject it at load time.
_KNOWN_SELECTORS = {
    ResourceKind.NODE: frozenset({SEL_MANAGED, SEL_HEARTBEAT}),
    ResourceKind.POD: frozenset({SEL_MANAGED, SEL_ON_MANAGED_NODE}),
}


def parse_duration(s) -> float:
    """'5s', '300ms', '1m30s', '0.5s', bare numbers = seconds."""
    if isinstance(s, (int, float)):
        return float(s)
    s = str(s).strip()
    if not s:
        return 0.0
    total, pos = 0.0, 0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"bad duration {s!r}")
        total += float(m.group(1)) * _UNIT[m.group(2)]
        pos = m.end()
    if pos != len(s):
        # bare number => seconds
        return float(s)
    return total


def _parse_delay(spec: dict | None) -> Delay:
    if not spec:
        return Delay.constant(0.0)
    if "exponential" in spec:
        e = spec["exponential"] or {}
        return Delay.exponential(
            parse_duration(e.get("mean", 0)), parse_duration(e.get("cap", 0))
        )
    if "uniform" in spec:
        u = spec["uniform"] or {}
        return Delay.uniform(
            parse_duration(u.get("min", 0)), parse_duration(u.get("max", 0))
        )
    return Delay.constant(parse_duration(spec.get("duration", 0)))


@dataclasses.dataclass
class Stage:
    name: str
    resource: ResourceKind
    from_phases: tuple[str, ...]
    deletion: int
    selector: str | None
    delay: Delay
    to_phase: str
    conditions: dict[str, bool]
    delete: bool
    # spec.weight: absent/0 = deterministic first-match ordering; > 0 opts
    # the stage into weighted-random selection among matching weighted
    # stages (see LifecycleRule.weight for the full semantics).
    weight: int = 0

    KIND = "Stage"

    @classmethod
    def from_doc(cls, doc: dict) -> "Stage":
        meta = doc.get("metadata") or {}
        spec = doc.get("spec") or {}
        ref = spec.get("resourceRef") or {}
        kind = ref.get("kind") or "Pod"
        if kind not in _KIND_TO_RESOURCE:
            raise ValueError(f"Stage resourceRef.kind {kind!r} not supported")
        sel = spec.get("selector") or {}
        nxt = spec.get("next") or {}
        delete = bool(nxt.get("delete", False))
        to_phase = nxt.get("phase") or ""
        if not to_phase:
            if delete:
                to_phase = "Gone"  # terminal phase for pure-delete stages
            else:
                raise ValueError(
                    f"Stage {meta.get('name')!r}: spec.next.phase is required "
                    "unless next.delete is true"
                )
        name = meta.get("name") or "stage"
        resource = _KIND_TO_RESOURCE[kind]
        # matchSelector: absent -> managed-only (safe default); explicit
        # null -> match every row
        selector = sel["matchSelector"] if "matchSelector" in sel else SEL_MANAGED
        known = _KNOWN_SELECTORS[resource]
        if selector is not None and selector not in known:
            raise ValueError(
                f"Stage {name!r}: unknown matchSelector {selector!r} for "
                f"{kind}; valid values: {sorted(known)} or null"
            )
        deletion_name = sel.get("matchDeletion", "absent")
        if deletion_name not in _DELETION:
            raise ValueError(
                f"Stage {name!r}: bad matchDeletion {deletion_name!r}; "
                f"valid values: {sorted(_DELETION)}"
            )
        weight = int(spec.get("weight", 0))
        if weight < 0:
            raise ValueError(f"Stage {name!r}: spec.weight must be >= 0")
        return cls(
            name=name,
            resource=resource,
            from_phases=tuple(sel.get("matchPhases") or ()),
            deletion=_DELETION[deletion_name],
            selector=selector,
            delay=_parse_delay(spec.get("delay")),
            to_phase=to_phase,
            conditions=dict(nxt.get("conditions") or {}),
            delete=delete,
            weight=weight,
        )

    def to_rule(self) -> LifecycleRule:
        return LifecycleRule(
            name=self.name,
            resource=self.resource,
            from_phases=self.from_phases,
            deletion=self.deletion,
            selector=self.selector or None,
            delay=self.delay,
            effect=StatusEffect(
                to_phase=self.to_phase,
                conditions=self.conditions,
                delete=self.delete,
            ),
            weight=self.weight,
        )

    def to_doc(self) -> dict:
        from kwok_tpu.config.types import GROUP_VERSION

        deletion_name = {v: k for k, v in _DELETION.items()}[self.deletion]
        # bare numbers = seconds; avoids float-repr strings parse_duration
        # can't re-read
        delay: dict = {}
        if self.delay.kind == 0:
            delay = {"duration": float(self.delay.a)}
        elif self.delay.kind == 1:
            delay = {"uniform": {"min": float(self.delay.a), "max": float(self.delay.b)}}
        else:
            delay = {
                "exponential": {"mean": float(self.delay.a), "cap": float(self.delay.b)}
            }
        return {
            "apiVersion": GROUP_VERSION,
            "kind": self.KIND,
            "metadata": {"name": self.name},
            "spec": {
                "resourceRef": {
                    "apiGroup": "v1",
                    "kind": "Pod" if self.resource == ResourceKind.POD else "Node",
                },
                "selector": {
                    "matchPhases": list(self.from_phases),
                    "matchDeletion": deletion_name,
                    "matchSelector": self.selector,  # null = match every row
                },
                "delay": delay,
                "next": {
                    "phase": self.to_phase,
                    "conditions": dict(self.conditions),
                    "delete": self.delete,
                },
                "weight": self.weight,
            },
        }


def stages_to_rules(
    stages: list[Stage], resource: ResourceKind
) -> list[LifecycleRule] | None:
    """Stages for `resource` -> rule list; None if no stages target it
    (caller falls back to the built-in default rule set)."""
    mine = [s for s in stages if s.resource == resource]
    if not mine:
        return None
    return [s.to_rule() for s in mine]
